"""stallguard unit battery: each deadline-discipline rule must fire on
its positive shape, stay quiet on the bounded/propagated/clamped shapes,
honor per-line suppressions, and the dynamic stall witness must catch
(and excuse) real parks correctly.

Pattern mirrors tests/test_leakguard.py: check_source with a root-less
config analyzes each snippet standalone through the real rule registry,
so suppression/baseline behavior is exactly the shipped one. Request-path
classification in these fixtures comes from the built-in HTTP-handler
heuristic (a BaseHTTPRequestHandler subclass) — the shipped pyproject
additionally seeds broker/scheduler/hub roots via
stallguard-request-roots, which test_request_roots_config covers.
"""
import sys
import threading
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.druidlint.core import LintConfig, check_source  # noqa: E402


def cfg(*rules) -> LintConfig:
    c = LintConfig(rules=list(rules) if rules else [])
    c.root = "/nonexistent-stallguard-root"
    return c


def findings_of(source: str, rule: str, path: str = "druid_tpu/mod.py",
                config: LintConfig = None):
    return [f for f in check_source(source, path, config or cfg(rule))
            if f.rule == rule]


# ---------------------------------------------------------------------------
# unbounded-blocking-call
# ---------------------------------------------------------------------------

def test_handler_park_without_timeout_fires():
    src = """\
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        self.server.ready.wait()
"""
    got = findings_of(src, "unbounded-blocking-call")
    assert len(got) == 1
    assert "no timeout" in got[0].message


def test_handler_park_reached_through_helper_fires():
    # the rule is whole-program: the park sits two call edges below the
    # handler and must still be attributed to the request path
    src = """\
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        self._serve()

    def _serve(self):
        self._gather()

    def _gather(self):
        self.server.done_q.get()
"""
    got = findings_of(src, "unbounded-blocking-call")
    assert len(got) == 1
    assert "HTTP handler" in got[0].message


def test_handler_park_with_timeout_is_quiet():
    src = """\
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        self.server.ready.wait(5.0)
        self.server.done_q.get(True, 2.0)
        self.server.worker.join(timeout=1.0)
"""
    assert findings_of(src, "unbounded-blocking-call") == []


def test_park_off_request_path_is_quiet():
    # same park, no handler anywhere: duty-thread code answers to
    # stop-signal-coverage, not to the request-path rule
    src = """\
class Pump:
    def drain(self):
        self.ready.wait()
"""
    assert findings_of(src, "unbounded-blocking-call") == []


def test_str_join_is_not_a_park():
    src = """\
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        body = ", ".join(self.parts)
        sep = "-"
        key = sep.join(body)
        self.wfile.write(key.encode())
"""
    assert findings_of(src, "unbounded-blocking-call") == []


def test_request_roots_config():
    # no handler class: the entry point runs on a request thread only
    # because config says so, and the park it reaches must then fire
    src = """\
class Hub:
    def poll(self):
        self._cond.wait()
"""
    c = cfg("unbounded-blocking-call")
    c.stallguard_request_roots = ["druid_tpu/*::Hub.poll"]
    assert len(findings_of(src, "unbounded-blocking-call",
                           config=c)) == 1
    assert findings_of(src, "unbounded-blocking-call") == []


def test_unbounded_park_suppression():
    src = """\
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        self.server.ready.wait()  # druidlint: disable=unbounded-blocking-call
"""
    assert findings_of(src, "unbounded-blocking-call") == []


# ---------------------------------------------------------------------------
# deadline-not-propagated
# ---------------------------------------------------------------------------

def test_budget_param_ignored_by_park_fires():
    src = """\
def fetch(ev, timeout):
    ev.wait()
"""
    got = findings_of(src, "deadline-not-propagated")
    assert len(got) == 1
    assert "timeout" in got[0].message


def test_budget_threaded_into_park_is_quiet():
    src = """\
def fetch(ev, timeout):
    ev.wait(timeout)
"""
    assert findings_of(src, "deadline-not-propagated") == []


def test_budget_derived_value_counts_as_propagated():
    # remaining = f(deadline) flows through a local before the park
    src = """\
def fetch(cond, deadline):
    remaining = deadline.remaining()
    cond.wait(remaining)
"""
    assert findings_of(src, "deadline-not-propagated") == []


def test_poll_loop_consulting_deadline_is_quiet():
    # the scheduler's _await idiom: fixed-quantum park, budget re-checked
    # every iteration — the budget is honored by the LOOP, not the park
    src = """\
def await_done(ev, deadline):
    while True:
        if ev.wait(0.05):
            return True
        deadline.check()
"""
    assert findings_of(src, "deadline-not-propagated") == []


def test_deadline_typed_param_without_budget_name_fires():
    # the shared Deadline type marks the param as a budget even when its
    # name says nothing — the satellite type is the analyzer's anchor
    src = """\
def gather(ev, budget: "Deadline"):
    ev.wait()
"""
    assert len(findings_of(src, "deadline-not-propagated")) == 1


def test_deadline_not_propagated_suppression():
    src = """\
def fetch(ev, timeout):
    ev.wait()  # druidlint: disable=deadline-not-propagated
"""
    assert findings_of(src, "deadline-not-propagated") == []


# ---------------------------------------------------------------------------
# unclamped-external-timeout
# ---------------------------------------------------------------------------

_POLL_TMPL = """\
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        self._poll(float(self.headers["x-timeout"]))

    def _poll(self, timeout_s):
{body}
"""


def test_wire_timeout_reaching_park_unclamped_fires():
    src = _POLL_TMPL.format(body="        self.cond.wait(timeout_s)")
    got = findings_of(src, "unclamped-external-timeout")
    assert len(got) == 1
    assert "unclamped" in got[0].message


def test_wire_timeout_clamped_by_min_is_quiet():
    src = _POLL_TMPL.format(
        body="        timeout_s = min(timeout_s, 60.0)\n"
             "        self.cond.wait(timeout_s)")
    assert findings_of(src, "unclamped-external-timeout") == []


def test_wire_timeout_bounding_a_park_loop_fires():
    # the PR 14 shape: per-park quantum is clamped, but the LOOP runs
    # until a deadline built from the raw wire value — the handler is
    # still parked for as long as the wire asked
    src = _POLL_TMPL.format(
        body="        deadline = Deadline.after_s(timeout_s)\n"
             "        while True:\n"
             "            if deadline.expired():\n"
             "                return None\n"
             "            self.cond.wait(0.25)")
    got = findings_of(src, "unclamped-external-timeout")
    assert len(got) == 1
    assert "loop" in got[0].message


def test_clamped_deadline_bounding_a_park_loop_is_quiet():
    src = _POLL_TMPL.format(
        body="        timeout_s = min(timeout_s, 60.0)\n"
             "        deadline = Deadline.after_s(timeout_s)\n"
             "        while True:\n"
             "            if deadline.expired():\n"
             "                return None\n"
             "            self.cond.wait(0.25)")
    assert findings_of(src, "unclamped-external-timeout") == []


def test_unclamped_timeout_suppression():
    src = _POLL_TMPL.format(
        body="        self.cond.wait(timeout_s)"
             "  # druidlint: disable=unclamped-external-timeout")
    assert findings_of(src, "unclamped-external-timeout") == []


# ---------------------------------------------------------------------------
# sleep-on-request-path
# ---------------------------------------------------------------------------

def test_fixed_sleep_on_handler_path_fires():
    src = """\
import time
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        time.sleep(1.0)
"""
    got = findings_of(src, "sleep-on-request-path")
    assert len(got) == 1
    assert "jitter" in got[0].message


def test_jittered_deadline_guarded_sleep_is_quiet():
    # the remote client's 429 back-off shape: pause from
    # decorrelated_jitter, guarded by the remaining deadline
    src = """\
import time
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        deadline = Deadline.after_s(5.0)
        sleep_s = decorrelated_jitter(0.05, 1.0, self.prev)
        if sleep_s < deadline.remaining():
            time.sleep(sleep_s)
"""
    assert findings_of(src, "sleep-on-request-path") == []


def test_sleep_off_request_path_is_quiet():
    src = """\
import time

def backoff():
    time.sleep(1.0)
"""
    assert findings_of(src, "sleep-on-request-path") == []


def test_sleep_suppression():
    src = """\
import time
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        time.sleep(1.0)  # druidlint: disable=sleep-on-request-path
"""
    assert findings_of(src, "sleep-on-request-path") == []


# ---------------------------------------------------------------------------
# stop-signal-coverage
# ---------------------------------------------------------------------------

_THREAD_TMPL = """\
import threading

class Pump:
    def start(self):
        self._stopping = False
        self._t = threading.Thread(target=self._loop)
        self._t.start()

    def _loop(self):
{body}

    def _step(self):
        pass
"""


def test_thread_loop_without_stop_consult_fires():
    src = _THREAD_TMPL.format(body="        while True:\n"
                                   "            self._step()")
    got = findings_of(src, "stop-signal-coverage")
    assert len(got) == 1
    assert "stop signal" in got[0].message


def test_thread_loop_checking_stop_flag_is_quiet():
    src = _THREAD_TMPL.format(body="        while True:\n"
                                   "            if self._stopping:\n"
                                   "                return\n"
                                   "            self._step()")
    assert findings_of(src, "stop-signal-coverage") == []


def test_thread_loop_waiting_on_stop_event_is_quiet():
    # latch.py's idiom: the loop condition IS the stop event
    src = _THREAD_TMPL.format(body="        while True:\n"
                                   "            if self._stop_event"
                                   ".wait(0.5):\n"
                                   "                return\n"
                                   "            self._step()")
    assert findings_of(src, "stop-signal-coverage") == []


def test_bounded_loop_in_thread_root_is_quiet():
    src = """\
import threading

class Pump:
    def start(self):
        self._t = threading.Thread(target=self._drain)
        self._t.start()

    def _drain(self):
        for item in self.items:
            self._step(item)

    def _step(self, item):
        pass
"""
    assert findings_of(src, "stop-signal-coverage") == []


def test_stop_coverage_suppression():
    src = _THREAD_TMPL.format(
        body="        while True:"
             "  # druidlint: disable=stop-signal-coverage\n"
             "            self._step()")
    assert findings_of(src, "stop-signal-coverage") == []


# ---------------------------------------------------------------------------
# module scoping: stallguard rides the raceguard member set
# ---------------------------------------------------------------------------

def test_non_member_module_is_ignored():
    src = """\
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        self.server.ready.wait()
"""
    assert findings_of(src, "unbounded-blocking-call",
                       path="scripts/helper.py") == []


# ---------------------------------------------------------------------------
# dynamic stall witness
# ---------------------------------------------------------------------------

def _witness(tmp_path):
    """A witness rooted at a temp tree with one fake druid_tpu module, so
    eligibility sees 'project' call sites without touching the real
    session singleton."""
    from tools.druidlint.stallwitness import StallWitness
    pkg = tmp_path / "druid_tpu"
    pkg.mkdir()
    return StallWitness(str(tmp_path)), pkg


def _run_site(pkg, body: str):
    """Compile `body` as a druid_tpu-resident function and run it — the
    witness's caller-frame eligibility keys on the code object's
    filename."""
    site = pkg / "parksite.py"
    site.write_text(body)
    code = compile(body, str(site), "exec")
    ns = {}
    exec(code, ns)
    return ns["park"]()


def test_witness_flags_untimed_park(tmp_path):
    w, pkg = _witness(tmp_path)
    with w:
        _run_site(pkg, """\
import threading

def park():
    ev = threading.Event()
    ev.set()
    ev.wait()
""")
    assert len(w.violations) == 1
    assert "untimed event-wait" in w.violations[0]


def test_witness_passes_timed_park(tmp_path):
    w, pkg = _witness(tmp_path)
    with w:
        _run_site(pkg, """\
import threading

def park():
    ev = threading.Event()
    ev.wait(0.01)
""")
    assert w.violations == []
    ((site, stats),) = w.sites.items()
    assert site[2] == "event-wait"
    assert stats["count"] == 1
    assert stats["max_s"] >= 0.01


def test_witness_excuses_shutdown_scoped_park(tmp_path):
    w, pkg = _witness(tmp_path)
    with w:
        _run_site(pkg, """\
import threading

def _drain_forever(ev):
    ev.wait()

def stop(ev):
    _drain_forever(ev)

def park():
    ev = threading.Event()
    ev.set()
    stop(ev)
""")
    # recorded as untimed, but excused: a stop() frame is on the stack
    assert w.violations == []
    assert sum(int(s["untimed"]) for s in w.sites.values()) == 1


def test_witness_ignores_foreign_call_sites(tmp_path):
    w, _pkg = _witness(tmp_path)
    with w:
        ev = threading.Event()
        ev.set()
        ev.wait()                 # this file is not under tmp_path
        time.sleep(0.001)
    assert w.sites == {}
    assert w.violations == []


def test_witness_uninstall_restores_primitives(tmp_path):
    import queue
    import subprocess
    originals = (threading.Event.wait, threading.Condition.wait,
                 threading.Thread.join, queue.Queue.get,
                 subprocess.Popen.wait, time.sleep)
    w, _pkg = _witness(tmp_path)
    w.install()
    try:
        assert threading.Event.wait is not originals[0]
    finally:
        w.uninstall()
    assert (threading.Event.wait, threading.Condition.wait,
            threading.Thread.join, queue.Queue.get,
            subprocess.Popen.wait, time.sleep) == originals
