"""Spatial dimensions + filters (reference: ImmutableRTree /
SpatialDimFilter / SpatialDimensionSchema — the coordinate-dim capability,
evaluated here as per-dictionary-value bound tests through the standard
LUT/bitmap machinery)."""
import numpy as np
import pytest

from druid_tpu.data.segment import SegmentBuilder
from druid_tpu.engine import QueryExecutor
from druid_tpu.ingest.input import (DimensionsSpec, InputRowParser,
                                    TimestampSpec)
from druid_tpu.query import (CountAggregator, LongSumAggregator,
                             PolygonBound, RadiusBound, RectangularBound,
                             SpatialFilter, filter_from_json)
from druid_tpu.query.model import GroupByQuery, ScanQuery, TimeseriesQuery
from druid_tpu.utils.intervals import Interval, parse_ts

DAY = Interval.of("2026-06-01", "2026-06-02")
T0 = parse_ts("2026-06-01")


@pytest.fixture(scope="module")
def geo_segment():
    rng = np.random.default_rng(12)
    n = 4000
    xs = rng.uniform(-10, 10, n).round(3)
    ys = rng.uniform(-10, 10, n).round(3)
    b = SegmentBuilder("geo", DAY)
    b.add_columns(
        np.asarray([T0 + i for i in range(n)], dtype=np.int64),
        {"loc": [f"{x},{y}" for x, y in zip(xs, ys)],
         "city": [f"c{i % 5}" for i in range(n)]},
        {"m": np.ones(n, dtype=np.int64)})
    return b.build(), xs, ys


def _count(seg, flt):
    rows = QueryExecutor([seg]).run(
        TimeseriesQuery.of("geo", [DAY], [CountAggregator("n")],
                           filter=flt))
    return rows[0]["result"]["n"] if rows else 0


def test_rectangular_bound(geo_segment):
    seg, xs, ys = geo_segment
    flt = SpatialFilter("loc", RectangularBound((-5.0, -2.0), (5.0, 8.0)))
    want = int(((xs >= -5) & (xs <= 5) & (ys >= -2) & (ys <= 8)).sum())
    assert want > 0 and _count(seg, flt) == want


def test_radius_bound(geo_segment):
    seg, xs, ys = geo_segment
    flt = SpatialFilter("loc", RadiusBound((1.0, 1.0), 4.0))
    want = int(((xs - 1) ** 2 + (ys - 1) ** 2 <= 16.0).sum())
    assert want > 0 and _count(seg, flt) == want


def test_polygon_bound(geo_segment):
    seg, xs, ys = geo_segment
    # triangle (-8,-8) (8,-8) (0,8)
    flt = SpatialFilter("loc", PolygonBound((-8.0, 8.0, 0.0),
                                            (-8.0, -8.0, 8.0)))
    got = _count(seg, flt)
    # golden: same even-odd test vectorized
    inside = np.zeros(len(xs), dtype=bool)
    vx, vy = [-8.0, 8.0, 0.0], [-8.0, -8.0, 8.0]
    j = 2
    with np.errstate(divide="ignore", invalid="ignore"):
        for i in range(3):
            cond = ((np.asarray(vy)[i] > ys) != (np.asarray(vy)[j] > ys)) & \
                (xs < (vx[j] - vx[i]) * (ys - vy[i]) / (vy[j] - vy[i]) + vx[i])
            inside ^= cond
            j = i
    assert got == int(inside.sum()) > 0


def test_spatial_composes_with_other_filters(geo_segment):
    seg, xs, ys = geo_segment
    from druid_tpu.query import AndFilter, SelectorFilter
    flt = AndFilter([
        SpatialFilter("loc", RectangularBound((-5.0, -5.0), (5.0, 5.0))),
        SelectorFilter("city", "c1")])
    city = np.asarray([f"c{i % 5}" for i in range(len(xs))])
    want = int(((xs >= -5) & (xs <= 5) & (ys >= -5) & (ys <= 5)
                & (city == "c1")).sum())
    assert _count(seg, flt) == want
    # groupBy + scan paths share the same predicate machinery
    rows = QueryExecutor([seg]).run(GroupByQuery.of(
        "geo", [DAY], ["city"], [CountAggregator("n")], filter=flt))
    assert sum(r["event"]["n"] for r in rows) == want
    batches = QueryExecutor([seg]).run(ScanQuery.of(
        "geo", [DAY], columns=["loc"], filter=flt))
    assert sum(len(b["events"]) for b in batches) == want


def test_spatial_filter_json_roundtrip():
    for bound in (RectangularBound((0.0, 0.0), (1.0, 2.0)),
                  RadiusBound((3.0, 4.0), 5.0),
                  PolygonBound((0.0, 1.0, 1.0), (0.0, 0.0, 1.0))):
        flt = SpatialFilter("loc", bound)
        back = filter_from_json(flt.to_json())
        assert back == flt


def test_spatial_dimension_ingest():
    """spatialDimensions joins coordinate fields into one 'x,y' dim at
    parse time (SpatialDimensionSchema)."""
    parser = InputRowParser(
        TimestampSpec("t", "millis"),
        DimensionsSpec(spatial_dimensions=(("coords", ("lat", "lon")),)))
    batch = parser.parse_batch([
        {"t": T0, "lat": 1.5, "lon": 2.5, "who": "a"},
        {"t": T0 + 1, "lat": -3.0, "lon": 0.25, "who": "b"},
    ])
    assert batch.columns["coords"] == ["1.5,2.5", "-3.0,0.25"]
    # round-trips through parser JSON for peon shipping
    back = InputRowParser.from_json(parser.to_json())
    b2 = back.parse_batch([{"t": T0, "lat": 9, "lon": 8}])
    assert b2.columns["coords"] == ["9,8"]