"""keyguard unit battery: every cache-key soundness rule must fire on its
positive shape, stay quiet on the keyed/pure/latched shapes, honor
per-line suppressions, and the REAL tree must stay gated — deleting a
descriptor from `_structure_sig`'s fold has to light the param-flow rule
up. The dynamic keywitness machinery gets its own unit section.

Pattern mirrors tests/test_leakguard.py: check_source with a root-less
config analyzes each snippet standalone through the real rule registry,
so suppression/baseline behavior is exactly the shipped one.
"""
import collections
import sys
from pathlib import Path

import numpy as np
import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.druidlint.core import LintConfig, check_source  # noqa: E402
from tools.druidlint.keywitness import (KeyWitness, RecordingCache,  # noqa: E402
                                        _fp, fingerprint_args)


def cfg(*rules) -> LintConfig:
    c = LintConfig(rules=list(rules) if rules else [])
    c.root = "/nonexistent-keyguard-root"
    return c


def findings_of(source: str, rule: str, path: str = "druid_tpu/mod.py",
                config: LintConfig = None):
    c = config if config is not None else cfg(rule)
    return [f for f in check_source(source, path, c) if f.rule == rule]


# ---------------------------------------------------------------------------
# unkeyed-trace-input: build-on-miss cache sites
# ---------------------------------------------------------------------------

def test_unkeyed_build_input_fires():
    src = """\
_JIT_CACHE = {}

def run(spec, extra):
    sig = f"s={spec}"
    fn = _JIT_CACHE.get(sig)
    if fn is None:
        fn = _build(spec, extra)
        _JIT_CACHE[sig] = fn
    return fn
"""
    got = findings_of(src, "unkeyed-trace-input")
    assert len(got) == 1
    assert "extra" in got[0].message
    assert "no dataflow into the key" in got[0].message


def test_fully_keyed_build_is_quiet():
    src = """\
_JIT_CACHE = {}

def run(spec, extra):
    sig = f"s={spec}|e={extra}"
    fn = _JIT_CACHE.get(sig)
    if fn is None:
        fn = _build(spec, extra)
        _JIT_CACHE[sig] = fn
    return fn
"""
    assert findings_of(src, "unkeyed-trace-input") == []


def test_unconditional_registry_store_is_quiet():
    # a checked-then-raise registry is not a build-on-miss cache: the
    # insert is not control-dependent on the miss probe
    src = """\
_REG = {}

def register(name, obj, owner):
    if name in _REG:
        raise ValueError(name)
    _REG[name] = _wrap(obj, owner)
"""
    assert findings_of(src, "unkeyed-trace-input") == []


def test_per_call_dict_is_quiet():
    src = """\
def fold(rows, extra):
    acc = {}
    for r in rows:
        k = r.key
        got = acc.get(k)
        if got is None:
            acc[k] = _merge(r, extra)
    return acc
"""
    assert findings_of(src, "unkeyed-trace-input") == []


def test_setdefault_build_with_unkeyed_input_fires():
    src = """\
_HOOKS = {}

def register(key, hook, ctx):
    return _HOOKS.setdefault(key, _make_hook(hook, ctx))
"""
    got = findings_of(src, "unkeyed-trace-input")
    assert len(got) == 1
    assert "hook" in got[0].message and "ctx" in got[0].message


def test_pool_get_or_build_lambda_inputs_must_be_keyed():
    src = """\
def stage(pool, owner, key, cols, layout):
    return pool.get_or_build(owner, key, lambda: _build(cols, layout))
"""
    got = findings_of(src, "unkeyed-trace-input")
    assert len(got) == 1
    assert "cols" in got[0].message and "layout" in got[0].message


def test_pool_get_or_build_keyed_lambda_is_quiet():
    src = """\
def stage(pool, owner, cols, layout):
    key = (tuple(cols), layout)
    return pool.get_or_build(owner, key, lambda: _build(cols, layout))
"""
    assert findings_of(src, "unkeyed-trace-input") == []


def test_unkeyed_trace_input_suppression():
    src = """\
_JIT_CACHE = {}

def run(spec, extra):
    sig = f"s={spec}"
    fn = _JIT_CACHE.get(sig)
    if fn is None:
        fn = _build(spec, extra)
        _JIT_CACHE[sig] = fn  # druidlint: disable=unkeyed-trace-input
    return fn
"""
    assert findings_of(src, "unkeyed-trace-input") == []


# ---------------------------------------------------------------------------
# unkeyed-trace-input: key-function param → return flow
# ---------------------------------------------------------------------------

def _key_fn_cfg(qual="make_sig"):
    c = cfg("unkeyed-trace-input")
    c.keyguard_key_fns = [f"druid_tpu/mod.py::{qual}"]
    return c


def test_key_fn_dropped_param_fires():
    src = """\
def make_sig(spec, packs, cascades):
    return f"s={spec}|c={cascades}"
"""
    got = findings_of(src, "unkeyed-trace-input", config=_key_fn_cfg())
    assert len(got) == 1
    assert "'packs'" in got[0].message


def test_key_fn_all_params_flow_is_quiet():
    src = """\
def make_sig(spec, packs, cascades):
    parts = [f"s={spec}"]
    parts.append(f"p={packs}")
    return "|".join(parts) + f"|c={cascades}"
"""
    assert findings_of(src, "unkeyed-trace-input",
                       config=_key_fn_cfg()) == []


def test_key_fn_underscore_params_exempt():
    src = """\
def make_sig(spec, _debug):
    return f"s={spec}"
"""
    assert findings_of(src, "unkeyed-trace-input",
                       config=_key_fn_cfg()) == []


def test_real_structure_sig_mutation_is_caught():
    """The acceptance gate: delete the pack descriptor from the REAL
    `_structure_sig`'s fold and keyguard must notice — with the stock
    source staying clean under the same config."""
    path = "druid_tpu/engine/grouping.py"
    src = (REPO_ROOT / path).read_text()
    assert 'f"packs={packs}",' in src
    mutated = src.replace('f"packs={packs}",', "")
    c = cfg("unkeyed-trace-input")
    c.keyguard_key_fns = [f"{path}::_structure_sig"]
    got = findings_of(mutated, "unkeyed-trace-input", path=path, config=c)
    assert any("'packs'" in f.message and "_structure_sig" in f.message
               for f in got)
    c2 = cfg("unkeyed-trace-input")
    c2.keyguard_key_fns = [f"{path}::_structure_sig"]
    assert findings_of(src, "unkeyed-trace-input", path=path,
                       config=c2) == []


def test_real_layout_sig_mutation_is_caught():
    """PR-17 rider gate, sharded edition: drop the segment axis from the
    REAL speclayout.layout_sig and keyguard must flag the `layout`
    parameter as unkeyed — a layout input silently missing from the
    sharded program's cache key would alias programs across meshes. Stock
    source stays clean under the same config."""
    path = "druid_tpu/parallel/speclayout.py"
    src = (REPO_ROOT / path).read_text()
    assert "return (layout.seg_axis," in src
    mutated = src.replace("return (layout.seg_axis,", "return (")
    c = cfg("unkeyed-trace-input")
    c.keyguard_key_fns = [f"{path}::layout_sig"]
    got = findings_of(mutated, "unkeyed-trace-input", path=path, config=c)
    assert any("'layout'" in f.message and "layout_sig" in f.message
               for f in got)
    c2 = cfg("unkeyed-trace-input")
    c2.keyguard_key_fns = [f"{path}::layout_sig"]
    assert findings_of(src, "unkeyed-trace-input", path=path,
                       config=c2) == []


# ---------------------------------------------------------------------------
# impure-eligibility
# ---------------------------------------------------------------------------

def _elig_cfg(qual="eligible"):
    c = cfg("impure-eligibility")
    c.keyguard_eligibility = [f"druid_tpu/mod.py::{qual}"]
    return c


def test_env_read_in_eligibility_fires():
    src = """\
import os

def eligible(col):
    if os.environ.get("DRUID_TPU_FAST") == "1":
        return True
    return col.cardinality < 1000
"""
    got = findings_of(src, "impure-eligibility", config=_elig_cfg())
    assert len(got) == 1
    assert "os.environ" in got[0].message


def test_clock_read_via_same_module_callee_fires():
    src = """\
import time

def _warm():
    return time.monotonic() > 100.0

def eligible(col):
    return _warm() and col.cardinality < 1000
"""
    got = findings_of(src, "impure-eligibility", config=_elig_cfg())
    assert len(got) == 1
    assert "time.monotonic" in got[0].message
    assert "via _warm" in got[0].message


def test_pure_eligibility_is_quiet():
    src = """\
def eligible(col, spec):
    return col.cardinality < 1000 and len(spec.dims) <= 4
"""
    assert findings_of(src, "impure-eligibility", config=_elig_cfg()) == []


def test_unconfigured_function_is_quiet():
    src = """\
import os

def helper(col):
    return os.environ.get("DRUID_TPU_FAST") == "1"
"""
    assert findings_of(src, "impure-eligibility", config=_elig_cfg()) == []


def test_impure_eligibility_suppression():
    src = """\
import os

def eligible(col):
    return os.environ.get("DRUID_TPU_FAST") == "1"  # druidlint: disable=impure-eligibility
"""
    assert findings_of(src, "impure-eligibility", config=_elig_cfg()) == []


# ---------------------------------------------------------------------------
# env-flag-latch (against a synthetic on-disk catalog)
# ---------------------------------------------------------------------------

_CATALOG_SRC = """\
class Flag:
    def __init__(self, default="", semantics="latch", doc="",
                 key_member=False):
        pass

FLAGS = {
    "DRUID_TPU_LATCHED": Flag(default="", semantics="latch", doc="x"),
    "DRUID_TPU_LIVE_KEYED": Flag(default="", semantics="live", doc="x",
                                 key_member=True),
    "DRUID_TPU_LIVE_UNKEYED": Flag(default="", semantics="live", doc="x"),
}
"""


def _latch_cfg(tmp_path, *extra_rules):
    (tmp_path / "flags.py").write_text(_CATALOG_SRC)
    c = cfg("env-flag-latch", *extra_rules)
    c.root = str(tmp_path)
    c.flags_catalog = "flags.py"
    c.keyguard_plan_modules = ["druid_tpu/*"]
    return c


def test_latch_flag_read_in_function_fires(tmp_path):
    src = """\
import os

def plan(col):
    return os.environ.get("DRUID_TPU_LATCHED") == "1"
"""
    got = findings_of(src, "env-flag-latch", config=_latch_cfg(tmp_path))
    assert len(got) == 1
    assert "declared 'latch' but read inside plan()" in got[0].message


def test_latch_flag_read_at_import_is_quiet(tmp_path):
    src = """\
import os

_FAST = os.environ.get("DRUID_TPU_LATCHED") == "1"

def plan(col):
    return _FAST
"""
    assert findings_of(src, "env-flag-latch",
                       config=_latch_cfg(tmp_path)) == []


def test_live_unkeyed_flag_read_in_function_fires(tmp_path):
    src = """\
import os

def plan(col):
    return os.environ.get("DRUID_TPU_LIVE_UNKEYED") == "1"
"""
    got = findings_of(src, "env-flag-latch", config=_latch_cfg(tmp_path))
    assert len(got) == 1
    assert "not a declared key member" in got[0].message


def test_live_key_member_read_in_function_is_quiet(tmp_path):
    src = """\
import os

def plan(col):
    return os.environ.get("DRUID_TPU_LIVE_KEYED") == "1"
"""
    assert findings_of(src, "env-flag-latch",
                       config=_latch_cfg(tmp_path)) == []


def test_live_flag_read_at_import_fires(tmp_path):
    src = """\
import os

_V = os.environ.get("DRUID_TPU_LIVE_KEYED")
"""
    got = findings_of(src, "env-flag-latch", config=_latch_cfg(tmp_path))
    assert len(got) == 1
    assert "read at import time" in got[0].message


def test_module_outside_plan_scope_is_quiet(tmp_path):
    src = """\
import os

def plan(col):
    return os.environ.get("DRUID_TPU_LATCHED") == "1"
"""
    c = _latch_cfg(tmp_path)
    c.keyguard_plan_modules = ["druid_tpu/engine/*"]
    assert findings_of(src, "env-flag-latch", path="druid_tpu/mod.py",
                       config=c) == []


# ---------------------------------------------------------------------------
# flag-name (undeclared DRUID_TPU_* reads)
# ---------------------------------------------------------------------------

def _flag_name_cfg(tmp_path):
    (tmp_path / "flags.py").write_text(_CATALOG_SRC)
    c = cfg("flag-name")
    c.root = str(tmp_path)
    c.flags_catalog = "flags.py"
    c.flag_modules = ["druid_tpu/*"]
    return c


def test_undeclared_flag_read_fires(tmp_path):
    src = """\
import os

_V = os.environ.get("DRUID_TPU_NO_SUCH_FLAG")
"""
    got = findings_of(src, "flag-name", config=_flag_name_cfg(tmp_path))
    assert len(got) == 1
    assert "DRUID_TPU_NO_SUCH_FLAG" in got[0].message
    assert "not declared" in got[0].message


def test_declared_flag_read_is_quiet(tmp_path):
    src = """\
import os

_V = os.environ.get("DRUID_TPU_LATCHED")
"""
    assert findings_of(src, "flag-name",
                       config=_flag_name_cfg(tmp_path)) == []


def test_catalog_file_itself_is_exempt(tmp_path):
    c = _flag_name_cfg(tmp_path)
    c.flag_modules = ["*"]
    src = """\
import os

_V = os.environ.get("DRUID_TPU_NO_SUCH_FLAG")
"""
    assert findings_of(src, "flag-name", path="flags.py", config=c) == []


def test_real_catalog_covers_every_tree_read():
    """Every DRUID_TPU_* read in the real tree is declared — the shipped
    config's flag-name burn stays clean (CLI equivalent lives in
    test_lint.py; this pins the catalog/tree agreement directly)."""
    from tools.druidlint.keyguard import flag_catalog
    from tools.druidlint.core import load_config
    c = load_config(REPO_ROOT)
    catalog = flag_catalog(str(REPO_ROOT), c.flags_catalog)
    assert len(catalog) >= 10
    import re
    pat = re.compile(r"DRUID_TPU_[A-Z0-9_]+")
    read = set()
    for p in (REPO_ROOT / "druid_tpu").rglob("*.py"):
        read |= set(pat.findall(p.read_text()))
    assert read <= set(catalog), f"undeclared flags: {read - set(catalog)}"


def test_readme_flags_table_in_sync():
    from druid_tpu.config.flags import flags_table_markdown
    readme = (REPO_ROOT / "README.md").read_text()
    assert flags_table_markdown() in readme, (
        "README flags table is stale — regenerate it with "
        "druid_tpu.config.flags.flags_table_markdown()")


# ---------------------------------------------------------------------------
# keywitness: fingerprints, collision detection, install/uninstall
# ---------------------------------------------------------------------------

def test_fingerprint_is_structural_not_data():
    a = np.zeros(64, np.int64)
    b = np.arange(128, dtype=np.int64)      # different data AND length
    assert _fp(a, shapes=False) == _fp(b, shapes=False) == "arr(int64,1)"
    assert _fp(a, shapes=True) != _fp(b, shapes=True)


def test_fingerprint_canonicalizes_dicts_and_objects():
    # insertion order is canonicalized away; scalar VALUES stay (a build
    # arg like K or n_intervals is structure)
    assert _fp({"b": 1, "a": 2}, shapes=False) \
        == _fp({"a": 2, "b": 1}, shapes=False)
    assert _fp({"a": 1}, shapes=False) != _fp({"a": 2}, shapes=False)

    class Spec:
        def __init__(self, n):
            self.dims = ["d"] * n
            self.mode = "hash"

    assert _fp(Spec(1), shapes=False) == _fp(Spec(1), shapes=False)
    # structure (list arity) differs → fingerprints differ
    assert _fp(Spec(1), shapes=False) != _fp(Spec(2), shapes=False)
    # no raw addresses ever leak into a fingerprint
    assert " at 0x" not in fingerprint_args(Spec(1), object())


def test_fingerprint_excludes_presentation_and_aux_fields():
    """Output-column names are host-side presentation and uniform bucket
    scalars ride aux as runtime arrays — one compiled program serving
    both sides of each pair is the engine design, not a collision."""
    class GroupSpec:                # matches the _FP_EXCLUDE registry row
        def __init__(self, off):
            self.bucket_mode = "uniform"
            self.uniform_first_offset = off
            self.uniform_period = 86400000

    assert _fp(GroupSpec(0), shapes=False) \
        == _fp(GroupSpec(-86400000), shapes=False)

    class Kern:
        def __init__(self, name, field):
            self.name = name
            self.field = field

    # the output label is excluded everywhere...
    assert _fp(Kern("ls", "metLong"), shapes=False) \
        == _fp(Kern("sumLong", "metLong"), shapes=False)
    # ...but input-SELECTING fields stay structural
    assert _fp(Kern("s", "metLong"), shapes=False) \
        != _fp(Kern("s", "metDouble"), shapes=False)


def test_fingerprint_canonicalizes_sequences_and_enums():
    import enum as enum_mod

    # list vs tuple cannot shape a built program (closure iteration,
    # never pytree leaves) — fingerprint them identically
    assert _fp([1, "x"], shapes=False) == _fp((1, "x"), shapes=False)

    class Mode(enum_mod.Enum):
        LONG = 3

    # enums print as type.member, never recursing into EnumMeta
    assert _fp(Mode.LONG, shapes=False) == "Mode.LONG"


def test_handback_prime_does_not_claim_parked_fingerprint():
    """The nested-witness hand-back re-inserts warm keys; a dangling
    parked fingerprint (an inner-span build both wrappers saw but only
    the inner cache recorded) must NOT be claimed by those re-inserts —
    that mis-attributes one build's structure to an unrelated key."""
    w = KeyWitness(str(REPO_ROOT))
    cache = RecordingCache(w, "c")
    w._park_pending("c", "fpA")
    cache["k1"] = "v1"                       # real insert claims fpA
    w._park_pending("c", "fpB-from-inner-span")   # left dangling
    cache._prime([("k1", "v1")])             # hand-back iteration
    assert w.collisions == []
    assert w._take_pending("c") == "fpB-from-inner-span"


def test_same_key_same_fingerprint_is_not_a_collision():
    w = KeyWitness(str(REPO_ROOT))
    w.record("c", ("k",), "fp1")
    w.record("c", ("k",), "fp1")
    w.record("c", ("other",), "fp2")
    assert w.collisions == []


def test_same_key_different_fingerprint_is_a_collision():
    w = KeyWitness(str(REPO_ROOT))
    w.record("c", ("k",), "fp1")
    w.record("c", ("k",), "fp2")
    assert len(w.collisions) == 1
    assert "different input structure" in w.collisions[0]


def test_fingerprint_table_outlives_eviction():
    """key→structure is a time-invariant contract: a key rebuilt after
    cache eviction must reproduce its FIRST build's fingerprint."""
    w = KeyWitness(str(REPO_ROOT))
    cache = RecordingCache(w, "c")
    w._park_pending("c", "fp1")
    cache["k"] = object()
    del cache["k"]                           # evicted
    w._park_pending("c", "fp2")              # rebuild, different structure
    cache["k"] = object()
    assert len(w.collisions) == 1


def test_install_uninstall_restores_engine_globals():
    import druid_tpu.engine.grouping as grouping
    orig_builder = grouping._build_device_fn
    orig_cache_type = type(grouping._JIT_CACHE)
    w = KeyWitness(str(REPO_ROOT)).install()
    try:
        assert grouping._build_device_fn is not orig_builder
        assert isinstance(grouping._JIT_CACHE, RecordingCache)
        assert grouping._JIT_CACHE._witness is w
    finally:
        w.uninstall()
    assert grouping._build_device_fn is orig_builder
    # restores the pre-install cache type — the session-wide witness's
    # RecordingCache when DRUID_TPU_KEY_WITNESS=1, a plain dict otherwise
    assert type(grouping._JIT_CACHE) is orig_cache_type
    if isinstance(grouping._JIT_CACHE, RecordingCache):
        assert grouping._JIT_CACHE._witness is not w
    assert issubclass(orig_cache_type, dict)


def test_uninstall_preserves_warm_entries():
    import druid_tpu.engine.grouping as grouping
    w = KeyWitness(str(REPO_ROOT)).install()
    try:
        grouping._JIT_CACHE["warm-key"] = "warm-value"
    finally:
        w.uninstall()
    try:
        assert grouping._JIT_CACHE.get("warm-key") == "warm-value"
    finally:
        grouping._JIT_CACHE.pop("warm-key", None)


def test_install_over_warm_cache():
    """Mid-suite installs see already-populated jit caches; wrapping must
    carry the warm entries into the RecordingCache without recording them
    as builds (OrderedDict.__init__ routes through __setitem__)."""
    import druid_tpu.engine.grouping as grouping
    grouping._JIT_CACHE["pre-warm"] = "pre-value"
    try:
        w = KeyWitness(str(REPO_ROOT)).install()
        try:
            assert grouping._JIT_CACHE.get("pre-warm") == "pre-value"
            assert w.collisions == []
            assert not any(c.get("build") for c in w.counts.values())
        finally:
            w.uninstall()
        assert grouping._JIT_CACHE.get("pre-warm") == "pre-value"
    finally:
        grouping._JIT_CACHE.pop("pre-warm", None)


def test_nested_witness_hands_back_to_outer():
    """A per-test witness inside the session-wide one must restore the
    OUTER witness's recording cache on uninstall, entries intact."""
    import druid_tpu.engine.grouping as grouping
    outer = KeyWitness(str(REPO_ROOT)).install()
    try:
        inner = KeyWitness(str(REPO_ROOT)).install()
        grouping._JIT_CACHE["nested-key"] = "v"
        inner.uninstall()
        assert isinstance(grouping._JIT_CACHE, RecordingCache)
        assert grouping._JIT_CACHE._witness is outer
        assert collections.OrderedDict.get(
            grouping._JIT_CACHE, "nested-key") == "v"
    finally:
        outer.uninstall()
    # fully unwound from THIS test's witnesses (under the session-wide
    # witness the cache legitimately remains its RecordingCache)
    if isinstance(grouping._JIT_CACHE, RecordingCache):
        assert grouping._JIT_CACHE._witness is not outer
    grouping._JIT_CACHE.pop("nested-key", None)


def test_pool_recording_scoped_to_install_time_singleton():
    """Only the production pool singleton is witnessed: isolated test
    pools deliberately churn toy keys (eviction/accounting tests) and
    must not register collisions."""
    from druid_tpu.data import devicepool

    class Owner:                             # weakref-able owner stand-in
        pass

    keep = [Owner(), Owner()]                # alive across the accesses
    w = KeyWitness(str(REPO_ROOT)).install()
    try:
        side = devicepool.DeviceSegmentPool(budget_bytes=1 << 30)
        tok = side.register_owner(keep[0])
        side.get_or_build(tok, ("k",), lambda: np.zeros(8, np.int64))
        assert w.fingerprints == {}          # side pool: unrecorded
        prod_tok = w._prod_pool.register_owner(keep[1])
        w._prod_pool.get_or_build(
            prod_tok, ("kw-test",), lambda: np.zeros(8, np.int64))
        assert any(label == "devicepool.get_or_build"
                   for label, _ in w.fingerprints)
    finally:
        w.uninstall()
