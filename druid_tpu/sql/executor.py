"""SQL execution entry point.

Reference analog: sql/src/main/java/org/apache/druid/sql/http/SqlResource.java
(POST /druid/v2/sql) + QueryMaker (runs the planned native query through
QueryLifecycle and shapes native result sequences back into SQL rows), and
calcite/schema/DruidSchema.java (table discovery from live segments) +
the INFORMATION_SCHEMA tables.
"""
from __future__ import annotations

import time

from typing import Dict, List, Optional, Sequence, Tuple

from druid_tpu.query.model import (GroupByQuery, ScanQuery, TimeBoundaryQuery,
                                   TimeseriesQuery, TopNQuery)
from druid_tpu.sql.parser import Select, parse_sql
from druid_tpu.sql.planner import (OutputColumn, PlannedQuery, PlannerError,
                                   SqlSchema, plan_sql)
from druid_tpu.utils.intervals import ts_to_iso


class SqlExecutor:
    """Plans SQL against the live segment schema and runs it on a
    QueryExecutor (or any object with .run(query) and .datasources /
    .segments_of)."""

    def __init__(self, query_executor, schema_ttl: float = 30.0,
                 min_refresh_interval: float = 1.0):
        self.qe = query_executor
        self.schema_ttl = schema_ttl
        #: floor between unknown-table-triggered rebuilds — a client
        #: looping on a typo'd table must not reduce the TTL to zero and
        #: hammer historicals with segmentMetadata scatters
        self.min_refresh_interval = min_refresh_interval
        self._schema_cache = None   # (expiry monotonic, SqlSchema)
        self._last_build = 0.0

    # ---- schema discovery (DruidSchema analog) ------------------------
    def schema(self) -> SqlSchema:
        """TTL-cached: remote-broker discovery costs a segmentMetadata
        scatter per datasource; the reference's DruidSchema likewise
        refreshes on a period, not per statement. invalidate_schema()
        forces the next call to rebuild."""
        cached = self._schema_cache
        if cached is not None and time.monotonic() < cached[0]:
            return cached[1]
        schema = self._build_schema()
        self._schema_cache = (time.monotonic() + self.schema_ttl, schema)
        self._last_build = time.monotonic()
        return schema

    def invalidate_schema(self) -> None:
        self._schema_cache = None

    def _plan(self, sel):
        """Plan with one invalidate-and-retry on an unknown table — a
        datasource announced since the last schema refresh must be
        queryable immediately, not after the TTL."""
        try:
            return plan_sql(sel, self.schema())
        except PlannerError as e:
            if "unknown table" in str(e) \
                    and self._schema_cache is not None \
                    and time.monotonic() - self._last_build \
                    >= self.min_refresh_interval:
                self.invalidate_schema()
                return plan_sql(sel, self.schema())
            raise

    def _build_schema(self) -> SqlSchema:
        tables: Dict[str, Dict[str, str]] = {}
        for ds in self.qe.datasources:
            cols: Dict[str, str] = {}
            for seg in self.qe.segments_of(ds):
                for d in seg.dims:
                    cols.setdefault(d, "string")
                for m, col in seg.metrics.items():
                    t = col.type.value if hasattr(col.type, "value") else str(col.type)
                    cols.setdefault(m, t)
            if not cols:
                # no local segment objects (broker over REMOTE nodes):
                # discover via a merged segmentMetadata query — exactly the
                # reference's DruidSchema refresh
                cols = self._metadata_schema(ds)
            tables[ds] = cols
        return SqlSchema(tables)

    def _metadata_schema(self, datasource: str) -> Dict[str, str]:
        from druid_tpu.query.model import SegmentMetadataQuery
        try:
            rows = self.qe.run(SegmentMetadataQuery.of(
                datasource, merge=True, analysis_types=()))
        except Exception:
            return {}
        out: Dict[str, str] = {}
        for analysis in rows:
            for name, info in (analysis.get("columns") or {}).items():
                if name == "__time":
                    continue
                t = str(info.get("type", "STRING")).lower()
                out.setdefault(
                    name, t if t in ("string", "long", "float", "double")
                    else "string")
        return out

    # ---- entry points --------------------------------------------------
    def explain(self, sql: str, parameters: Sequence[object] = ()) -> dict:
        sel = parse_sql(sql, parameters)
        planned = self._plan(sel)
        if planned.native is None:
            return {"queryType": "metadata", "table": planned.meta_table}
        return planned.native.to_json()

    def execute(self, sql: str, parameters: Sequence[object] = ()
                ) -> Tuple[List[str], List[list]]:
        """Returns (column names, rows as lists) — the SQL resource's
        array-result format."""
        sel = parse_sql(sql, parameters)
        if sel.explain:
            import json as _json
            planned_json = self.explain(_strip_explain(sql), parameters)
            return (["PLAN"], [[_json.dumps(planned_json, sort_keys=True)]])
        planned = self._plan(sel)
        if planned.meta_table is not None:
            return self._run_meta(planned)
        rows = self.qe.run(planned.native)
        return self._shape(planned, rows)

    def tables_of(self, sql: str, parameters: Sequence[object] = ()
                  ) -> Tuple[List[str], bool]:
        """(datasources a statement reads, is_information_schema) — the
        authorization surface (reference: SqlResource resource-action
        collection before execution)."""
        sel = parse_sql(sql, parameters)
        planned = self._plan(sel)
        if planned.meta_table is not None:
            return [], True
        tables: List[str] = []
        q = planned.native
        while q is not None:
            tables += list(q.union_datasources or (q.datasource,))
            q = q.inner_query
        # the synthetic nested-query datasource is not a real resource
        return sorted({t for t in tables
                       if t and t != "__subquery__"}), False

    def execute_dicts(self, sql: str, parameters: Sequence[object] = ()
                      ) -> List[dict]:
        cols, rows = self.execute(sql, parameters)
        return [dict(zip(cols, r)) for r in rows]

    # ---- result shaping (QueryMaker analog) ---------------------------
    def _shape(self, planned: PlannedQuery, rows) -> Tuple[List[str], List[list]]:
        q = planned.native
        outs = planned.outputs
        names = [o.alias for o in outs]
        table: List[list] = []
        if isinstance(q, TimeseriesQuery):
            # executor-side ORDER BY (non-time orderings of bucket rows);
            # sorts the native rows so non-projected order fields work too
            for fname, desc in reversed(planned.sort_in_executor):
                rows = sorted(rows, key=lambda r, f=fname:
                              (r["result"].get(f) is None,
                               r["result"].get(f) or 0), reverse=desc)
            for r in rows:
                table.append(_emit(outs, r["result"], r["timestamp"]))
        elif isinstance(q, TopNQuery):
            for r in rows:
                for entry in r["result"]:
                    table.append(_emit(outs, entry, r["timestamp"]))
        elif isinstance(q, GroupByQuery):
            for r in rows:
                table.append(_emit(outs, r["event"], r["timestamp"]))
        elif isinstance(q, TimeBoundaryQuery):
            for r in rows:
                table.append([_iso(r["result"].get(o.key)) for o in outs])
        elif isinstance(q, ScanQuery):
            for batch in rows:
                for ev in batch["events"]:
                    table.append(_emit(outs, ev, ev.get("__time")))
        else:
            raise PlannerError(f"cannot shape {type(q).__name__} results")
        if planned.limit_in_executor is not None or planned.offset_in_executor:
            off = planned.offset_in_executor
            lim = planned.limit_in_executor
            table = table[off:off + lim if lim is not None else None]
        return names, table

    # ---- INFORMATION_SCHEMA -------------------------------------------
    def _run_meta(self, planned: PlannedQuery) -> Tuple[List[str], List[list]]:
        sel = planned.meta_select
        schema = self.schema()
        if planned.meta_table == "SCHEMATA":
            data = [{"CATALOG_NAME": "druid", "SCHEMA_NAME": s}
                    for s in ("druid", "INFORMATION_SCHEMA")]
        elif planned.meta_table == "TABLES":
            data = [{"TABLE_CATALOG": "druid", "TABLE_SCHEMA": "druid",
                     "TABLE_NAME": t, "TABLE_TYPE": "TABLE"}
                    for t in sorted(schema.tables)]
        elif planned.meta_table == "COLUMNS":
            data = []
            for t in sorted(schema.tables):
                cols = [("__time", "TIMESTAMP")] + sorted(
                    (c, _sql_type(ty)) for c, ty in schema.tables[t].items())
                for i, (c, ty) in enumerate(cols):
                    data.append({"TABLE_CATALOG": "druid",
                                 "TABLE_SCHEMA": "druid", "TABLE_NAME": t,
                                 "COLUMN_NAME": c, "ORDINAL_POSITION": i + 1,
                                 "DATA_TYPE": ty,
                                 "IS_NULLABLE": "YES" if ty == "VARCHAR" else "NO"})
        else:
            raise PlannerError(
                f"unknown INFORMATION_SCHEMA table [{planned.meta_table}]")
        return _meta_select(sel, data)


def _strip_explain(sql: str) -> str:
    import re
    return re.sub(r"(?is)^\s*EXPLAIN\s+PLAN\s+FOR\s+", "", sql)


def _sql_type(t: str) -> str:
    return {"string": "VARCHAR", "long": "BIGINT", "float": "FLOAT",
            "double": "DOUBLE"}.get(t, t.upper())


def _iso(v):
    return ts_to_iso(v) if v is not None else None


def _emit(outs: List[OutputColumn], fields: dict, ts) -> list:
    row = []
    for o in outs:
        if o.kind == "time":
            row.append(_iso(ts))
        elif o.kind == "constant":
            row.append(o.constant)
        elif o.kind == "column" and o.key == "__time":
            row.append(_iso(fields.get("__time", ts)))
        else:
            row.append(fields.get(o.key))
    return row


def _meta_select(sel: Select, data: List[dict]) -> Tuple[List[str], List[list]]:
    """Evaluate a (restricted) select over an in-memory metadata table:
    column projections, simple equality/IN where, ORDER BY columns, LIMIT."""
    from druid_tpu.sql import parser as P

    def match(row, e) -> bool:
        if e is None:
            return True
        if isinstance(e, P.Bin) and e.op == "AND":
            return match(row, e.left) and match(row, e.right)
        if isinstance(e, P.Bin) and e.op == "OR":
            return match(row, e.left) or match(row, e.right)
        if isinstance(e, P.Un) and e.op == "NOT":
            return not match(row, e.operand)
        if isinstance(e, P.Bin) and e.op in ("=", "<>"):
            l, r = e.left, e.right
            if isinstance(r, P.Col):
                l, r = r, l
            if isinstance(l, P.Col) and isinstance(r, P.Lit):
                eq = str(row.get(l.name)) == str(r.value)
                return eq if e.op == "=" else not eq
        if isinstance(e, P.InExpr) and isinstance(e.operand, P.Col):
            hit = str(row.get(e.operand.name)) in {str(v.value) for v in e.values}
            return hit != e.negated
        if isinstance(e, P.LikeExpr) and isinstance(e.operand, P.Col):
            import re as _re
            pat = "^" + "".join(
                ".*" if ch == "%" else "." if ch == "_" else _re.escape(ch)
                for ch in str(e.pattern.value)) + "$"
            hit = bool(_re.match(pat, str(row.get(e.operand.name, ""))))
            return hit != e.negated
        raise PlannerError("unsupported WHERE on INFORMATION_SCHEMA")

    rows = [r for r in data if match(r, sel.where)]
    if sel.order_by:
        for ob in reversed(sel.order_by):
            if not isinstance(ob.expr, P.Col):
                raise PlannerError("ORDER BY columns only on INFORMATION_SCHEMA")
            rows.sort(key=lambda r: str(r.get(ob.expr.name)),
                      reverse=ob.descending)
    if sel.limit is not None:
        rows = rows[sel.offset:sel.offset + sel.limit]
    elif sel.offset:
        rows = rows[sel.offset:]

    if len(sel.items) == 1 and isinstance(sel.items[0].expr, P.Star):
        names = keys = list(data[0].keys()) if data else []
    else:
        names, keys = [], []
        for it in sel.items:
            if not isinstance(it.expr, P.Col):
                raise PlannerError("INFORMATION_SCHEMA projections are columns")
            names.append(it.alias or it.expr.name)
            keys.append(it.expr.name)
    return names, [[r.get(k) for k in keys] for r in rows]
