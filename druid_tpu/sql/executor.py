"""SQL execution entry point.

Reference analog: sql/src/main/java/org/apache/druid/sql/http/SqlResource.java
(POST /druid/v2/sql) + QueryMaker (runs the planned native query through
QueryLifecycle and shapes native result sequences back into SQL rows), and
calcite/schema/DruidSchema.java (table discovery from live segments) +
the INFORMATION_SCHEMA tables.
"""
from __future__ import annotations

import logging
import time

from typing import Dict, List, Optional, Sequence, Tuple

from druid_tpu.query.model import (GroupByQuery, ScanQuery, TimeBoundaryQuery,
                                   TimeseriesQuery, TopNQuery)
from druid_tpu.sql import parser as P
from druid_tpu.sql.parser import Select, Union, parse_sql
from druid_tpu.sql.planner import (OutputColumn, PlannedQuery, PlannerError,
                                   SqlSchema, plan_sql)
from druid_tpu.utils.intervals import ts_to_iso

#: materialized IN-subquery row cap
#: (reference: sql/.../planner/PlannerConfig.java maxSemiJoinRowsInMemory)
MAX_SEMIJOIN_ROWS = 100_000

#: expression AST node types the semi-join rewriter walks
_AST_NODES = (P.Fn, P.Bin, P.Un, P.InExpr, P.LikeExpr, P.BetweenExpr,
              P.IsNullExpr, P.Case, P.Cast, P.SelectItem, P.Lit, P.Col)


class SqlExecutor:
    """Plans SQL against the live segment schema and runs it on a
    QueryExecutor (or any object with .run(query) and .datasources /
    .segments_of)."""

    def __init__(self, query_executor, schema_ttl: float = 30.0,
                 min_refresh_interval: float = 1.0):
        self.qe = query_executor
        self.schema_ttl = schema_ttl
        #: floor between unknown-table-triggered rebuilds — a client
        #: looping on a typo'd table must not reduce the TTL to zero and
        #: hammer historicals with segmentMetadata scatters
        self.min_refresh_interval = min_refresh_interval
        self._schema_cache = None   # (expiry monotonic, SqlSchema)
        self._last_build = 0.0

    # ---- schema discovery (DruidSchema analog) ------------------------
    def schema(self) -> SqlSchema:
        """TTL-cached: remote-broker discovery costs a segmentMetadata
        scatter per datasource; the reference's DruidSchema likewise
        refreshes on a period, not per statement. invalidate_schema()
        forces the next call to rebuild."""
        cached = self._schema_cache
        if cached is not None and time.monotonic() < cached[0]:
            return cached[1]
        schema = self._build_schema()
        self._schema_cache = (time.monotonic() + self.schema_ttl, schema)
        self._last_build = time.monotonic()
        return schema

    def invalidate_schema(self) -> None:
        self._schema_cache = None

    def _plan(self, sel):
        """Plan with one invalidate-and-retry on an unknown table — a
        datasource announced since the last schema refresh must be
        queryable immediately, not after the TTL."""
        try:
            return plan_sql(sel, self.schema())
        except PlannerError as e:
            if "unknown table" in str(e) \
                    and self._schema_cache is not None \
                    and time.monotonic() - self._last_build \
                    >= self.min_refresh_interval:
                self.invalidate_schema()
                return plan_sql(sel, self.schema())
            raise

    def _build_schema(self) -> SqlSchema:
        tables: Dict[str, Dict[str, str]] = {}
        for ds in self.qe.datasources:
            cols: Dict[str, str] = {}
            for seg in self.qe.segments_of(ds):
                for d in seg.dims:
                    cols.setdefault(d, "string")
                for m, col in seg.metrics.items():
                    t = col.type.value if hasattr(col.type, "value") else str(col.type)
                    cols.setdefault(m, t)
            if not cols:
                # no local segment objects (broker over REMOTE nodes):
                # discover via a merged segmentMetadata query — exactly the
                # reference's DruidSchema refresh
                cols = self._metadata_schema(ds)
            tables[ds] = cols
        return SqlSchema(tables)

    def _metadata_schema(self, datasource: str) -> Dict[str, str]:
        from druid_tpu.query.model import SegmentMetadataQuery
        try:
            rows = self.qe.run(SegmentMetadataQuery.of(
                datasource, merge=True, analysis_types=()))
        except Exception:
            # schema stays numeric-default; queries still parse
            logging.getLogger(__name__).debug(
                "segment metadata scan for [%s] failed", datasource,
                exc_info=True)
            return {}
        out: Dict[str, str] = {}
        for analysis in rows:
            for name, info in (analysis.get("columns") or {}).items():
                if name == "__time":
                    continue
                t = str(info.get("type", "STRING")).lower()
                out.setdefault(
                    name, t if t in ("string", "long", "float", "double")
                    else "string")
        return out

    # ---- IN (SELECT ...) materialization (DruidSemiJoin analog) -------
    def _expand_select(self, sel: Select, depth: int = 0) -> Select:
        """Replace every `IN (SELECT ...)` in WHERE/HAVING (and the nested
        FROM subquery) with the inner query's materialized value list."""
        def on_in(node):
            vals, had_null = self._materialize_semijoin(node.subquery, depth)
            if node.negated and had_null:
                # three-valued logic: `x NOT IN (..., NULL)` is never true
                return P.Lit(False, "bool")
            return P.InExpr(_map_expr(node.operand, on_in), vals,
                            node.negated)

        return _map_select(
            sel, on_where=on_in, on_other=_reject_in,
            on_subselect=lambda s: self._expand_select(s, depth))

    def _materialize_semijoin(self, sub: Select, depth: int
                              ) -> Tuple[Tuple[P.Lit, ...], bool]:
        """(literal values, whether the inner result contained NULL)."""
        if depth >= 3:
            raise PlannerError("IN subqueries nested too deeply (max 3)")
        names, rows = self._execute_select(sub, depth + 1)
        if len(names) != 1:
            raise PlannerError(
                f"IN subquery must select exactly one column, got {names}")
        if len(rows) > MAX_SEMIJOIN_ROWS:
            raise PlannerError(
                f"IN subquery returned {len(rows)} rows "
                f"(max {MAX_SEMIJOIN_ROWS})")
        vals, had_null = [], False
        for r in rows:
            v = r[0]
            if v is None:
                had_null = True   # NULL never matches `=`
                continue
            t = "string" if isinstance(v, str) else \
                "double" if isinstance(v, float) else "long"
            vals.append(P.Lit(v, t))
        return tuple(vals), had_null

    # ---- entry points --------------------------------------------------
    def explain(self, sql: str, parameters: Sequence[object] = ()) -> dict:
        stmt = parse_sql(sql, parameters)
        if isinstance(stmt, Union):
            return {"queryType": "unionAll",
                    "arms": [self._explain_select(a) for a in stmt.arms]}
        return self._explain_select(stmt)

    def _explain_select(self, sel: Select) -> dict:
        """EXPLAIN never executes IN-subqueries (the reference's explain
        surface is plan-only): each is planned separately and listed under
        `semiJoinSubPlans`, with an empty IN standing in on the outer plan."""
        sub_plans: List[dict] = []
        sel = self._stub_semijoins(sel, sub_plans)
        planned = self._plan(sel)
        if planned.native is None:
            out = {"queryType": "metadata", "table": planned.meta_table}
        else:
            out = planned.native.to_json()
        if sub_plans:
            out = dict(out)
            out["semiJoinSubPlans"] = sub_plans
        return out

    def _stub_semijoins(self, sel: Select, sub_plans: List[dict]) -> Select:
        def on_in(node):
            sub_plans.append(self._explain_select(node.subquery))
            return P.InExpr(node.operand, (), node.negated)

        return _map_select(
            sel, on_where=on_in, on_other=_reject_in,
            on_subselect=lambda s: self._stub_semijoins(s, sub_plans))

    def execute(self, sql: str, parameters: Sequence[object] = (),
                context: Optional[Dict] = None
                ) -> Tuple[List[str], List[list]]:
        """Returns (column names, rows as lists) — the SQL resource's
        array-result format. `context` (the SQL payload's "context"
        object, reference SqlQuery.context) merges into the planned
        native query's context: queryId, timeout, allowPartialResults
        and the other data-plane flags reach the broker. Semi-join
        INNER subqueries deliberately do NOT inherit it — a silently
        partial inner row set would corrupt the outer result, exactly
        the failure mode allowPartialResults must never cause."""
        stmt = parse_sql(sql, parameters)
        if stmt.explain:
            import json as _json
            planned_json = self.explain(_strip_explain(sql), parameters)
            return (["PLAN"], [[_json.dumps(planned_json, sort_keys=True)]])
        if isinstance(stmt, Union):
            return self._execute_union(stmt, context)
        return self._execute_select(stmt, 0, context)

    def _execute_select(self, sel: Select, depth: int,
                        context: Optional[Dict] = None
                        ) -> Tuple[List[str], List[list]]:
        planned = self._plan(self._expand_select(sel, depth))
        if planned.meta_table is not None:
            return self._run_meta(planned)
        native = planned.native
        if context:
            from dataclasses import replace as _replace
            native = _replace(native, context=tuple(sorted(
                {**native.context_map, **dict(context)}.items())))
        rows = self.qe.run(native)
        cols, shaped = self._shape(planned, rows)
        missing = getattr(rows, "missing_segments", None)
        if missing is not None:
            # a degraded native result (allowPartialResults) stays typed
            # through SQL shaping: the report must reach the SQL client,
            # never vanish into an ordinary row list
            from druid_tpu.cluster.resilience import PartialResult
            shaped = PartialResult(shaped, missing)
        return cols, shaped

    def _execute_union(self, un: Union,
                       context: Optional[Dict] = None
                       ) -> Tuple[List[str], List[list]]:
        """Arms execute independently and concatenate; union-level ORDER
        BY/LIMIT apply to the combined rows; column names come from the
        first arm (reference: DruidUnionRel)."""
        names: Optional[List[str]] = None
        rows: List[list] = []
        missing: List[str] = []
        for arm in un.arms:
            cols, arm_rows = self._execute_select(arm, 0, context)
            if names is None:
                names = cols
            elif len(cols) != len(names):
                raise PlannerError(
                    "UNION ALL arms must select the same number of columns "
                    f"({len(names)} vs {len(cols)})")
            rows.extend(arm_rows)
            missing.extend(getattr(arm_rows, "missing_segments", ()))
        for oi in reversed(un.order_by):
            ix = self._union_order_index(oi, names)
            rows.sort(key=lambda r: _order_key(r[ix]),
                      reverse=oi.descending)
        if un.limit is not None or un.offset:
            rows = rows[un.offset:
                        un.offset + un.limit if un.limit is not None
                        else None]
        if missing:
            # one arm degrading degrades the union — typed, with the
            # combined report
            from druid_tpu.cluster.resilience import PartialResult
            rows = PartialResult(rows, missing)
        return names, rows

    @staticmethod
    def _union_order_index(oi, names: List[str]) -> int:
        e = oi.expr
        if isinstance(e, P.Col) and e.name in names:
            return names.index(e.name)
        if isinstance(e, P.Lit) and isinstance(e.value, int) \
                and 1 <= e.value <= len(names):
            return e.value - 1
        raise PlannerError(
            "UNION ALL ORDER BY must name an output column or ordinal")

    def tables_of(self, sql: str, parameters: Sequence[object] = ()
                  ) -> Tuple[List[str], bool]:
        """(datasources a statement reads, is_information_schema) — the
        authorization surface (reference: SqlResource resource-action
        collection before execution). Purely syntactic: authorization must
        not execute subqueries."""
        stmt = parse_sql(sql, parameters)
        tables: set = set()
        meta = [False]
        arms = stmt.arms if isinstance(stmt, Union) else (stmt,)
        for arm in arms:
            _collect_tables(arm, tables, meta)
        return sorted(tables), meta[0]

    def execute_dicts(self, sql: str, parameters: Sequence[object] = (),
                      context: Optional[Dict] = None
                      ) -> List[dict]:
        cols, rows = self.execute(sql, parameters, context)
        return [dict(zip(cols, r)) for r in rows]

    # ---- result shaping (QueryMaker analog) ---------------------------
    def _shape(self, planned: PlannedQuery, rows) -> Tuple[List[str], List[list]]:
        q = planned.native
        outs = planned.outputs
        names = [o.alias for o in outs]
        table: List[list] = []
        if isinstance(q, TimeseriesQuery):
            # executor-side ORDER BY (non-time orderings of bucket rows);
            # sorts the native rows so non-projected order fields work too
            for fname, desc in reversed(planned.sort_in_executor):
                rows = sorted(rows, key=lambda r, f=fname:
                              (r["result"].get(f) is None,
                               r["result"].get(f) or 0), reverse=desc)
            for r in rows:
                table.append(_emit(outs, r["result"], r["timestamp"]))
            if not table and not q.skip_empty_buckets \
                    and q.granularity.is_all:
                # scalar aggregate whose time bound pruned every segment:
                # still one row of aggregate identities, consistent with the
                # engine's covered-but-empty bucket (COUNT()=0, SUM()=0)
                table.append(_emit(outs, _empty_agg_row(q), None))
        elif isinstance(q, TopNQuery):
            for r in rows:
                for entry in r["result"]:
                    table.append(_emit(outs, entry, r["timestamp"]))
        elif isinstance(q, GroupByQuery):
            for r in rows:
                table.append(_emit(outs, r["event"], r["timestamp"]))
        elif isinstance(q, TimeBoundaryQuery):
            for r in rows:
                table.append([_iso(r["result"].get(o.key)) for o in outs])
        elif isinstance(q, ScanQuery):
            for batch in rows:
                for ev in batch["events"]:
                    table.append(_emit(outs, ev, ev.get("__time")))
        else:
            raise PlannerError(f"cannot shape {type(q).__name__} results")
        if planned.limit_in_executor is not None or planned.offset_in_executor:
            off = planned.offset_in_executor
            lim = planned.limit_in_executor
            table = table[off:off + lim if lim is not None else None]
        return names, table

    # ---- INFORMATION_SCHEMA -------------------------------------------
    def _run_meta(self, planned: PlannedQuery) -> Tuple[List[str], List[list]]:
        sel = planned.meta_select
        schema = self.schema()
        if planned.meta_table == "SCHEMATA":
            data = [{"CATALOG_NAME": "druid", "SCHEMA_NAME": s}
                    for s in ("druid", "INFORMATION_SCHEMA")]
        elif planned.meta_table == "TABLES":
            data = [{"TABLE_CATALOG": "druid", "TABLE_SCHEMA": "druid",
                     "TABLE_NAME": t, "TABLE_TYPE": "TABLE"}
                    for t in sorted(schema.tables)]
        elif planned.meta_table == "COLUMNS":
            data = []
            for t in sorted(schema.tables):
                cols = [("__time", "TIMESTAMP")] + sorted(
                    (c, _sql_type(ty)) for c, ty in schema.tables[t].items())
                for i, (c, ty) in enumerate(cols):
                    data.append({"TABLE_CATALOG": "druid",
                                 "TABLE_SCHEMA": "druid", "TABLE_NAME": t,
                                 "COLUMN_NAME": c, "ORDINAL_POSITION": i + 1,
                                 "DATA_TYPE": ty,
                                 "IS_NULLABLE": "YES" if ty == "VARCHAR" else "NO"})
        else:
            raise PlannerError(
                f"unknown INFORMATION_SCHEMA table [{planned.meta_table}]")
        return _meta_select(sel, data)


def _map_expr(node, on_in):
    """Bottom-up expression-AST rewrite; `on_in` handles (and replaces)
    every `IN (SELECT ...)` node. The single walker behind semi-join
    expansion, EXPLAIN stubbing and table collection."""
    import dataclasses
    if isinstance(node, P.InExpr) and node.subquery is not None:
        return on_in(node)
    if isinstance(node, _AST_NODES):
        changes = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, _AST_NODES):
                nv = _map_expr(v, on_in)
            elif isinstance(v, tuple):
                nv = tuple(tuple(_map_expr(y, on_in) for y in x)
                           if isinstance(x, tuple) else _map_expr(x, on_in)
                           for x in v)
                if nv == v:
                    continue
            else:
                continue
            if nv is not v:
                changes[f.name] = nv
        return dataclasses.replace(node, **changes) if changes else node
    return node


def _map_select(sel: Select, on_where, on_other, on_subselect) -> Select:
    """Map every expression position of ONE Select: `on_where` handles
    IN-subqueries in WHERE, `on_other` those in select items / GROUP BY /
    HAVING / ORDER BY, `on_subselect` the nested FROM subquery."""
    import dataclasses
    changes = {}
    if sel.subquery is not None:
        sub = on_subselect(sel.subquery)
        if sub is not sel.subquery:
            changes["subquery"] = sub
    if sel.where is not None:
        ne = _map_expr(sel.where, on_where)
        if ne is not sel.where:
            changes["where"] = ne
    if sel.having is not None:
        ne = _map_expr(sel.having, on_other)
        if ne is not sel.having:
            changes["having"] = ne
    items = tuple(_map_expr(it, on_other) for it in sel.items)
    if items != sel.items:
        changes["items"] = items
    gb = tuple(_map_expr(e, on_other) for e in sel.group_by)
    if gb != sel.group_by:
        changes["group_by"] = gb
    ob = []
    for o in sel.order_by:
        ne = _map_expr(o.expr, on_other)
        ob.append(dataclasses.replace(o, expr=ne) if ne is not o.expr else o)
    if tuple(ob) != sel.order_by:
        changes["order_by"] = tuple(ob)
    return dataclasses.replace(sel, **changes) if changes else sel


def _reject_in(node):
    raise PlannerError(
        "IN (SELECT ...) is only supported in WHERE — not in select items, "
        "GROUP BY, HAVING or ORDER BY")


def _empty_agg_row(q) -> dict:
    """Aggregate identities for a zero-row scalar result — the SAME
    kernel empty states the engine emits for a covered-but-empty bucket
    (engines.finish_timeseries empty_defaults), so both zero-row paths
    agree for every aggregator type."""
    from druid_tpu.cluster.wire import rebuild_kernels
    kernels = rebuild_kernels([a.to_json() for a in q.aggregations])
    fields = {}
    for k in kernels:
        v = k.finalize_array(k.empty_state(1))[0]
        fields[k.spec.name] = v.item() if hasattr(v, "item") else v
    for pa in q.post_aggregations:
        try:
            fields[pa.name] = pa.compute(fields)
        except Exception:
            # SQL NULL on an uncomputable post-agg (reference behavior)
            logging.getLogger(__name__).debug(
                "post-aggregator [%s] failed on empty-result fields",
                pa.name, exc_info=True)
            fields[pa.name] = None
    return fields


def _order_key(v):
    """Mixed-type sort key for union-level ORDER BY: NULLs first, then
    numbers, then strings."""
    if v is None:
        return (0, 0.0, "")
    if isinstance(v, bool):
        return (1, float(v), "")
    if isinstance(v, (int, float)):
        return (1, float(v), "")
    return (2, 0.0, str(v))


def _collect_tables(sel: Select, out: set, meta: List[bool]) -> None:
    """Syntactic datasource collection over FROM, nested FROM subqueries
    and IN-subqueries in EVERY expression position — the authorization
    surface must over-collect, never miss a table."""
    if sel.schema is not None:
        meta[0] = True
    elif sel.subquery is None and sel.table:
        out.add(sel.table)

    def on_in(node):
        _collect_tables(node.subquery, out, meta)
        return node

    def recurse(sub):
        _collect_tables(sub, out, meta)
        return sub

    _map_select(sel, on_where=on_in, on_other=on_in, on_subselect=recurse)


def _strip_explain(sql: str) -> str:
    import re
    return re.sub(r"(?is)^\s*EXPLAIN\s+PLAN\s+FOR\s+", "", sql)


def _sql_type(t: str) -> str:
    return {"string": "VARCHAR", "long": "BIGINT", "float": "FLOAT",
            "double": "DOUBLE"}.get(t, t.upper())


def _iso(v):
    return ts_to_iso(v) if v is not None else None


def _emit(outs: List[OutputColumn], fields: dict, ts) -> list:
    row = []
    for o in outs:
        if o.kind == "time":
            row.append(_iso(ts))
        elif o.kind == "constant":
            row.append(o.constant)
        elif o.kind == "column" and o.key == "__time":
            row.append(_iso(fields.get("__time", ts)))
        else:
            row.append(fields.get(o.key))
    return row


def _meta_select(sel: Select, data: List[dict]) -> Tuple[List[str], List[list]]:
    """Evaluate a (restricted) select over an in-memory metadata table:
    column projections, simple equality/IN where, ORDER BY columns, LIMIT."""
    from druid_tpu.sql import parser as P

    def match(row, e) -> bool:
        if e is None:
            return True
        if isinstance(e, P.Bin) and e.op == "AND":
            return match(row, e.left) and match(row, e.right)
        if isinstance(e, P.Bin) and e.op == "OR":
            return match(row, e.left) or match(row, e.right)
        if isinstance(e, P.Un) and e.op == "NOT":
            return not match(row, e.operand)
        if isinstance(e, P.Bin) and e.op in ("=", "<>"):
            l, r = e.left, e.right
            if isinstance(r, P.Col):
                l, r = r, l
            if isinstance(l, P.Col) and isinstance(r, P.Lit):
                eq = str(row.get(l.name)) == str(r.value)
                return eq if e.op == "=" else not eq
        if isinstance(e, P.InExpr) and isinstance(e.operand, P.Col):
            hit = str(row.get(e.operand.name)) in {str(v.value) for v in e.values}
            return hit != e.negated
        if isinstance(e, P.LikeExpr) and isinstance(e.operand, P.Col):
            import re as _re
            pat = "^" + "".join(
                ".*" if ch == "%" else "." if ch == "_" else _re.escape(ch)
                for ch in str(e.pattern.value)) + "$"
            hit = bool(_re.match(pat, str(row.get(e.operand.name, ""))))
            return hit != e.negated
        raise PlannerError("unsupported WHERE on INFORMATION_SCHEMA")

    rows = [r for r in data if match(r, sel.where)]
    if sel.order_by:
        for ob in reversed(sel.order_by):
            if not isinstance(ob.expr, P.Col):
                raise PlannerError("ORDER BY columns only on INFORMATION_SCHEMA")
            rows.sort(key=lambda r: str(r.get(ob.expr.name)),
                      reverse=ob.descending)
    if sel.limit is not None:
        rows = rows[sel.offset:sel.offset + sel.limit]
    elif sel.offset:
        rows = rows[sel.offset:]

    if len(sel.items) == 1 and isinstance(sel.items[0].expr, P.Star):
        names = keys = list(data[0].keys()) if data else []
    else:
        names, keys = [], []
        for it in sel.items:
            if not isinstance(it.expr, P.Col):
                raise PlannerError("INFORMATION_SCHEMA projections are columns")
            names.append(it.alias or it.expr.name)
            keys.append(it.expr.name)
    return names, [[r.get(k) for k in keys] for r in rows]
