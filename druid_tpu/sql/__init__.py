"""SQL layer: parser → planner → native queries (reference: sql/ module,
Calcite-based DruidPlanner → DruidQuery → native query types).

The TPU build replaces Calcite with a self-contained recursive-descent SQL
parser and a direct planner that picks the native query type exactly like
DruidQuery.toDruidQuery (sql/.../calcite/rel/DruidQuery.java): scan for
non-aggregate selects, timeseries for time-bucketed aggregates, topN for
single-dimension ordered-limited aggregates, groupBy otherwise.
"""
from druid_tpu.sql.executor import SqlExecutor
from druid_tpu.sql.parser import parse_sql
from druid_tpu.sql.planner import PlannerError, plan_sql

__all__ = ["SqlExecutor", "parse_sql", "plan_sql", "PlannerError"]
