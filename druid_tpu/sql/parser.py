"""SQL tokenizer + recursive-descent parser for the SELECT dialect.

Reference analog: Calcite's parser/validator as driven by
sql/src/main/java/org/apache/druid/sql/calcite/planner/DruidPlanner.java.
This is a from-scratch implementation of the subset Druid SQL exercises:
SELECT [DISTINCT] items FROM table [WHERE] [GROUP BY] [HAVING] [ORDER BY]
[LIMIT] [OFFSET], with CASE/CAST/EXTRACT/FLOOR..TO/SUBSTRING/TRIM syntax,
aggregate FILTER (WHERE ...) clauses, COUNT(DISTINCT x), TIMESTAMP/DATE/
INTERVAL literals, and ? parameter placeholders (Avatica-style).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple, Union

# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Lit:
    value: object                 # str | int | float | bool | None
    type: str = "unknown"         # string | long | double | bool | null | timestamp | interval

    def __str__(self):
        return repr(self.value)


@dataclass(frozen=True)
class Col:
    name: str

    def __str__(self):
        return self.name


@dataclass(frozen=True)
class Star:
    def __str__(self):
        return "*"


@dataclass(frozen=True)
class Fn:
    name: str                    # upper-cased
    args: Tuple[object, ...] = ()
    distinct: bool = False
    filter: Optional[object] = None   # FILTER (WHERE <expr>)
    extra: Optional[str] = None       # e.g. FLOOR(x TO DAY) unit, EXTRACT field

    def __str__(self):
        a = ", ".join(str(x) for x in self.args)
        d = "DISTINCT " if self.distinct else ""
        e = f" TO {self.extra}" if self.extra else ""
        return f"{self.name}({d}{a}{e})"


@dataclass(frozen=True)
class Bin:
    op: str
    left: object
    right: object

    def __str__(self):
        return f"({self.left} {self.op} {self.right})"


@dataclass(frozen=True)
class Un:
    op: str                      # NOT | -
    operand: object

    def __str__(self):
        return f"{self.op}({self.operand})"


@dataclass(frozen=True)
class InExpr:
    operand: object
    values: Tuple[object, ...]
    negated: bool = False
    #: `IN (SELECT ...)` semi-join form — the executor materializes the
    #: inner query's single output column into `values` before planning
    #: (reference: sql/.../calcite/rel/DruidSemiJoin.java)
    subquery: Optional["Select"] = None


@dataclass(frozen=True)
class LikeExpr:
    operand: object
    pattern: object
    negated: bool = False


@dataclass(frozen=True)
class BetweenExpr:
    operand: object
    low: object
    high: object
    negated: bool = False


@dataclass(frozen=True)
class IsNullExpr:
    operand: object
    negated: bool = False


@dataclass(frozen=True)
class Case:
    whens: Tuple[Tuple[object, object], ...]
    else_: Optional[object] = None


@dataclass(frozen=True)
class Cast:
    operand: object
    to_type: str


@dataclass(frozen=True)
class SelectItem:
    expr: object
    alias: Optional[str] = None


@dataclass(frozen=True)
class OrderItem:
    expr: object
    descending: bool = False


@dataclass(frozen=True)
class Select:
    items: Tuple[SelectItem, ...]
    table: Optional[str] = None
    schema: Optional[str] = None        # e.g. INFORMATION_SCHEMA
    subquery: Optional["Select"] = None  # FROM (SELECT ...) [alias]
    where: Optional[object] = None
    group_by: Tuple[object, ...] = ()
    having: Optional[object] = None
    order_by: Tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0
    distinct: bool = False
    explain: bool = False


@dataclass(frozen=True)
class Union:
    """`SELECT ... UNION ALL SELECT ... [ORDER BY] [LIMIT] [OFFSET]` — arms
    execute independently and concatenate; ORDER BY/LIMIT bind to the whole
    union (reference: sql/.../calcite/rel/DruidUnionRel.java). Column names
    come from the first arm."""
    arms: Tuple[Select, ...]
    order_by: Tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0
    explain: bool = False


class SqlParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<qid>"(?:[^"]|"")*")
  | (?P<id>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op><>|!=|>=|<=|\|\||[=<>+\-*/%(),.?])
""", re.VERBOSE)

_KEYWORDS = {
    "SELECT", "DISTINCT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER",
    "LIMIT", "OFFSET", "AS", "AND", "OR", "NOT", "IN", "LIKE", "BETWEEN",
    "IS", "NULL", "TRUE", "FALSE", "CASE", "WHEN", "THEN", "ELSE", "END",
    "CAST", "EXTRACT", "ASC", "DESC", "FILTER", "TIMESTAMP", "DATE",
    "INTERVAL", "TO", "FOR", "EXPLAIN", "PLAN", "SUBSTRING", "TRIM",
    "LEADING", "TRAILING", "BOTH", "UNION", "ALL",
}


@dataclass(frozen=True)
class _Tok:
    kind: str      # num | str | id | qid | op | kw | eof
    text: str
    pos: int


def _tokenize(sql: str) -> List[_Tok]:
    out, i = [], 0
    while i < len(sql):
        m = _TOKEN_RE.match(sql, i)
        if not m:
            raise SqlParseError(f"cannot tokenize at {sql[i:i+20]!r}")
        i = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        text = m.group()
        if kind == "id" and text.upper() in _KEYWORDS:
            out.append(_Tok("kw", text.upper(), m.start()))
        else:
            out.append(_Tok(kind, text, m.start()))
    out.append(_Tok("eof", "", len(sql)))
    return out


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

_AGG_FNS = {"COUNT", "SUM", "MIN", "MAX", "AVG", "APPROX_COUNT_DISTINCT",
            "APPROX_QUANTILE", "STDDEV", "STDDEV_POP", "STDDEV_SAMP",
            "VARIANCE", "VAR_POP", "VAR_SAMP", "EARLIEST", "LATEST",
            "DS_THETA", "DS_QUANTILES_SKETCH", "BLOOM_FILTER"}


class _P:
    def __init__(self, tokens: List[_Tok], params: Sequence[object] = ()):
        self.toks = tokens
        self.i = 0
        self.params = list(params)
        self.param_i = 0

    # -- token helpers
    def peek(self, k: int = 0) -> _Tok:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> _Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> Optional[str]:
        t = self.peek()
        if t.kind == "kw" and t.text in kws:
            self.i += 1
            return t.text
        return None

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise SqlParseError(f"expected {kw}, got {self.peek().text!r}")

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.text == op:
            self.i += 1
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SqlParseError(f"expected {op!r}, got {self.peek().text!r}")

    def ident(self) -> str:
        t = self.peek()
        if t.kind == "id":
            self.i += 1
            return t.text
        if t.kind == "qid":
            self.i += 1
            return t.text[1:-1].replace('""', '"')
        # soft keywords usable as identifiers
        if t.kind == "kw" and t.text in ("PLAN", "TIMESTAMP", "DATE", "TO"):
            self.i += 1
            return t.text
        raise SqlParseError(f"expected identifier, got {t.text!r}")

    # -- entry
    def statement(self):
        """Top-level: a Select or a `UNION ALL` chain (Union)."""
        first = self.select(top_level=False)
        if not self.accept_kw("UNION"):
            if self.peek().kind != "eof":
                raise SqlParseError(
                    f"unexpected trailing {self.peek().text!r}")
            return first
        if first.order_by or first.limit is not None or first.offset:
            raise SqlParseError(
                "ORDER BY/LIMIT/OFFSET before UNION ALL bind to the whole "
                "union — move them after the last arm")
        self.expect_kw("ALL")
        arms = [first, self.select(top_level=False, allow_order=False)]
        while self.accept_kw("UNION"):
            self.expect_kw("ALL")
            arms.append(self.select(top_level=False, allow_order=False))
        order_by, limit, offset = self._order_limit_offset()
        if self.peek().kind != "eof":
            raise SqlParseError(f"unexpected trailing {self.peek().text!r}")
        return Union(tuple(arms), tuple(order_by), limit, offset,
                     first.explain)

    def _order_limit_offset(self):
        order_by: List[OrderItem] = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order_by.append(self.order_item())
            while self.accept_op(","):
                order_by.append(self.order_item())
        limit = None
        if self.accept_kw("LIMIT"):
            t = self.next()
            if t.kind != "num":
                raise SqlParseError(f"LIMIT expects a number, got {t.text!r}")
            limit = int(t.text)
        offset = 0
        if self.accept_kw("OFFSET"):
            t = self.next()
            if t.kind != "num":
                raise SqlParseError(f"OFFSET expects a number, got {t.text!r}")
            offset = int(t.text)
        return order_by, limit, offset

    def select(self, top_level: bool = True,
               allow_order: bool = True) -> Select:
        explain = False
        if self.accept_kw("EXPLAIN"):
            self.expect_kw("PLAN")
            self.expect_kw("FOR")
            explain = True
        self.expect_kw("SELECT")
        distinct = bool(self.accept_kw("DISTINCT"))
        items = [self.select_item()]
        while self.accept_op(","):
            items.append(self.select_item())
        table = schema = None
        subquery = None
        if self.accept_kw("FROM"):
            if self.accept_op("("):
                # FROM (SELECT ...) [alias] — nested query datasource
                subquery = self.select(top_level=False)
                self.expect_op(")")
                if self.peek().kind in ("id", "qid") or \
                        (self.peek().kind == "kw"
                         and self.peek().text == "AS"):
                    self.accept_kw("AS")
                    self.ident()   # alias accepted, unused (one subquery)
                table = "__subquery__"
            else:
                name = self.ident()
                if self.accept_op("."):
                    schema, table = name, self.ident()
                else:
                    table = name
        where = self.expr() if self.accept_kw("WHERE") else None
        group_by: List[object] = []
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            group_by.append(self.expr())
            while self.accept_op(","):
                group_by.append(self.expr())
        having = self.expr() if self.accept_kw("HAVING") else None
        if allow_order:
            order_by, limit, offset = self._order_limit_offset()
        else:
            order_by, limit, offset = [], None, 0
        if top_level and self.peek().kind != "eof":
            raise SqlParseError(f"unexpected trailing {self.peek().text!r}")
        return Select(tuple(items), table, schema, subquery, where,
                      tuple(group_by), having, tuple(order_by), limit,
                      offset, distinct, explain)

    def select_item(self) -> SelectItem:
        if self.peek().kind == "op" and self.peek().text == "*":
            self.next()
            return SelectItem(Star())
        e = self.expr()
        alias = None
        if self.accept_kw("AS"):
            alias = self.ident()
        elif self.peek().kind in ("id", "qid"):
            alias = self.ident()
        return SelectItem(e, alias)

    def order_item(self) -> OrderItem:
        e = self.expr()
        desc = False
        if self.accept_kw("DESC"):
            desc = True
        else:
            self.accept_kw("ASC")
        return OrderItem(e, desc)

    # -- expression precedence climb
    def expr(self) -> object:
        return self.or_expr()

    def or_expr(self) -> object:
        left = self.and_expr()
        while self.accept_kw("OR"):
            left = Bin("OR", left, self.and_expr())
        return left

    def and_expr(self) -> object:
        left = self.not_expr()
        while self.accept_kw("AND"):
            left = Bin("AND", left, self.not_expr())
        return left

    def not_expr(self) -> object:
        if self.accept_kw("NOT"):
            return Un("NOT", self.not_expr())
        return self.comparison()

    def comparison(self) -> object:
        left = self.additive()
        t = self.peek()
        if t.kind == "op" and t.text in ("=", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            op = "<>" if t.text == "!=" else t.text
            return Bin(op, left, self.additive())
        if t.kind == "kw" and t.text == "IS":
            self.next()
            neg = bool(self.accept_kw("NOT"))
            self.expect_kw("NULL")
            return IsNullExpr(left, neg)
        neg = bool(self.accept_kw("NOT"))
        if self.accept_kw("IN"):
            self.expect_op("(")
            if self.peek().kind == "kw" and self.peek().text == "SELECT":
                sub = self.select(top_level=False)
                self.expect_op(")")
                return InExpr(left, (), neg, sub)
            vals = [self.expr()]
            while self.accept_op(","):
                vals.append(self.expr())
            self.expect_op(")")
            return InExpr(left, tuple(vals), neg)
        if self.accept_kw("LIKE"):
            return LikeExpr(left, self.additive(), neg)
        if self.accept_kw("BETWEEN"):
            low = self.additive()
            self.expect_kw("AND")
            return BetweenExpr(left, low, self.additive(), neg)
        if neg:
            raise SqlParseError("NOT must precede IN/LIKE/BETWEEN here")
        return left

    def additive(self) -> object:
        left = self.multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in ("+", "-", "||"):
                self.next()
                left = Bin(t.text, left, self.multiplicative())
            else:
                return left

    def multiplicative(self) -> object:
        left = self.unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in ("*", "/", "%"):
                self.next()
                left = Bin(t.text, left, self.unary())
            else:
                return left

    def unary(self) -> object:
        if self.accept_op("-"):
            operand = self.unary()
            if isinstance(operand, Lit) and operand.type in ("long", "double"):
                return Lit(-operand.value, operand.type)
            return Un("-", operand)
        self.accept_op("+")
        return self.primary()

    def primary(self) -> object:
        t = self.peek()
        if t.kind == "num":
            self.next()
            if re.search(r"[.eE]", t.text):
                return Lit(float(t.text), "double")
            return Lit(int(t.text), "long")
        if t.kind == "str":
            self.next()
            return Lit(t.text[1:-1].replace("''", "'"), "string")
        if t.kind == "op" and t.text == "?":
            self.next()
            if self.param_i >= len(self.params):
                raise SqlParseError("not enough parameters for ? placeholders")
            v = self.params[self.param_i]
            self.param_i += 1
            if v is None:
                return Lit(None, "null")
            if isinstance(v, bool):
                return Lit(v, "bool")
            if isinstance(v, int):
                return Lit(v, "long")
            if isinstance(v, float):
                return Lit(v, "double")
            return Lit(str(v), "string")
        if t.kind == "op" and t.text == "(":
            self.next()
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind == "kw":
            return self.kw_primary(t)
        if t.kind in ("id", "qid"):
            name = self.ident()
            if self.accept_op("("):
                return self.call(name.upper())
            return Col(name)
        raise SqlParseError(f"unexpected {t.text!r}")

    def kw_primary(self, t: _Tok) -> object:
        if self.accept_kw("TRUE"):
            return Lit(True, "bool")
        if self.accept_kw("FALSE"):
            return Lit(False, "bool")
        if self.accept_kw("NULL"):
            return Lit(None, "null")
        if self.accept_kw("TIMESTAMP"):
            s = self.next()
            if s.kind != "str":
                raise SqlParseError("expected string after TIMESTAMP")
            return Lit(s.text[1:-1], "timestamp")
        if self.accept_kw("DATE"):
            s = self.next()
            if s.kind != "str":
                raise SqlParseError("expected string after DATE")
            return Lit(s.text[1:-1], "timestamp")
        if self.accept_kw("INTERVAL"):
            s = self.next()
            if s.kind != "str":
                raise SqlParseError("expected string after INTERVAL")
            unit = self.ident().upper()
            return Lit((s.text[1:-1], unit), "interval")
        if self.accept_kw("CASE"):
            whens = []
            while self.accept_kw("WHEN"):
                c = self.expr()
                self.expect_kw("THEN")
                whens.append((c, self.expr()))
            else_ = self.expr() if self.accept_kw("ELSE") else None
            self.expect_kw("END")
            return Case(tuple(whens), else_)
        if self.accept_kw("CAST"):
            self.expect_op("(")
            e = self.expr()
            self.expect_kw("AS")
            ty = self.ident().upper()
            self.expect_op(")")
            return Cast(e, ty)
        if self.accept_kw("EXTRACT"):
            self.expect_op("(")
            unit = self.ident().upper()
            # FROM is not a soft keyword here
            if not (self.peek().kind == "kw" and self.peek().text == "FROM"):
                raise SqlParseError("expected FROM in EXTRACT")
            self.next()
            e = self.expr()
            self.expect_op(")")
            return Fn("EXTRACT", (e,), extra=unit)
        if self.accept_kw("SUBSTRING"):
            self.expect_op("(")
            e = self.expr()
            if self.accept_op(","):
                start = self.expr()
                length = self.expr() if self.accept_op(",") else None
            elif self.peek().kind == "kw" and self.peek().text == "FROM":
                self.next()
                start = self.expr()
                length = self.expr() if self.accept_kw("FOR") else None
            else:
                raise SqlParseError("malformed SUBSTRING")
            self.expect_op(")")
            args = (e, start) if length is None else (e, start, length)
            return Fn("SUBSTRING", args)
        if self.accept_kw("TRIM"):
            self.expect_op("(")
            self.accept_kw("LEADING") or self.accept_kw("TRAILING") \
                or self.accept_kw("BOTH")
            e = self.expr()
            self.expect_op(")")
            return Fn("TRIM", (e,))
        raise SqlParseError(f"unexpected keyword {t.text!r}")

    def call(self, name: str) -> Fn:
        distinct = False
        args: Tuple[object, ...] = ()
        extra = None
        if self.peek().kind == "op" and self.peek().text == "*" \
                and name == "COUNT":
            self.next()
            self.expect_op(")")
        elif self.accept_op(")"):
            pass
        else:
            distinct = bool(self.accept_kw("DISTINCT"))
            arglist = [self.expr()]
            # FLOOR(x TO DAY) / CEIL(x TO DAY)
            if name in ("FLOOR", "CEIL") and self.accept_kw("TO"):
                extra = self.ident().upper()
            while self.accept_op(","):
                arglist.append(self.expr())
            self.expect_op(")")
            args = tuple(arglist)
        flt = None
        if name in _AGG_FNS and self.accept_kw("FILTER"):
            self.expect_op("(")
            self.expect_kw("WHERE")
            flt = self.expr()
            self.expect_op(")")
        return Fn(name, args, distinct, flt, extra)


def parse_sql(sql: str, parameters: Sequence[object] = ()):
    """Parse one statement → Select, or Union for `UNION ALL` chains."""
    return _P(_tokenize(sql), parameters).statement()
