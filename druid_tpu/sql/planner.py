"""SQL → native query planner.

Reference analog: sql/src/main/java/org/apache/druid/sql/calcite/rel/
DruidQuery.java (1054 LoC — decides scan | timeseries | topN | groupBy from
the rel tree) plus Expressions.java (SQL operator → Druid expression /
filter translation) and Aggregations.java (SQL aggregate → AggregatorFactory).

Planning is type-directed by a SqlSchema (table → column types), the analog
of DruidSchema's segmentMetadata-driven table discovery.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from druid_tpu.query import aggregators as A
from druid_tpu.query import filters as F
from druid_tpu.query import postaggs as PA
from druid_tpu.query.model import (DefaultDimensionSpec, DefaultLimitSpec,
                                   DimensionSpec, EqualToHaving,
                                   ExpressionDimensionSpec,
                                   ExpressionVirtualColumn,
                                   ExtractionDimensionSpec, FilterHaving,
                                   GreaterThanHaving, GroupByQuery, HavingSpec,
                                   LessThanHaving, LowerExtractionFn,
                                   OrderByColumnSpec, Query,
                                   RegisteredLookupExtractionFn, ScanQuery,
                                   AndHaving, OrHaving, NotHaving,
                                   SubstringExtractionFn, TimeBoundaryQuery,
                                   TimeseriesQuery, TopNQuery,
                                   UpperExtractionFn)
from druid_tpu.sql import parser as P
from druid_tpu.utils.intervals import (ETERNITY_END, ETERNITY_START, Interval,
                                       parse_ts, ts_to_iso)

TIME_COL = "__time"
TOPN_MAX_THRESHOLD = 1000

_FLOOR_UNITS = {"SECOND": "second", "MINUTE": "minute", "HOUR": "hour",
                "DAY": "day", "WEEK": "week", "MONTH": "month",
                "QUARTER": "quarter", "YEAR": "year"}


class PlannerError(ValueError):
    pass


@dataclass
class OutputColumn:
    """How one SQL projection maps onto the native result row."""
    alias: str
    kind: str          # "time" | "dim" | "value" | "column" | "constant"
    key: str = ""      # native field name (dim output / agg / postagg / col)
    constant: object = None


@dataclass
class PlannedQuery:
    native: Optional[Query]
    outputs: List[OutputColumn]
    # meta-queries (INFORMATION_SCHEMA) are answered by the executor
    meta_table: Optional[str] = None
    meta_select: Optional[P.Select] = None
    sort_in_executor: List[Tuple[str, bool]] = field(default_factory=list)
    limit_in_executor: Optional[int] = None
    offset_in_executor: int = 0


# ---------------------------------------------------------------------------
# Schema
# ---------------------------------------------------------------------------

class SqlSchema:
    """table → {column: type}; types: string | long | float | double.
    The reference discovers this via segmentMetadata queries
    (sql/.../calcite/schema/DruidSchema.java); here the executor feeds it
    from live segments."""

    def __init__(self, tables: Optional[Dict[str, Dict[str, str]]] = None):
        self.tables = dict(tables or {})

    def columns(self, table: str) -> Dict[str, str]:
        if table not in self.tables:
            raise PlannerError(f"unknown table [{table}]")
        return self.tables[table]

    def type_of(self, table: str, col: str) -> Optional[str]:
        if col == TIME_COL:
            return "long"
        return self.columns(table).get(col)


# ---------------------------------------------------------------------------
# Expression → Druid expression string (druid_tpu/utils/expression.py syntax)
# ---------------------------------------------------------------------------

_SQL_TO_EXPR_OP = {"=": "==", "<>": "!=", "<": "<", "<=": "<=", ">": ">",
                   ">=": ">=", "AND": "&&", "OR": "||", "+": "+", "-": "-",
                   "*": "*", "/": "/", "%": "%"}

_SQL_FN_TO_EXPR = {"ABS": "abs", "CEIL": "ceil", "FLOOR": "floor",
                   "EXP": "exp", "LN": "log", "LOG10": "log10",
                   "SQRT": "sqrt", "SIN": "sin", "COS": "cos", "TAN": "tan",
                   "POWER": "pow", "POW": "pow", "COALESCE": "nvl",
                   "NVL": "nvl", "MOD": "mod", "ROUND": "round",
                   "SIGN": "sign", "TRUNCATE": "trunc", "TRUNC": "trunc",
                   "GREATEST": "greatest", "LEAST": "least",
                   "SAFE_DIVIDE": "safe_divide",
                   "ASIN": "asin", "ACOS": "acos", "ATAN": "atan",
                   "ATAN2": "atan2", "COT": "cot", "DEGREES": "degrees",
                   "RADIANS": "radians", "PI": "pi",
                   # string→numeric fns: per-dictionary-value LUT gathers
                   # (utils.expression._STR_NUM_FNS)
                   "CHAR_LENGTH": "strlen", "LENGTH": "strlen",
                   "STRLEN": "strlen"}


_UNIT_MS = {"SECOND": 1000, "MINUTE": 60_000, "HOUR": 3_600_000,
            "DAY": 86_400_000, "WEEK": 7 * 86_400_000}
#: ISO weeks are Monday-aligned; epoch day 0 is a Thursday
_WEEK_ORIGIN_MS = -3 * 86_400_000

def _check_extract_unit(unit: str) -> None:
    from druid_tpu.utils.expression import EXTRACT_UNITS
    if unit not in EXTRACT_UNITS:
        raise PlannerError(
            f"EXTRACT unit {unit!r} not supported "
            f"(supported: {', '.join(sorted(EXTRACT_UNITS))})")


def _period_literal_ms(e) -> Tuple[int, int]:
    """(period_ms, origin_ms) for a UNIFORM ISO period literal. Calendar
    periods (months/years) are non-uniform in millis and reject — an
    approximation here would return silently wrong buckets (those belong
    in the GROUP BY granularity path). Week periods align to ISO Mondays."""
    from druid_tpu.utils.intervals import parse_period_ms
    if not isinstance(e, P.Lit):
        raise PlannerError("period argument must be a literal")
    s = str(e.value).strip().upper()
    # months appear before any T section; minutes only after it
    if re.search(r"\d+Y", s) or re.match(r"^P[^T]*?\d+M", s):
        raise PlannerError(
            f"calendar period {e.value!r} is non-uniform in millis; use "
            f"FLOOR(__time TO ...) in GROUP BY for month/year bucketing")
    ms = parse_period_ms(e.value)
    origin = _WEEK_ORIGIN_MS if re.match(r"^P\d+W$", s) else 0
    return ms, origin


def _expr_str(e, table: str, schema: SqlSchema) -> str:
    """Render a SQL AST node as a Druid expression-language string."""
    if isinstance(e, P.Lit):
        if e.type == "string":
            return "'" + str(e.value).replace("\\", "\\\\").replace("'", "\\'") + "'"
        if e.type == "timestamp":
            return str(parse_ts(e.value))
        if e.type == "bool":
            return "1" if e.value else "0"
        if e.value is None:
            return "''"
        return repr(e.value)
    if isinstance(e, P.Col):
        return e.name
    if isinstance(e, P.Bin):
        op = _SQL_TO_EXPR_OP.get(e.op)
        if op is None:
            raise PlannerError(f"operator {e.op!r} not translatable")
        return f"({_expr_str(e.left, table, schema)} {op} {_expr_str(e.right, table, schema)})"
    if isinstance(e, P.Un):
        if e.op == "-":
            return f"(0 - {_expr_str(e.operand, table, schema)})"
        return f"(1 - ({_expr_str(e.operand, table, schema)}))"  # NOT
    if isinstance(e, P.Case):
        out = None
        for cond, val in reversed(e.whens):
            tail = _expr_str(e.else_, table, schema) if out is None and e.else_ is not None \
                else (out if out is not None else "0")
            out = f"if({_expr_str(cond, table, schema)}, {_expr_str(val, table, schema)}, {tail})"
        return out or "0"
    if isinstance(e, P.Cast):
        return f"cast({_expr_str(e.operand, table, schema)}, '{e.to_type}')"
    if isinstance(e, P.BetweenExpr):
        lo = _expr_str(e.low, table, schema)
        hi = _expr_str(e.high, table, schema)
        x = _expr_str(e.operand, table, schema)
        s = f"(({x} >= {lo}) && ({x} <= {hi}))"
        return f"(1 - {s})" if e.negated else s
    if isinstance(e, P.Fn):
        if e.extra is not None:
            unit = str(e.extra).upper()
            x = _expr_str(e.args[0], table, schema)
            if e.name == "EXTRACT":
                _check_extract_unit(unit)
                return f"timestamp_extract({x}, '{unit}')"
            if e.name in ("FLOOR", "CEIL") and unit in _UNIT_MS:
                period = _UNIT_MS[unit]
                origin = _WEEK_ORIGIN_MS if unit == "WEEK" else 0
                if e.name == "FLOOR":
                    return f"timestamp_floor({x}, {period}, {origin})"
                return (f"timestamp_floor(({x}) + {period - 1}, {period}, "
                        f"{origin})")
            # calendar (month/year) floors are non-uniform in millis; only
            # the GROUP BY granularity path understands those
            raise PlannerError(
                f"{e.name}(... TO {e.extra}) not expressible in millis "
                f"arithmetic (use it in GROUP BY)")
        if e.name == "TIME_FLOOR":
            if len(e.args) != 2:
                # origin/timezone arguments would be silently dropped —
                # reject rather than return offset buckets
                raise PlannerError(
                    "TIME_FLOOR(expr, period) supports exactly 2 arguments")
            x = _expr_str(e.args[0], table, schema)
            period, origin = _period_literal_ms(e.args[1])
            return f"timestamp_floor({x}, {period}, {origin})"
        if e.name == "TIME_SHIFT" and len(e.args) == 3:
            x = _expr_str(e.args[0], table, schema)
            period, _ = _period_literal_ms(e.args[1])
            n = _expr_str(e.args[2], table, schema)
            return f"timestamp_shift({x}, {period}, {n})"
        if e.name == "TIME_EXTRACT" and len(e.args) == 2 \
                and isinstance(e.args[1], P.Lit):
            x = _expr_str(e.args[0], table, schema)
            unit = str(e.args[1].value).upper()
            _check_extract_unit(unit)
            return f"timestamp_extract({x}, '{unit}')"
        if e.name in ("TIMESTAMP_TO_MILLIS", "MILLIS_TO_TIMESTAMP") \
                and len(e.args) == 1:
            return _expr_str(e.args[0], table, schema)   # millis both ways
        if e.name in ("TIMESTAMPADD", "TIMESTAMPDIFF") and len(e.args) == 3:
            u = e.args[0]
            unit = (u.name if isinstance(u, P.Col)
                    else str(getattr(u, "value", u))).upper()
            period = _UNIT_MS.get(unit)
            if period is None:
                raise PlannerError(
                    f"{e.name} supports uniform units "
                    f"({', '.join(sorted(_UNIT_MS))}); {unit} is "
                    "calendar-variable")
            if e.name == "TIMESTAMPADD":
                n = _expr_str(e.args[1], table, schema)
                x = _expr_str(e.args[2], table, schema)
                return f"timestamp_shift({x}, {period}, {n})"
            a = _expr_str(e.args[1], table, schema)
            b = _expr_str(e.args[2], table, schema)
            return f"div(({b}) - ({a}), {period})"
        if e.name == "STRPOS" and len(e.args) == 2:
            # SQL STRPOS is 1-based with 0 for absent; the native
            # expression strpos is Druid's 0-based/-1 form
            x = _expr_str(e.args[0], table, schema)
            lit = _expr_str(e.args[1], table, schema)
            return f"(strpos({x}, {lit}) + 1)"
        fn = _SQL_FN_TO_EXPR.get(e.name)
        if fn is not None:
            args = ", ".join(_expr_str(a, table, schema) for a in e.args)
            return f"{fn}({args})"
        raise PlannerError(f"function {e.name} not translatable to expression")
    raise PlannerError(f"cannot translate {type(e).__name__} to expression")


# ---------------------------------------------------------------------------
# WHERE → (intervals, DimFilter)
# ---------------------------------------------------------------------------

def _is_time_col(e) -> bool:
    return isinstance(e, P.Col) and e.name == TIME_COL


def _lit_ms(e) -> Optional[int]:
    if isinstance(e, P.Lit):
        if e.type == "timestamp":
            return parse_ts(e.value)
        if e.type in ("long", "double"):
            return int(e.value)
        if e.type == "string":
            try:
                return parse_ts(e.value)
            except (ValueError, TypeError):
                return None
    return None


def split_where(e, table: str, schema: SqlSchema
                ) -> Tuple[Optional[Interval], Optional[F.DimFilter]]:
    """Split the WHERE conjunction into a __time interval + a DimFilter
    (the analog of Calcite's interval extraction in DruidQuery/Expressions)."""
    lo, hi = None, None
    rest: List[F.DimFilter] = []

    def add_bound(which: str, ms: int):
        nonlocal lo, hi
        if which == "lo":
            lo = ms if lo is None else max(lo, ms)
        else:
            hi = ms if hi is None else min(hi, ms)

    def walk(node):
        if isinstance(node, P.Bin) and node.op == "AND":
            walk(node.left)
            walk(node.right)
            return
        if isinstance(node, P.BetweenExpr) and _is_time_col(node.operand) \
                and not node.negated:
            blo, bhi = _lit_ms(node.low), _lit_ms(node.high)
            if blo is not None and bhi is not None:
                add_bound("lo", blo)
                add_bound("hi", bhi + 1)  # BETWEEN is inclusive
                return
        b = _time_bound(node)
        if b is not None:
            add_bound(*b)
            return
        rest.append(to_filter(node, table, schema))

    if e is not None:
        walk(e)
    interval = None
    if lo is not None or hi is not None:
        start = lo if lo is not None else ETERNITY_START
        end = hi if hi is not None else ETERNITY_END
        # contradictory bounds → legal empty range, not an error
        interval = Interval(start, max(start, end))
    flt = None
    if rest:
        flt = rest[0] if len(rest) == 1 else F.AndFilter(tuple(rest))
    return interval, flt


def _time_bound(node) -> Optional[Tuple[str, int]]:
    """__time <cmp> TIMESTAMP → ("lo"/"hi", ms). Intervals are [lo, hi)."""
    if not isinstance(node, P.Bin):
        return None
    l, r, op = node.left, node.right, node.op
    if _is_time_col(r) and not _is_time_col(l):
        # flip: 't' < __time  →  __time > 't'
        l, r = r, l
        op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
    if not _is_time_col(l):
        return None
    ms = _lit_ms(r)
    if ms is None:
        return None
    if op == ">=":
        return ("lo", ms)
    if op == ">":
        return ("lo", ms + 1)
    if op == "<":
        return ("hi", ms)
    if op == "<=":
        return ("hi", ms + 1)
    return None


def _lit_str(e) -> str:
    if not isinstance(e, P.Lit):
        raise PlannerError("expected literal")
    if e.type == "timestamp":
        # __time comparisons that escape interval extraction (e.g. under OR)
        # filter against numeric epoch millis
        return str(parse_ts(e.value))
    return "" if e.value is None else str(e.value)


def _peel_varchar_casts(e):
    while isinstance(e, P.Cast) and \
            str(e.to_type).upper() in ("VARCHAR", "CHAR", "STRING"):
        e = e.operand
    return e


def _canonical_number(s: str, ctype: Optional[str] = None) -> bool:
    """Does this literal round-trip the COLUMN TYPE's stringification?
    Only then is CAST(numcol AS VARCHAR) = lit the same as numcol =
    number. Long columns stringify via str(int): '7' matches, '7.0'/'07'/
    '7a' never can. Double/float columns stringify via str(float): '7.0'
    matches but '7' never can (the cast yields '7.0'). With no ctype,
    either canonical form passes (pre-type-awareness callers)."""
    if ctype == "long":
        try:
            return str(int(s)) == s
        except ValueError:
            return False
    if ctype in ("float", "double"):
        try:
            return s in (str(float(s)), repr(float(s)))
        except ValueError:
            return False
    try:
        if str(int(s)) == s:
            return True
    except ValueError:
        pass
    try:
        return s in (str(float(s)), repr(float(s)))
    except ValueError:
        return False


class _NeverMatch:
    """Sentinel from _unwrap_varchar_cast: the comparison is statically
    false — the literal can never equal the column's stringification
    (e.g. CAST(double AS VARCHAR) = '7', which stringifies to '7.0')."""


_NEVER = _NeverMatch()


def _unwrap_varchar_cast(e, table: str, schema: SqlSchema,
                         op: str = "=", literals=()):
    """CAST(x AS VARCHAR) unwraps ONLY where string-compare semantics
    equal the column's own: always for string columns (pure identity);
    for numeric columns only under =/<>/IN with literals canonical FOR
    THAT TYPE (ordering and LIKE compare strings lexicographically —
    numeric planning would return different rows). Non-canonical =/<>
    literals return _NEVER: the equality is statically false, so the
    caller plans zero rows (or all rows for <>) instead of handing the
    engine a number-vs-string comparison that crashes or silently
    mismatches (int('7.0') → ValueError → 500)."""
    inner = _peel_varchar_casts(e)
    if inner is e:
        return e
    if not isinstance(inner, P.Col):
        return inner          # fn trees: the extraction path type-checks
    ctype = schema.type_of(table, inner.name)
    if ctype == "string":
        return inner
    if op in ("=", "<>", "in") and literals:
        if all(_canonical_number(str(v), ctype) for v in literals):
            return inner
        return _NEVER
    if op in ("<", "<=", ">", ">="):
        # SQL compares the STRINGS lexicographically; numeric columns
        # have no dictionary to realize that on the device, and the
        # expression fallback would crash comparing number to string
        raise PlannerError(
            "lexicographic ordering over CAST(numeric AS VARCHAR) is not "
            "supported — compare the numeric column directly")
    return e


def _extraction_of(e, table: str, schema: SqlSchema):
    """String-function call tree over ONE column → (column name,
    ExtractionFn), or None. Nested calls cascade (reference:
    Expressions.toSimpleExtraction — UPPER/LOWER/SUBSTRING/TRIM/LEFT/
    RIGHT/CHAR_LENGTH/REGEXP_EXTRACT/LOOKUP compose on a dimension)."""
    from druid_tpu.query.model import (CascadeExtractionFn, ExtractionFn,
                                       RegexExtractionFn, StrlenExtractionFn)

    def inner(node):
        if isinstance(node, P.Col):
            if schema.type_of(table, node.name) != "string":
                return None           # extraction reads string dims only
            return node.name, ()
        if not isinstance(node, P.Fn) or not node.args:
            return None
        base = inner(node.args[0])
        if base is None:
            return None
        col, chain = base

        def lit(i, default=None):
            if len(node.args) > i and isinstance(node.args[i], P.Lit):
                return node.args[i].value
            return default

        nm = node.name
        if nm == "UPPER" and len(node.args) == 1:
            return col, chain + (UpperExtractionFn(),)
        if nm == "LOWER" and len(node.args) == 1:
            return col, chain + (LowerExtractionFn(),)
        if nm == "SUBSTRING" and len(node.args) >= 2:
            start = lit(1)
            if start is None:
                return None
            if len(node.args) > 2 and lit(2) is None:
                return None    # non-literal length → expression path
            return col, chain + (SubstringExtractionFn(
                int(start) - 1,
                None if len(node.args) < 3 else int(lit(2))),)
        if nm == "LEFT" and len(node.args) == 2 and lit(1) is not None:
            return col, chain + (SubstringExtractionFn(0, int(lit(1))),)
        if nm == "RIGHT" and len(node.args) == 2 and lit(1) is not None:
            n = int(lit(1))
            return col, chain + (RegexExtractionFn(
                f"(.{{0,{n}}})$", 1),)
        if nm == "TRIM" and len(node.args) == 1:
            # SQL TRIM strips SPACE characters only — \s would also eat
            # tabs/newlines and match values the reference would not
            return col, chain + (RegexExtractionFn(
                "^ *(.*?) *$", 1),)
        if nm in ("CHAR_LENGTH", "LENGTH", "STRLEN") \
                and len(node.args) == 1:
            return col, chain + (StrlenExtractionFn(),)
        if nm == "REGEXP_EXTRACT" and len(node.args) >= 2 \
                and lit(1) is not None:
            if len(node.args) > 2 and lit(2) is None:
                return None    # non-literal group index → expression path
            return col, chain + (RegexExtractionFn(
                str(lit(1)), int(lit(2, 0)),
                replace_missing=True, replacement=None),)
        if nm == "LOOKUP" and len(node.args) == 2 and lit(1) is not None:
            return col, chain + (RegisteredLookupExtractionFn(str(lit(1))),)
        return None

    got = inner(e)
    if got is None or not got[1]:
        return None
    col, chain = got
    fn: ExtractionFn = chain[0] if len(chain) == 1 \
        else CascadeExtractionFn(tuple(chain))
    return col, fn


def to_filter(e, table: str, schema: SqlSchema) -> F.DimFilter:
    """SQL boolean AST → DimFilter tree (reference: Expressions.toFilter)."""
    if isinstance(e, P.Bin) and e.op in ("AND", "OR"):
        parts = (to_filter(e.left, table, schema),
                 to_filter(e.right, table, schema))
        return F.AndFilter(parts) if e.op == "AND" else F.OrFilter(parts)
    if isinstance(e, P.Un) and e.op == "NOT":
        return F.NotFilter(to_filter(e.operand, table, schema))
    if isinstance(e, P.IsNullExpr):
        if not isinstance(e.operand, P.Col):
            raise PlannerError("IS NULL supported on columns only")
        flt = F.SelectorFilter(e.operand.name, None)
        return F.NotFilter(flt) if e.negated else flt
    if isinstance(e, P.InExpr):
        if e.subquery is not None:
            raise PlannerError(
                "IN (SELECT ...) must be materialized by the SQL executor")
        operand = _peel_varchar_casts(e.operand)
        if operand is not e.operand and isinstance(operand, P.Col) \
                and schema.type_of(table, operand.name) != "string":
            # CAST(numcol AS VARCHAR) IN (...): only literals canonical
            # for the COLUMN TYPE can ever equal its stringification —
            # keep those, drop the rest ('7.0' against a long column, '7'
            # against a double); an all-dropped list matches nothing
            ctype = schema.type_of(table, operand.name)
            vals = tuple(_lit_str(v) for v in e.values
                         if _canonical_number(_lit_str(v), ctype))
            if not vals:
                return F.NotFilter(F.FalseFilter()) if e.negated \
                    else F.FalseFilter()
            flt = F.InFilter(operand.name, vals)
            return F.NotFilter(flt) if e.negated else flt
        if isinstance(operand, P.Col):
            vals = tuple(_lit_str(v) for v in e.values)
            flt = F.InFilter(operand.name, vals)
            return F.NotFilter(flt) if e.negated else flt
        ext = _extraction_of(operand, table, schema)
        if ext is not None:
            vals = tuple(_lit_str(v) for v in e.values)
            flt = F.InFilter(ext[0], vals, extraction_fn=ext[1])
            return F.NotFilter(flt) if e.negated else flt
        raise PlannerError("IN supported on columns only")
    if isinstance(e, P.LikeExpr):
        if isinstance(e.pattern, P.Lit):
            # LIKE is string-lexical: unwrap applies to string columns only
            operand = _unwrap_varchar_cast(e.operand, table, schema,
                                           op="like")
            if isinstance(operand, P.Col):
                flt = F.LikeFilter(operand.name, str(e.pattern.value))
                return F.NotFilter(flt) if e.negated else flt
            ext = _extraction_of(operand, table, schema)
            if ext is not None:
                flt = F.LikeFilter(ext[0], str(e.pattern.value),
                                   extraction_fn=ext[1])
                return F.NotFilter(flt) if e.negated else flt
        raise PlannerError("LIKE needs column and literal pattern")
    if isinstance(e, P.BetweenExpr):
        if isinstance(e.operand, P.Col):
            ctype = schema.type_of(table, e.operand.name)
            ordering = "numeric" if ctype in ("long", "float", "double") \
                else "lexicographic"
            flt = F.BoundFilter(e.operand.name,
                                lower=_lit_str(e.low), upper=_lit_str(e.high),
                                lower_strict=False, upper_strict=False,
                                ordering=ordering)
            return F.NotFilter(flt) if e.negated else flt
        raise PlannerError("BETWEEN supported on columns only")
    if isinstance(e, P.Bin) and e.op in ("=", "<>", "<", "<=", ">", ">="):
        l, r, op = e.left, e.right, e.op
        # CAST(col AS VARCHAR) compared to a literal: unwrap where that is
        # value-identity (see _unwrap_varchar_cast) so it plans as a
        # proper column filter instead of a number-vs-string expression
        # that silently matches nothing
        if isinstance(r, P.Lit):
            l = _unwrap_varchar_cast(l, table, schema, op,
                                     (_lit_str(r),))
        if isinstance(l, P.Lit):
            r = _unwrap_varchar_cast(r, table, schema, op,
                                     (_lit_str(l),))
        if l is _NEVER or r is _NEVER:
            # statically-false equality: CAST(numcol AS VARCHAR) can never
            # stringify to this literal — zero rows for =, all rows for <>
            if op == "=":
                return F.FalseFilter()
            if op == "<>":
                return F.TrueFilter()
            return F.FalseFilter()   # unreachable: ordering ops raise
        if isinstance(r, P.Col) and not isinstance(l, P.Col):
            l, r = r, l
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        if isinstance(l, P.Col) and isinstance(r, P.Lit):
            name = l.name
            ctype = schema.type_of(table, name)
            numeric = ctype in ("long", "float", "double")
            ordering = "numeric" if numeric else "lexicographic"
            v = _lit_str(r)
            if op == "=":
                if numeric:
                    return F.BoundFilter(name, lower=v, upper=v,
                                         ordering="numeric")
                return F.SelectorFilter(name, v)
            if op == "<>":
                if numeric:
                    return F.NotFilter(F.BoundFilter(name, lower=v, upper=v,
                                                     ordering="numeric"))
                return F.NotFilter(F.SelectorFilter(name, v))
            if op == "<":
                return F.BoundFilter(name, upper=v, upper_strict=True,
                                     ordering=ordering)
            if op == "<=":
                return F.BoundFilter(name, upper=v, ordering=ordering)
            if op == ">":
                return F.BoundFilter(name, lower=v, lower_strict=True,
                                     ordering=ordering)
            if op == ">=":
                return F.BoundFilter(name, lower=v, ordering=ordering)
        if isinstance(l, P.Col) and isinstance(r, P.Col) and op == "=":
            return F.ColumnComparisonFilter((l.name, r.name))
        if isinstance(r, P.Lit) and not isinstance(l, P.Col):
            # string-function call over a dimension: filter through an
            # extraction fn on the dictionary (Expressions.toSimpleExtraction)
            ext = _extraction_of(l, table, schema)
            if ext is not None:
                name, fn = ext
                v = _lit_str(r)
                ordering = "numeric" if isinstance(r.value, (int, float)) \
                    and not isinstance(r.value, bool) else "lexicographic"
                if op == "=":
                    return F.SelectorFilter(name, v, extraction_fn=fn)
                if op == "<>":
                    return F.NotFilter(
                        F.SelectorFilter(name, v, extraction_fn=fn))
                strict = op in ("<", ">")
                if op in ("<", "<="):
                    return F.BoundFilter(name, upper=v, upper_strict=strict,
                                         ordering=ordering,
                                         extraction_fn=fn)
                return F.BoundFilter(name, lower=v, lower_strict=strict,
                                     ordering=ordering, extraction_fn=fn)
        # fall through to expression filter
        return F.ExpressionFilter(_expr_str(e, table, schema))
    if isinstance(e, P.Lit) and e.type == "bool":
        return F.TrueFilter() if e.value else F.FalseFilter()
    # general fallback
    return F.ExpressionFilter(_expr_str(e, table, schema))


# ---------------------------------------------------------------------------
# Aggregates
# ---------------------------------------------------------------------------

def _is_aggregate(e) -> bool:
    if isinstance(e, P.Fn) and e.name in P._AGG_FNS:
        return True
    if isinstance(e, P.Bin):
        return _is_aggregate(e.left) or _is_aggregate(e.right)
    if isinstance(e, P.Un):
        return _is_aggregate(e.operand)
    if isinstance(e, P.Cast):
        return _is_aggregate(e.operand)
    return False


class _AggBuilder:
    """Accumulates AggregatorSpecs / PostAggregators / virtual columns while
    translating aggregate projections (reference: Aggregations.java +
    GroupByRules)."""

    def __init__(self, table: str, schema: SqlSchema):
        self.table = table
        self.schema = schema
        self.aggs: List[A.AggregatorSpec] = []
        self.postaggs: List[PA.PostAggregator] = []
        self.vcols: List[ExpressionVirtualColumn] = []
        self._n = 0
        self._agg_by_key: Dict[str, str] = {}   # dedup: ast-repr → agg name

    def fresh(self, prefix: str = "a") -> str:
        self._n += 1
        return f"_{prefix}{self._n - 1}"

    def _field_for(self, e) -> Tuple[str, str]:
        """Aggregation input → (column name, type). Non-column exprs become
        virtual columns (double-typed)."""
        if isinstance(e, P.Col):
            t = self.schema.type_of(self.table, e.name)
            if t is None:
                raise PlannerError(f"unknown column [{e.name}]")
            return e.name, t
        name = self.fresh("v")
        self.vcols.append(ExpressionVirtualColumn(
            name, _expr_str(e, self.table, self.schema), "double"))
        return name, "double"

    def _simple(self, kind: str, col: str, ctype: str, name: str) -> A.AggregatorSpec:
        table = {
            ("SUM", "long"): A.LongSumAggregator,
            ("SUM", "float"): A.FloatSumAggregator,
            ("SUM", "double"): A.DoubleSumAggregator,
            ("MIN", "long"): A.LongMinAggregator,
            ("MIN", "float"): A.FloatMinAggregator,
            ("MIN", "double"): A.DoubleMinAggregator,
            ("MAX", "long"): A.LongMaxAggregator,
            ("MAX", "float"): A.FloatMaxAggregator,
            ("MAX", "double"): A.DoubleMaxAggregator,
        }
        cls = table.get((kind, ctype))
        if cls is None:
            if ctype == "string":
                raise PlannerError(f"{kind} over string column [{col}]")
            cls = table[(kind, "double")]
        return cls(name, col)

    def translate(self, e, alias: str) -> str:
        """Translate an aggregate projection; returns the native output
        field name carrying its value (agg name or postagg name)."""
        if isinstance(e, P.Fn) and e.name in P._AGG_FNS:
            return self._agg_fn(e, alias)
        if isinstance(e, P.Bin):
            # arithmetic over aggregates → post-aggregator
            l = self._operand(e.left)
            r = self._operand(e.right)
            fn = {"+": "+", "-": "-", "*": "*", "/": "/", "%": "%"}.get(e.op)
            if fn is None:
                raise PlannerError(f"operator {e.op!r} over aggregates")
            self.postaggs.append(PA.ArithmeticPostAgg(alias, fn, (l, r)))
            return alias
        if isinstance(e, P.Cast):
            return self.translate(e.operand, alias)
        raise PlannerError(f"cannot translate aggregate {e!s}")

    def _operand(self, e) -> PA.PostAggregator:
        if isinstance(e, P.Lit) and e.type in ("long", "double"):
            return PA.ConstantPostAgg("c", float(e.value))
        name = self.translate(e, self.fresh())
        return PA.FieldAccessPostAgg(name, name)

    def _agg_fn(self, e: P.Fn, alias: str) -> str:
        key = repr((e.name, e.args, e.distinct, e.filter, e.extra))
        hit = self._agg_by_key.get(key)
        if hit is not None:
            return hit

        def reg(agg: A.AggregatorSpec) -> str:
            if e.filter is not None:
                agg = A.FilteredAggregator(
                    agg.name, agg, to_filter(e.filter, self.table, self.schema))
            self.aggs.append(agg)
            self._agg_by_key[key] = agg.name
            return agg.name

        if e.name == "COUNT":
            if e.distinct:
                col, _ = self._field_for(e.args[0])
                return reg(A.CardinalityAggregator(alias, (col,), round=True))
            if e.args:
                # COUNT(col) = rows where col is not null; an attached
                # FILTER clause ANDs with the not-null predicate
                col = e.args[0]
                if not isinstance(col, P.Col):
                    raise PlannerError("COUNT(expr) not supported; use COUNT(*)")
                flt = F.NotFilter(F.SelectorFilter(col.name, None))
                if e.filter is not None:
                    flt = F.AndFilter(
                        (flt, to_filter(e.filter, self.table, self.schema)))
                self.aggs.append(A.FilteredAggregator(
                    alias, A.CountAggregator(alias), flt))
                # structural dedupe: the FIRST planner alias is shared
                # by every identical aggregate expression on purpose
                self._agg_by_key[key] = alias  # druidlint: disable=unkeyed-trace-input
                return alias
            return reg(A.CountAggregator(alias))
        if e.name == "APPROX_COUNT_DISTINCT":
            col, _ = self._field_for(e.args[0])
            return reg(A.CardinalityAggregator(alias, (col,), round=True))
        if e.name in ("SUM", "MIN", "MAX"):
            col, ctype = self._field_for(e.args[0])
            return reg(self._simple(e.name, col, ctype, alias))
        if e.name == "AVG":
            col, ctype = self._field_for(e.args[0])
            sname, cname = self.fresh(), self.fresh()
            ssum = self._simple("SUM", col, ctype, sname)
            cnt = A.CountAggregator(cname)
            if e.filter is not None:
                flt = to_filter(e.filter, self.table, self.schema)
                ssum = A.FilteredAggregator(sname, ssum, flt)
                cnt = A.FilteredAggregator(cname, cnt, flt)
            self.aggs += [ssum, cnt]
            self.postaggs.append(PA.ArithmeticPostAgg(
                alias, "/", (PA.FieldAccessPostAgg(sname, sname),
                             PA.FieldAccessPostAgg(cname, cname))))
            # structural dedupe: first alias shared by design (see COUNT)
            self._agg_by_key[key] = alias  # druidlint: disable=unkeyed-trace-input
            return alias
        if e.name in ("EARLIEST", "LATEST"):
            col, ctype = self._field_for(e.args[0])
            cls = A.FirstAggregator if e.name == "EARLIEST" else A.LastAggregator
            kind = "long" if ctype == "long" else "double"
            return reg(cls(alias, col, kind))
        if e.name in ("VARIANCE", "VAR_POP", "VAR_SAMP", "STDDEV",
                      "STDDEV_POP", "STDDEV_SAMP"):
            from druid_tpu.ext.stats import (StandardDeviationPostAgg,
                                             VarianceAggregator)
            col, _ = self._field_for(e.args[0])
            # SQL/Druid default: VARIANCE ≡ VAR_SAMP, STDDEV ≡ STDDEV_SAMP
            estimator = "population" if e.name.endswith("_POP") else "sample"
            if e.name.startswith("STDDEV"):
                vname = self.fresh("var")
                reg(VarianceAggregator(vname, col, estimator))
                self.postaggs.append(StandardDeviationPostAgg(alias, vname))
                # structural dedupe: first alias shared by design
                self._agg_by_key[key] = alias  # druidlint: disable=unkeyed-trace-input
                return alias
            return reg(VarianceAggregator(alias, col, estimator))
        if e.name == "APPROX_QUANTILE":
            from druid_tpu.ext.sketches import (QuantilePostAgg,
                                                QuantilesSketchAggregator)
            col, _ = self._field_for(e.args[0])
            if len(e.args) < 2 or not isinstance(e.args[1], P.Lit):
                raise PlannerError("APPROX_QUANTILE needs a literal fraction")
            # one sketch per (column, filter) feeds every fraction over it
            skey = repr(("__qsketch", col, e.filter))
            sname = self._agg_by_key.get(skey)
            if sname is None:
                sname = self.fresh("qs")
                agg = QuantilesSketchAggregator(sname, col)
                if e.filter is not None:
                    agg = A.FilteredAggregator(
                        sname, agg, to_filter(e.filter, self.table,
                                              self.schema))
                self.aggs.append(agg)
                self._agg_by_key[skey] = sname
            self.postaggs.append(QuantilePostAgg(
                alias, PA.FieldAccessPostAgg(sname, sname),
                float(e.args[1].value)))
            # structural dedupe: first alias shared by design
            self._agg_by_key[key] = alias  # druidlint: disable=unkeyed-trace-input
            return alias
        if e.name == "DS_THETA":
            from druid_tpu.ext.sketches import ThetaSketchAggregator
            col, _ = self._field_for(e.args[0])
            return reg(ThetaSketchAggregator(alias, col, should_finalize=True))
        raise PlannerError(f"aggregate {e.name} not supported")


# ---------------------------------------------------------------------------
# Grouping expressions → dimension specs / granularity
# ---------------------------------------------------------------------------

def _floor_unit(e) -> Optional[str]:
    """FLOOR(__time TO unit) → granularity name."""
    if isinstance(e, P.Fn) and e.name == "FLOOR" and e.extra \
            and len(e.args) == 1 and _is_time_col(e.args[0]):
        unit = _FLOOR_UNITS.get(e.extra)
        if unit is None:
            raise PlannerError(f"FLOOR unit {e.extra} unsupported")
        return unit
    return None


def _dimension_spec(e, alias: str, table: str, schema: SqlSchema,
                    builder: _AggBuilder) -> DimensionSpec:
    if isinstance(e, P.Col):
        t = schema.type_of(table, e.name)
        if t is None:
            raise PlannerError(f"unknown column [{e.name}]")
        # numeric columns group through the engine's numeric dimension
        # handler (query-time value dictionary)
        return DefaultDimensionSpec(e.name, alias)
    if isinstance(e, P.Fn) and e.name == "LOOKUP" \
            and isinstance(e.args[0], P.Col) and isinstance(e.args[1], P.Lit):
        return ExtractionDimensionSpec(
            e.args[0].name, alias,
            RegisteredLookupExtractionFn(str(e.args[1].value)))
    ext = _extraction_of(e, table, schema)
    if ext is not None:
        # the whole string-fn family (SUBSTRING/UPPER/LOWER/TRIM/LEFT/
        # RIGHT/CHAR_LENGTH/REGEXP_EXTRACT, nested) groups through one
        # extraction dimension spec
        return ExtractionDimensionSpec(ext[0], alias, ext[1])
    # anything translatable to an expression groups as a computed
    # dimension (EXTRACT, TIME_FLOOR, MOD, CASE, arithmetic, ...): the
    # engine host-evaluates it into a per-segment value dictionary
    try:
        expr_s = _expr_str(e, table, schema)
    except PlannerError as err:
        raise PlannerError(f"cannot group by {e!s}: {err}") from err
    return ExpressionDimensionSpec(expr_s, alias, "long")


# ---------------------------------------------------------------------------
# HAVING
# ---------------------------------------------------------------------------

def _having(e, alias_to_field: Dict[str, str], builder: _AggBuilder,
            table: str, schema: SqlSchema) -> HavingSpec:
    if isinstance(e, P.Bin) and e.op in ("AND", "OR"):
        parts = (_having(e.left, alias_to_field, builder, table, schema),
                 _having(e.right, alias_to_field, builder, table, schema))
        return AndHaving(parts) if e.op == "AND" else OrHaving(parts)
    if isinstance(e, P.Un) and e.op == "NOT":
        return NotHaving(_having(e.operand, alias_to_field, builder, table,
                                 schema))
    if isinstance(e, P.Bin) and e.op in ("=", "<", ">", "<=", ">="):
        l, r = e.left, e.right
        if isinstance(r, P.Lit) and r.type in ("long", "double"):
            field_name = _having_field(l, alias_to_field, builder)
            v = float(r.value)
            if e.op == ">":
                return GreaterThanHaving(field_name, v)
            if e.op == "<":
                return LessThanHaving(field_name, v)
            if e.op == "=":
                return EqualToHaving(field_name, v)
            if e.op == ">=":
                return NotHaving(LessThanHaving(field_name, v))
            if e.op == "<=":
                return NotHaving(GreaterThanHaving(field_name, v))
    raise PlannerError(f"cannot translate HAVING {e!s}")


def _having_field(e, alias_to_field: Dict[str, str],
                  builder: _AggBuilder) -> str:
    if isinstance(e, P.Col) and e.name in alias_to_field:
        return alias_to_field[e.name]
    if _is_aggregate(e):
        return builder.translate(e, builder.fresh("h"))
    raise PlannerError(f"HAVING references non-aggregate {e!s}")


# ---------------------------------------------------------------------------
# Top-level planning
# ---------------------------------------------------------------------------

def _ast_eq(a, b) -> bool:
    return repr(a) == repr(b)


def plan_sql(sel: P.Select, schema: SqlSchema) -> PlannedQuery:
    if sel.schema is not None:
        if sel.schema.upper() == "INFORMATION_SCHEMA":
            return PlannedQuery(None, [], meta_table=sel.table.upper(),
                                meta_select=sel)
        raise PlannerError(f"unknown schema [{sel.schema}]")
    if sel.subquery is not None:
        return _plan_nested(sel, schema)
    if sel.table is None:
        raise PlannerError("SELECT without FROM not supported")
    table = sel.table
    schema.columns(table)  # validate

    interval, flt = split_where(sel.where, table, schema)
    intervals = [interval if interval is not None else Interval.eternity()]

    # resolve GROUP BY ordinals (GROUP BY 1)
    group_by = []
    for g in sel.group_by:
        if isinstance(g, P.Lit) and g.type == "long":
            idx = int(g.value) - 1
            if not (0 <= idx < len(sel.items)):
                raise PlannerError(f"GROUP BY ordinal {g.value} out of range")
            group_by.append(sel.items[idx].expr)
        else:
            group_by.append(g)

    has_agg = any(_is_aggregate(it.expr) for it in sel.items) \
        or (sel.having is not None)

    if sel.distinct and not has_agg and not group_by:
        # SELECT DISTINCT a, b → GROUP BY a, b
        group_by = [it.expr for it in sel.items if not isinstance(it.expr, P.Star)]
        has_agg = True

    if not has_agg and not group_by:
        return _plan_scan(sel, table, schema, intervals, flt)
    return _plan_grouped(sel, table, schema, intervals, flt, group_by)


def _plan_nested(sel: P.Select, schema: SqlSchema) -> PlannedQuery:
    """FROM (SELECT ...): plan the inner statement, expose its output
    aliases as the synthetic __subquery__ table, and nest the natives via
    Query.inner_query — the executor/broker materialize inner groupBy rows
    as an in-memory segment (reference: DruidOuterQueryRel +
    GroupByStrategyV2.processSubqueryResult)."""
    from dataclasses import replace as _dc_replace
    inner = plan_sql(sel.subquery, schema)
    if not isinstance(inner.native, GroupByQuery):
        raise PlannerError(
            "FROM (subquery) requires the inner statement to plan as a "
            "groupBy (add a GROUP BY)")
    if inner.sort_in_executor or inner.limit_in_executor is not None \
            or inner.offset_in_executor:
        raise PlannerError(
            "inner ORDER BY/LIMIT handled outside the native query is not "
            "nestable — put the ordering on the outer statement")

    # inner outputs become the outer table's columns, typed from the
    # inner aggregators (dims → string except expression dims → long)
    agg_types: Dict[str, str] = {}
    for a in inner.native.aggregations:
        t = type(a).__name__
        agg_types[a.name] = "long" if t in ("CountAggregator",
                                            "LongSumAggregator",
                                            "LongMinAggregator",
                                            "LongMaxAggregator") else "double"
    for pa in inner.native.post_aggregations:
        agg_types[pa.name] = "double"
    expr_dims = {d.output_name for d in inner.native.dimensions
                 if isinstance(d, ExpressionDimensionSpec)}
    cols: Dict[str, str] = {}
    for o in inner.outputs:
        if o.kind == "time":
            continue      # outer references __time directly
        if o.kind == "dim":
            cols[o.alias] = "long" if o.key in expr_dims or \
                o.alias in expr_dims else "string"
        else:
            cols[o.alias] = agg_types.get(o.key, "double")
    inner_schema = SqlSchema({"__subquery__": cols})

    # the OUTER statement plans against the synthetic table; the inner's
    # native output columns are exposed under their SQL aliases, so remap
    # the inner outputs to emit alias-named event fields (mapped by the
    # NATIVE output name — projection order can differ from GROUP BY order)
    outer = plan_sql(_dc_replace(sel, subquery=None), inner_schema)
    inner_native = inner.native
    dim_alias_by_key: Dict[str, str] = {}
    value_renames: Dict[str, str] = {}
    for o in inner.outputs:
        ren = dim_alias_by_key if o.kind == "dim" else (
            value_renames if o.kind == "value" else None)
        if ren is None:
            continue
        if o.key in ren and ren[o.key] != o.alias:
            # two SQL aliases share one deduped native field; a last-wins
            # rename would silently drop one column — fail loudly
            raise PlannerError(
                f"inner column projected under two aliases "
                f"({ren[o.key]!r}, {o.alias!r}) — project it once and "
                f"reference the single alias in the outer statement")
        ren[o.key] = o.alias
    value_renames = {k: v for k, v in value_renames.items() if k != v}
    needs_rename = value_renames or any(
        dim_alias_by_key.get(d.output_name, d.output_name) != d.output_name
        for d in inner_native.dimensions)
    if needs_rename and inner_native.limit_spec is not None:
        raise PlannerError(
            "inner ORDER BY/LIMIT references pre-alias field names — put "
            "the ordering on the outer statement")
    ren_dims = []
    for d in inner_native.dimensions:
        alias = dim_alias_by_key.get(d.output_name, d.output_name)
        if alias == d.output_name:
            ren_dims.append(d)
        elif isinstance(d, ExpressionDimensionSpec):
            ren_dims.append(_dc_replace(d, output_name=alias))
        elif isinstance(d, DefaultDimensionSpec):
            ren_dims.append(DefaultDimensionSpec(d.dimension, alias))
        else:
            raise PlannerError(f"cannot alias nested dimension {d!r}")
    if value_renames:
        inner_native = _dc_replace(
            inner_native,
            aggregations=tuple(
                _rename_agg(a, value_renames.get(a.name)) for a in
                inner_native.aggregations),
            post_aggregations=tuple(
                _rename_postagg(pa, value_renames.get(pa.name)) for pa in
                inner_native.post_aggregations))
    inner_native = _dc_replace(inner_native, dimensions=tuple(ren_dims))
    outer_native = _dc_replace(outer.native, inner_query=inner_native)
    return PlannedQuery(outer_native, outer.outputs,
                        sort_in_executor=outer.sort_in_executor,
                        limit_in_executor=outer.limit_in_executor,
                        offset_in_executor=outer.offset_in_executor)


def _rename_agg(a, new_name):
    from dataclasses import replace as _dc_replace
    return a if new_name is None else _dc_replace(a, name=new_name)


def _rename_postagg(pa, new_name):
    from dataclasses import replace as _dc_replace
    return pa if new_name is None else _dc_replace(pa, name=new_name)


def _alias_of(it: P.SelectItem, i: int) -> str:
    if it.alias:
        return it.alias
    if isinstance(it.expr, P.Col):
        return it.expr.name
    return f"EXPR${i}"


def _plan_scan(sel: P.Select, table: str, schema: SqlSchema,
               intervals, flt) -> PlannedQuery:
    cols: List[str] = []
    outputs: List[OutputColumn] = []
    for i, it in enumerate(sel.items):
        if isinstance(it.expr, P.Star):
            allcols = [TIME_COL] + sorted(schema.columns(table))
            cols += [c for c in allcols if c not in cols]
            outputs += [OutputColumn(c, "column", c) for c in allcols]
        elif isinstance(it.expr, P.Col):
            name = it.expr.name
            if schema.type_of(table, name) is None:
                raise PlannerError(f"unknown column [{name}]")
            if name not in cols:
                cols.append(name)
            outputs.append(OutputColumn(_alias_of(it, i), "column", name))
        else:
            raise PlannerError("scan projections must be plain columns")
    order = "none"
    if sel.order_by:
        if len(sel.order_by) != 1 or not _is_time_col(sel.order_by[0].expr):
            raise PlannerError("non-aggregate ORDER BY supports __time only")
        order = "descending" if sel.order_by[0].descending else "ascending"
    q = ScanQuery.of(table, intervals, columns=tuple(cols), limit=sel.limit,
                     offset=sel.offset, order=order, filter=flt)
    return PlannedQuery(q, outputs)


def _plan_grouped(sel: P.Select, table: str, schema: SqlSchema,
                  intervals, flt, group_by) -> PlannedQuery:
    builder = _AggBuilder(table, schema)

    # split grouping exprs: time floor → granularity; rest → dimensions
    granularity = "all"
    time_expr = None
    dim_exprs: List[object] = []
    for g in group_by:
        unit = _floor_unit(g)
        if unit is not None:
            if time_expr is not None:
                raise PlannerError("multiple time FLOORs in GROUP BY")
            granularity = unit
            time_expr = g
        else:
            dim_exprs.append(g)

    # projections
    outputs: List[OutputColumn] = []
    dimspecs: List[DimensionSpec] = []
    dim_alias: Dict[str, str] = {}      # repr(expr) → output name
    alias_to_field: Dict[str, str] = {}  # SQL alias → native field
    for i, it in enumerate(sel.items):
        alias = _alias_of(it, i)
        e = it.expr
        if isinstance(e, P.Star):
            raise PlannerError("SELECT * incompatible with GROUP BY")
        if time_expr is not None and _ast_eq(e, time_expr):
            outputs.append(OutputColumn(alias, "time"))
            alias_to_field[alias] = "__timestamp"
            continue
        matched = next((g for g in dim_exprs if _ast_eq(e, g)), None)
        if matched is not None:
            key = repr(matched)
            if key not in dim_alias:
                dim_alias[key] = alias
                dimspecs.append(_dimension_spec(matched, alias, table, schema,
                                                builder))
            outputs.append(OutputColumn(alias, "dim", dim_alias[key]))
            alias_to_field[alias] = dim_alias[key]
            continue
        if _is_aggregate(e):
            name = builder.translate(e, alias)
            outputs.append(OutputColumn(alias, "value", name))
            alias_to_field[alias] = name
            continue
        if isinstance(e, P.Lit):
            outputs.append(OutputColumn(alias, "constant", constant=e.value))
            continue
        raise PlannerError(
            f"projection {e!s} is neither grouped nor aggregate")

    # grouping exprs not projected still need dimension specs
    for g in dim_exprs:
        key = repr(g)
        if key not in dim_alias:
            name = builder.fresh("d")
            dim_alias[key] = name
            dimspecs.append(_dimension_spec(g, name, table, schema, builder))

    having = None
    if sel.having is not None:
        having = _having(sel.having, alias_to_field, builder, table, schema)

    # ORDER BY → limit columns
    order_cols: List[OrderByColumnSpec] = []
    for ob in sel.order_by:
        e = ob.expr
        if isinstance(e, P.Lit) and isinstance(e.value, int) \
                and not isinstance(e.value, bool):
            # ordinal: ORDER BY 1 refers to the first projection
            if not (1 <= e.value <= len(outputs)):
                raise PlannerError(f"ORDER BY position {e.value} out of "
                                   f"range")
            e = P.Col(outputs[e.value - 1].alias)
        fname = None
        numeric = True
        if isinstance(e, P.Col) and e.name in alias_to_field:
            fname = alias_to_field[e.name]
            out = next(o for o in outputs if o.alias == e.name)
            numeric = out.kind in ("value", "time")
        elif time_expr is not None and _ast_eq(e, time_expr):
            fname = "__timestamp"
        elif repr(e) in dim_alias:
            fname = dim_alias[repr(e)]
            numeric = False
        elif _is_aggregate(e):
            fname = builder.translate(e, builder.fresh("o"))
        elif isinstance(e, P.Col):
            raise PlannerError(f"ORDER BY unknown column [{e.name}]")
        else:
            raise PlannerError(f"cannot ORDER BY {e!s}")
        direction = "descending" if ob.descending else "ascending"
        order_cols.append(OrderByColumnSpec(
            fname, direction, "numeric" if numeric else "lexicographic"))

    vcols = tuple(builder.vcols)

    # ---- timeseries: no dimensions
    if not dimspecs:
        # pure ungrouped MIN/MAX(__time) → timeBoundary (a time-bucketed or
        # HAVING-filtered variant must keep the timeseries machinery)
        if granularity == "all" and sel.having is None:
            tb = _time_boundary(sel, table, intervals, flt)
            if tb is not None:
                return tb
        for a in builder.aggs:
            if TIME_COL in a.required_columns():
                raise PlannerError("aggregating __time requires timeBoundary "
                                   "(pure MIN/MAX(__time) select)")
        descending = any(o.dimension == "__timestamp"
                         and o.direction == "descending" for o in order_cols)
        # non-time orderings (e.g. ORDER BY an aggregate) sort the shaped
        # rows in the executor — timeseries results are per-bucket
        sort_exec = [(o.dimension, o.direction == "descending")
                     for o in order_cols if o.dimension != "__timestamp"]
        # scalar aggregates (granularity 'all') must emit their one row even
        # when nothing matches — SELECT COUNT(*) WHERE <false> is 0, not
        # empty; time-floored buckets skip empties like the reference's
        # Calcite-planned timeseries
        q = TimeseriesQuery.of(
            table, intervals, builder.aggs, granularity=granularity,
            filter=flt, post_aggregations=tuple(builder.postaggs),
            descending=descending, skip_empty_buckets=(granularity != "all"),
            virtual_columns=vcols)
        return PlannedQuery(q, outputs,
                            sort_in_executor=sort_exec,
                            limit_in_executor=sel.limit,
                            offset_in_executor=sel.offset)

    # ---- topN: 1 dim, ordered by one agg desc, limited, no having/offset
    if (len(dimspecs) == 1 and granularity == "all" and having is None
            and sel.limit is not None and sel.limit <= TOPN_MAX_THRESHOLD
            and sel.offset == 0 and len(order_cols) == 1
            and order_cols[0].direction == "descending"
            and order_cols[0].dimension not in
            (dimspecs[0].output_name, "__timestamp")
            and not builder.vcols):
        metric = order_cols[0].dimension
        q = TopNQuery.of(
            table, intervals, dimspecs[0], metric, sel.limit, builder.aggs,
            granularity="all", filter=flt,
            post_aggregations=tuple(builder.postaggs))
        return PlannedQuery(q, outputs)

    limit_spec = None
    if order_cols or sel.limit is not None or sel.offset:
        limit_spec = DefaultLimitSpec(tuple(order_cols), sel.limit, sel.offset)
    q = GroupByQuery.of(
        table, intervals, dimspecs, builder.aggs, granularity=granularity,
        filter=flt, post_aggregations=tuple(builder.postaggs), having=having,
        limit_spec=limit_spec, virtual_columns=vcols)
    return PlannedQuery(q, outputs)


def _time_boundary(sel: P.Select, table: str, intervals, flt
                   ) -> Optional[PlannedQuery]:
    """SELECT MIN(__time)[, MAX(__time)] FROM t → timeBoundary."""
    bounds = []
    for i, it in enumerate(sel.items):
        e = it.expr
        if isinstance(e, P.Fn) and e.name in ("MIN", "MAX") \
                and len(e.args) == 1 and _is_time_col(e.args[0]) \
                and e.filter is None and not e.distinct:
            bounds.append(("minTime" if e.name == "MIN" else "maxTime",
                           _alias_of(it, i)))
        else:
            return None
    if not bounds:
        return None
    bound = bounds[0][0] if len(bounds) == 1 else None
    q = TimeBoundaryQuery.of(table, intervals, bound=bound, filter=flt)
    outputs = [OutputColumn(alias, "value", key) for key, alias in bounds]
    return PlannedQuery(q, outputs)
