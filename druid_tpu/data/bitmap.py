"""Bitmap indexes: per-dimension-value row bitmaps with AND/OR/NOT algebra.

Capability parity with the reference's CONCISE/Roaring bitmap indexes
(extendedset/src/main/java/org/apache/druid/extendedset/intset/ImmutableConciseSet.java,
processing/.../collections/bitmap/BitmapFactory.java). TPU-first design: the
bitmap index is a host-side planning structure. Bitmaps are bit-packed numpy
uint8 words (np.packbits layout); algebra is vectorized bitwise ops. The
output of filter planning is either
  * a packed bitmap shipped to the device and unpacked into a bool mask, or
  * a row-selectivity estimate used to decide bitmap-vs-device-predicate
    (the same decision as Filters.shouldUseBitmapIndex, reference
    processing/.../segment/filter/Filters.java).

Density adaptivity (the CONCISE/Roaring capability, not the format): a
value matching few rows stores a sorted row-id list (memory ∝ matches),
a dense value stores packed words (memory ∝ rows/8); per-value bitmaps
materialize lazily under an LRU byte budget, and multi-value unions build
straight from the index's sorted row order without materializing any
per-value bitmap at all.
"""
from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Sequence, Union

import numpy as np


class Bitmap:
    """Fixed-length packed bitset over row ids [0, n_rows)."""

    __slots__ = ("words", "n_rows")

    def __init__(self, words: np.ndarray, n_rows: int):
        assert words.dtype == np.uint8
        self.words = words
        self.n_rows = n_rows

    # ---- constructors -------------------------------------------------
    @staticmethod
    def from_bool(mask: np.ndarray) -> "Bitmap":
        mask = np.asarray(mask, dtype=bool)
        return Bitmap(np.packbits(mask), mask.shape[0])

    @staticmethod
    def from_indices(indices: np.ndarray, n_rows: int) -> "Bitmap":
        mask = np.zeros(n_rows, dtype=bool)
        mask[indices] = True
        return Bitmap.from_bool(mask)

    @staticmethod
    def empty(n_rows: int) -> "Bitmap":
        return Bitmap(np.zeros((n_rows + 7) // 8, dtype=np.uint8), n_rows)

    @staticmethod
    def full(n_rows: int) -> "Bitmap":
        b = Bitmap(np.full((n_rows + 7) // 8, 0xFF, dtype=np.uint8), n_rows)
        return b._trim()

    def _trim(self) -> "Bitmap":
        # zero the tail bits past n_rows
        extra = self.words.shape[0] * 8 - self.n_rows
        if extra:
            tail_mask = np.uint8(0xFF << extra & 0xFF)
            self.words[-1] &= tail_mask
        return self

    # ---- algebra ------------------------------------------------------
    def __and__(self, other) -> "AnyBitmap":
        if isinstance(other, SparseBitmap):
            return bitmap_and(other, self)
        return Bitmap(self.words & other.words, self.n_rows)

    def __or__(self, other) -> "AnyBitmap":
        if isinstance(other, SparseBitmap):
            return bitmap_or(other, self)
        return Bitmap(self.words | other.words, self.n_rows)

    def __xor__(self, other) -> "AnyBitmap":
        if isinstance(other, SparseBitmap):
            return bitmap_xor(other, self)
        return Bitmap(self.words ^ other.words, self.n_rows)

    def __invert__(self) -> "Bitmap":
        return Bitmap(~self.words, self.n_rows)._trim()

    @staticmethod
    def union(bitmaps: Sequence["Bitmap"], n_rows: int) -> "Bitmap":
        if not bitmaps:
            return Bitmap.empty(n_rows)
        out = bitmaps[0].words.copy()
        for b in bitmaps[1:]:
            np.bitwise_or(out, b.words, out=out)
        return Bitmap(out, n_rows)

    @staticmethod
    def intersection(bitmaps: Sequence["Bitmap"], n_rows: int) -> "Bitmap":
        if not bitmaps:
            return Bitmap.full(n_rows)
        out = bitmaps[0].words.copy()
        for b in bitmaps[1:]:
            np.bitwise_and(out, b.words, out=out)
        return Bitmap(out, n_rows)

    # ---- materialization ---------------------------------------------
    def to_bool(self) -> np.ndarray:
        return np.unpackbits(self.words, count=self.n_rows).astype(bool)

    def to_indices(self) -> np.ndarray:
        return np.flatnonzero(self.to_bool())

    def test_ids(self, ids: np.ndarray) -> np.ndarray:
        """Membership of each row id — a word probe per id, no unpack
        (np.packbits stores row r at bit 7 - r%8 of byte r//8)."""
        ids = np.asarray(ids, dtype=np.int64)
        return ((self.words[ids >> 3] >> (7 - (ids & 7))) & 1).astype(bool)

    def cardinality(self) -> int:
        return int(np.unpackbits(self.words, count=self.n_rows).sum())

    def size_bytes(self) -> int:
        return int(self.words.nbytes)

    def __eq__(self, other):
        if not isinstance(other, Bitmap):
            # defer to the reflected __eq__ (SparseBitmap compares content)
            return NotImplemented
        return (self.n_rows == other.n_rows
                and np.array_equal(self.words, other.words))


class SparseBitmap:
    """Row-id-list bitmap for low-density values: memory scales with the
    matching rows, not the segment rows (the capability ImmutableConciseSet
    :79 / RoaringBitmap provide in the reference). Duck-types Bitmap.
    Algebra against another sparse operand stays sparse (sorted-id set
    ops); against a dense operand it probes the dense words at its own ids
    — the operand that is sparse is NEVER densified. Only complement
    (`~`), whose result is inherently dense, materializes words."""

    __slots__ = ("ids", "n_rows")

    def __init__(self, ids: np.ndarray, n_rows: int):
        self.ids = np.asarray(ids, dtype=np.int32)
        self.n_rows = n_rows

    @property
    def words(self) -> np.ndarray:
        return np.packbits(self.to_bool())

    def _dense(self) -> Bitmap:
        return Bitmap.from_bool(self.to_bool())

    def to_bool(self) -> np.ndarray:
        mask = np.zeros(self.n_rows, dtype=bool)
        mask[self.ids] = True
        return mask

    def to_indices(self) -> np.ndarray:
        return self.ids

    def cardinality(self) -> int:
        return int(self.ids.shape[0])

    def size_bytes(self) -> int:
        return int(self.ids.nbytes)

    def __and__(self, other):
        return bitmap_and(self, other)

    def __or__(self, other):
        return bitmap_or(self, other)

    def __xor__(self, other):
        return bitmap_xor(self, other)

    def __invert__(self):
        # the complement of a sparse set is dense by definition — this is
        # the one NECESSARY densification (callers wanting only the
        # cardinality use n_rows - cardinality(), no materialization)
        return ~self._dense()

    def __eq__(self, other):
        if isinstance(other, SparseBitmap):
            return (self.n_rows == other.n_rows
                    and np.array_equal(self.ids, other.ids))
        if isinstance(other, Bitmap):
            return self._dense() == other
        return NotImplemented


AnyBitmap = Union[Bitmap, SparseBitmap]

#: a value stores sparse when 4·matches < rows/8 (int32 ids vs packed words)
SPARSE_DENSITY_DIVISOR = 32
#: default budget for LRU-cached materialized per-value bitmaps per index
BITMAP_CACHE_BUDGET = 16 << 20


# ---------------------------------------------------------------------------
# Representation-aware algebra (the Roaring container-combine capability):
# sparse×sparse stays sparse via sorted-id set ops, sparse×dense probes the
# dense words at the sparse ids — a SparseBitmap operand is never densified.
# ---------------------------------------------------------------------------

def bitmap_and(a: AnyBitmap, b: AnyBitmap) -> AnyBitmap:
    if isinstance(a, SparseBitmap) and isinstance(b, SparseBitmap):
        return SparseBitmap(np.intersect1d(a.ids, b.ids, assume_unique=True),
                            a.n_rows)
    if isinstance(b, SparseBitmap):
        a, b = b, a
    if isinstance(a, SparseBitmap):
        return SparseBitmap(a.ids[b.test_ids(a.ids)], a.n_rows)
    return a & b


def bitmap_or(a: AnyBitmap, b: AnyBitmap) -> AnyBitmap:
    if isinstance(a, SparseBitmap) and isinstance(b, SparseBitmap):
        return SparseBitmap(np.union1d(a.ids, b.ids), a.n_rows)
    if isinstance(b, SparseBitmap):
        a, b = b, a
    if isinstance(a, SparseBitmap):
        # the union is at least as dense as the dense operand: fold the
        # sparse ids into a copy of its words (per-id bit set, no unpack)
        words = b.words.copy()
        ids = a.ids.astype(np.int64)
        np.bitwise_or.at(words, ids >> 3,
                         (1 << (7 - (ids & 7))).astype(np.uint8))
        return Bitmap(words, a.n_rows)
    return a | b


def bitmap_xor(a: AnyBitmap, b: AnyBitmap) -> AnyBitmap:
    if isinstance(a, SparseBitmap) and isinstance(b, SparseBitmap):
        return SparseBitmap(np.setxor1d(a.ids, b.ids), a.n_rows)
    if isinstance(b, SparseBitmap):
        a, b = b, a
    if isinstance(a, SparseBitmap):
        words = b.words.copy()
        ids = a.ids.astype(np.int64)
        np.bitwise_xor.at(words, ids >> 3,
                          (1 << (7 - (ids & 7))).astype(np.uint8))
        return Bitmap(words, a.n_rows)._trim()
    return a ^ b


def sparse_if_small(bm: AnyBitmap) -> AnyBitmap:
    """Demote a dense result to the id-list representation when that is
    the smaller container (the Roaring array/bitmap container cutover)."""
    if isinstance(bm, SparseBitmap):
        return bm
    if bm.cardinality() < bm.n_rows // SPARSE_DENSITY_DIVISOR:
        return SparseBitmap(bm.to_indices().astype(np.int32), bm.n_rows)
    return bm


# ---------------------------------------------------------------------------
# Device representation: packed uint32 words (LSB-first — row r lives at bit
# r % 32 of word r // 32) for the device-side bitmap algebra
# (engine/filters.py). Density-adaptive shipping: a sparse bitmap ships its
# sorted id list (scattered into words ON DEVICE), a dense one ships the
# packed words directly — the host-decided Roaring container split.
# ---------------------------------------------------------------------------

#: bits per device bitmap word; checked against the engine contract on
#: first use (lazy — importing engine.contracts here at module time would
#: cycle through the engine package, the data/packed.py discipline)
WORD_BITS = 32


def _word_bits() -> int:
    from druid_tpu.engine.contracts import FILTER_WORD_BITS
    assert FILTER_WORD_BITS == WORD_BITS, \
        "data/bitmap.WORD_BITS must match contracts.FILTER_WORD_BITS"
    return WORD_BITS


def to_words32(bm: AnyBitmap, padded_rows: int) -> np.ndarray:
    """Packed uint32 row words over [0, padded_rows); rows past n_rows are
    0. padded_rows must be a multiple of 32 (any device row alignment is)."""
    assert padded_rows % _word_bits() == 0 and padded_rows >= bm.n_rows
    mask = np.zeros(padded_rows, dtype=bool)
    mask[: bm.n_rows] = bm.to_bool()
    return np.packbits(mask, bitorder="little").view(np.uint32)


def device_repr(bm: AnyBitmap, padded_rows: int):
    """("sparse", int32 ids padded to a pow2 rung with `padded_rows` as the
    out-of-range sentinel) when the id list is the smaller transfer, else
    ("dense", uint32 words). The rung quantization bounds distinct device
    shapes (compile keys) exactly like the batching row ladder."""
    m = bm.cardinality()
    rung = 8
    while rung < m:
        rung <<= 1
    if rung * 4 < padded_rows // 8:
        ids = np.full(rung, padded_rows, dtype=np.int32)
        ids[:m] = np.sort(bm.to_indices())[:m]
        return "sparse", ids
    return "dense", to_words32(bm, padded_rows)


class BitmapIndex:
    """Per-dimension inverted index: dictionary id -> row bitmap.

    Reference analog: segment/column/BitmapIndex.java:27 backed by one
    compressed bitmap per dictionary value. The index keeps ONE sorted row
    order (built lazily from the id column); per-value bitmaps materialize
    on demand — dense packed words or sparse row-id lists by density — and
    live under an LRU byte budget, so a card-5000 dim on a 12.5M-row
    segment costs ~index order (n·4B), not card · n/8 bytes."""

    def __init__(self, n_rows: int, cardinality: int,
                 bitmaps: List[Optional[AnyBitmap]],
                 ids: Optional[np.ndarray] = None):
        self.n_rows = n_rows
        self.cardinality = cardinality
        self._bitmaps = bitmaps
        self._ids = ids
        self._order: Optional[np.ndarray] = None
        self._boundaries: Optional[np.ndarray] = None
        self._lru: "collections.OrderedDict[int, int]" = \
            collections.OrderedDict()          # vid -> size_bytes
        self._cached_bytes = 0
        self._budget = BITMAP_CACHE_BUDGET
        self._lock = threading.Lock()

    @staticmethod
    def build(ids: np.ndarray, cardinality: int) -> "BitmapIndex":
        ids = np.asarray(ids)
        return BitmapIndex(int(ids.shape[0]), cardinality,
                           [None] * cardinality, ids=ids)

    # ---- lazy sorted order ---------------------------------------------
    def _sorted(self):
        if self._order is None:
            order = np.argsort(self._ids, kind="stable").astype(np.int32)
            self._boundaries = np.searchsorted(
                self._ids[order], np.arange(self.cardinality + 1))
            self._order = order
        return self._order, self._boundaries

    def _materialize(self, value_id: int) -> AnyBitmap:
        order, bounds = self._sorted()
        rows = order[bounds[value_id]:bounds[value_id + 1]]
        if rows.size < self.n_rows // SPARSE_DENSITY_DIVISOR:
            return SparseBitmap(np.sort(rows), self.n_rows)
        return Bitmap.from_indices(rows, self.n_rows)

    def _cache_put(self, value_id: int, b: AnyBitmap) -> None:
        size = b.size_bytes()
        self._bitmaps[value_id] = b
        self._lru[value_id] = size
        self._lru.move_to_end(value_id)
        self._cached_bytes += size
        while self._cached_bytes > self._budget and len(self._lru) > 1:
            vid, sz = self._lru.popitem(last=False)
            self._bitmaps[vid] = None
            self._cached_bytes -= sz

    # ---- lookups --------------------------------------------------------
    def bitmap(self, value_id: int) -> AnyBitmap:
        if value_id < 0 or value_id >= self.cardinality:
            return Bitmap.empty(self.n_rows)
        with self._lock:
            b = self._bitmaps[value_id]
            if b is not None:
                if value_id in self._lru:
                    self._lru.move_to_end(value_id)
                return b
            b = self._materialize(value_id)
            self._cache_put(value_id, b)
            return b

    def union_of(self, value_ids: np.ndarray) -> AnyBitmap:
        """Union over many values straight from the sorted row order — no
        per-value bitmaps are materialized (an OR / IN / regex over
        thousands of values touches each row id exactly once). A
        low-density result stays a SparseBitmap (id list), so downstream
        algebra and selectivity estimation never pay words for it."""
        import functools
        valid = [int(v) for v in value_ids if 0 <= v < self.cardinality]
        if not valid:
            return SparseBitmap(np.zeros(0, dtype=np.int32), self.n_rows)
        if self._ids is None:       # subclass without a backing id column
            return sparse_if_small(functools.reduce(
                bitmap_or, [self.bitmap(v) for v in valid]))
        with self._lock:
            order, bounds = self._sorted()
            parts = [order[bounds[v]:bounds[v + 1]] for v in valid]
        ids = np.concatenate(parts)
        if ids.size < self.n_rows // SPARSE_DENSITY_DIVISOR:
            return SparseBitmap(np.sort(ids).astype(np.int32), self.n_rows)
        return Bitmap.from_indices(ids, self.n_rows)

    def size_bytes(self) -> int:
        n = 0 if self._order is None else int(self._order.nbytes)
        with self._lock:
            return n + self._cached_bytes
