"""Bitmap indexes: per-dimension-value row bitmaps with AND/OR/NOT algebra.

Capability parity with the reference's CONCISE/Roaring bitmap indexes
(extendedset/src/main/java/org/apache/druid/extendedset/intset/ImmutableConciseSet.java,
processing/.../collections/bitmap/BitmapFactory.java). TPU-first design: the
bitmap index is a host-side planning structure. Bitmaps are bit-packed numpy
uint8 words (np.packbits layout); algebra is vectorized bitwise ops. The
output of filter planning is either
  * a packed bitmap shipped to the device and unpacked into a bool mask, or
  * a row-selectivity estimate used to decide bitmap-vs-device-predicate
    (the same decision as Filters.shouldUseBitmapIndex, reference
    processing/.../segment/filter/Filters.java).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class Bitmap:
    """Fixed-length packed bitset over row ids [0, n_rows)."""

    __slots__ = ("words", "n_rows")

    def __init__(self, words: np.ndarray, n_rows: int):
        assert words.dtype == np.uint8
        self.words = words
        self.n_rows = n_rows

    # ---- constructors -------------------------------------------------
    @staticmethod
    def from_bool(mask: np.ndarray) -> "Bitmap":
        mask = np.asarray(mask, dtype=bool)
        return Bitmap(np.packbits(mask), mask.shape[0])

    @staticmethod
    def from_indices(indices: np.ndarray, n_rows: int) -> "Bitmap":
        mask = np.zeros(n_rows, dtype=bool)
        mask[indices] = True
        return Bitmap.from_bool(mask)

    @staticmethod
    def empty(n_rows: int) -> "Bitmap":
        return Bitmap(np.zeros((n_rows + 7) // 8, dtype=np.uint8), n_rows)

    @staticmethod
    def full(n_rows: int) -> "Bitmap":
        b = Bitmap(np.full((n_rows + 7) // 8, 0xFF, dtype=np.uint8), n_rows)
        return b._trim()

    def _trim(self) -> "Bitmap":
        # zero the tail bits past n_rows
        extra = self.words.shape[0] * 8 - self.n_rows
        if extra:
            tail_mask = np.uint8(0xFF << extra & 0xFF)
            self.words[-1] &= tail_mask
        return self

    # ---- algebra ------------------------------------------------------
    def __and__(self, other: "Bitmap") -> "Bitmap":
        return Bitmap(self.words & other.words, self.n_rows)

    def __or__(self, other: "Bitmap") -> "Bitmap":
        return Bitmap(self.words | other.words, self.n_rows)

    def __xor__(self, other: "Bitmap") -> "Bitmap":
        return Bitmap(self.words ^ other.words, self.n_rows)

    def __invert__(self) -> "Bitmap":
        return Bitmap(~self.words, self.n_rows)._trim()

    @staticmethod
    def union(bitmaps: Sequence["Bitmap"], n_rows: int) -> "Bitmap":
        if not bitmaps:
            return Bitmap.empty(n_rows)
        out = bitmaps[0].words.copy()
        for b in bitmaps[1:]:
            np.bitwise_or(out, b.words, out=out)
        return Bitmap(out, n_rows)

    @staticmethod
    def intersection(bitmaps: Sequence["Bitmap"], n_rows: int) -> "Bitmap":
        if not bitmaps:
            return Bitmap.full(n_rows)
        out = bitmaps[0].words.copy()
        for b in bitmaps[1:]:
            np.bitwise_and(out, b.words, out=out)
        return Bitmap(out, n_rows)

    # ---- materialization ---------------------------------------------
    def to_bool(self) -> np.ndarray:
        return np.unpackbits(self.words, count=self.n_rows).astype(bool)

    def to_indices(self) -> np.ndarray:
        return np.flatnonzero(self.to_bool())

    def cardinality(self) -> int:
        return int(np.unpackbits(self.words, count=self.n_rows).sum())

    def size_bytes(self) -> int:
        return int(self.words.nbytes)

    def __eq__(self, other):
        return (isinstance(other, Bitmap) and self.n_rows == other.n_rows
                and np.array_equal(self.words, other.words))


class BitmapIndex:
    """Per-dimension inverted index: dictionary id -> row Bitmap.

    Reference analog: segment/column/BitmapIndex.java:27 backed by one
    compressed bitmap per dictionary value. Stored packed; built from the id
    column in one vectorized pass.
    """

    __slots__ = ("n_rows", "cardinality", "_bitmaps")

    def __init__(self, n_rows: int, cardinality: int, bitmaps: List[Bitmap]):
        self.n_rows = n_rows
        self.cardinality = cardinality
        self._bitmaps = bitmaps

    @staticmethod
    def build(ids: np.ndarray, cardinality: int) -> "BitmapIndex":
        n = ids.shape[0]
        # one-hot per value via sorted row ids (vectorized, O(n log n))
        order = np.argsort(ids, kind="stable")
        sorted_ids = ids[order]
        boundaries = np.searchsorted(sorted_ids, np.arange(cardinality + 1))
        bitmaps = []
        for v in range(cardinality):
            rows = order[boundaries[v]:boundaries[v + 1]]
            bitmaps.append(Bitmap.from_indices(rows, n))
        return BitmapIndex(n, cardinality, bitmaps)

    def bitmap(self, value_id: int) -> Bitmap:
        if value_id < 0 or value_id >= self.cardinality:
            return Bitmap.empty(self.n_rows)
        return self._bitmaps[value_id]

    def union_of(self, value_ids: np.ndarray) -> Bitmap:
        return Bitmap.union([self._bitmaps[int(v)] for v in value_ids
                             if 0 <= v < self.cardinality], self.n_rows)

    def size_bytes(self) -> int:
        return sum(b.size_bytes() for b in self._bitmaps)
