"""Cascaded encodings + code-domain aggregation: never decode what you
don't have to.

PR 9 proved ONE rung of the compression ladder — fixed-width bit-packing
(data/packed.py) with in-kernel decode. Following *GPU Acceleration of SQL
Analytics on Compressed Data* (PAPERS.md), this module adds the cascade on
top of it and, where the query allows, stops decoding entirely:

  * **RLE** (`RleColumn`): low-run-count int32 columns (dimension-sorted
    rollup dims, near-constant metrics) stage as run values + pow2-padded
    inclusive run ends; the traced decode is one searchsorted + gather.
    Run metadata is ~8 bytes/run vs 4 bytes/ROW decoded, so sorted real
    data multiplies the device pool's effective capacity far past the
    bit-packing ratio.
  * **delta / FOR** (`DeltaColumn` / `ForColumn`): `__time_offset` in
    rollup segments is near-constant — it stages as base-biased
    range-packed words (FOR) or width-packed non-negative deltas with an
    in-program cumsum (delta, time-ordered segments only). The derived
    `__key`/`__bucket` projection columns ride the same FOR rung
    (grouping._pad_device_cached): their range is the group/bucket space,
    known exactly at plan time.
  * **LZ4** (`Lz4Column`): cold float columns whose raw bytes compress
    ≥ 2x stay LZ4-BLOCK-compressed in HBM; the traced decoder resolves
    match back-references with a pointer-doubling shift window (log2(n)
    gathers) over the token arrays — an exact, device-side LZ4 block
    decode. Host staging comparison fallback: DRUID_TPU_LZ4=host
    decompresses on host before staging (native/druid_native.cpp or the
    pure-python codec, druid_tpu/native/lz4block.py).
  * **code-domain aggregation** (`try_run_domain`): when every referenced
    column (group dims, filter columns, aggregated values) is constant
    within one shared run partition and the query is a granularity-"all"
    dense-key aggregation whose intervals cover the segment, the whole
    grouped aggregate executes over RUN METADATA — count = Σ mask·len,
    sum = Σ value·len, min/max over run values, filters decided once per
    run (LUT gather on run values) — with NO row-width array anywhere:
    nothing decodes, nothing row-sized even stages. Exact by construction
    for count/int-sum/min/max (modular int arithmetic and identical
    identities), so results are bit-identical to the row-domain oracle.

Eligibility everywhere is a PURE function of cached column stats (run
count, value range, max delta, compressed size) with pow2-quantized
padded shapes, so plan signatures stay stable and batching shape buckets
stay shared (the data/packed.py discipline). Every encoding's descriptor
joins the device-pool staging key, the jit-cache structure signature, and
batching._Plan.digest. Opt-out: DRUID_TPU_CASCADE=0 restores the
packed-only world bit-for-bit.

The decode counter (`decode_stats`) increments at TRACE time whenever any
decode (packed/rle/delta/lz4) enters a program — the "code-domain paths
perform ZERO unpack" acceptance gate is asserted against its deltas.
"""
from __future__ import annotations

import collections
import threading
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data import packed as packed_mod
from druid_tpu.utils.emitter import Monitor

_LANE = 128

_ENABLED = os.environ.get("DRUID_TPU_CASCADE", "1").lower() \
    not in ("0", "false", "no")
#: "device" = XLA pointer-doubling decode; "host" = host-staging comparison
#: fallback (decompress before device_put); "0" = rung off
_LZ4_MODE = os.environ.get("DRUID_TPU_LZ4", "device").lower()
_STATE_LOCK = threading.Lock()

#: RLE stages only when its run metadata is at least this many times
#: smaller than the best row-width alternative (packed or decoded bytes).
RLE_MIN_WIN = 2
#: run-domain aggregation requires at least this many rows per run on
#: average — below it the row program is already cheap and the run tables
#: would churn the pool for nothing.
RUN_DOMAIN_MIN_ROWS_PER_RUN = 16
#: __time_offset cascades only when genuinely near-constant (rollup
#: segments): widths above this mean real time spread, where the decoded
#: int32 column is cheap relative to everything else staged.
TIME_MAX_WIDTH = 8
#: LZ4 stages only at a real compression win on the RAW column bytes.
LZ4_MIN_RATIO = 2.0


def set_enabled(on: bool) -> bool:
    """Flip the process-wide cascade default; returns the previous value
    (bench/test toggle, the packed.set_enabled discipline)."""
    global _ENABLED
    with _STATE_LOCK:
        prev = _ENABLED
        _ENABLED = bool(on)
        return prev


def enabled() -> bool:
    return _ENABLED


_RUN_DOMAIN = True


def set_run_domain_enabled(on: bool) -> bool:
    """Toggle ONLY the code-domain (run-space) execution path, leaving the
    cascade STAGING rungs on — tests/benches that measure staged bytes or
    the row program pin this off so an eligible shape cannot route around
    what they measure."""
    global _RUN_DOMAIN
    with _STATE_LOCK:
        prev = _RUN_DOMAIN
        _RUN_DOMAIN = bool(on)
        return prev


def run_domain_enabled() -> bool:
    return _RUN_DOMAIN


def set_lz4_mode(mode: str) -> str:
    global _LZ4_MODE
    with _STATE_LOCK:
        prev = _LZ4_MODE
        _LZ4_MODE = mode
        return prev


def lz4_mode() -> str:
    return _LZ4_MODE


def _contracts():
    # lazy: importing the engine package at data-module import time would
    # cycle (the packed.py pattern)
    from druid_tpu.engine import contracts
    return contracts


def pad_pow2(n: int, floor: int = 8) -> int:
    n = max(int(n), 1)
    p = floor
    while p < n:
        p <<= 1
    return p


# ---------------------------------------------------------------------------
# Decode counter (trace-time): the zero-unpack witness
# ---------------------------------------------------------------------------

_DECODES: "collections.Counter" = collections.Counter()
_DECODES_LOCK = threading.Lock()


def record_decode(kind: str, n: int = 1) -> None:
    """Count one decode entering a traced program. Trace-time by design:
    a jit-cache hit re-dispatches a program whose decodes were already
    counted once — zero stays zero exactly when no program containing a
    decode of that column kind was ever built."""
    with _DECODES_LOCK:
        _DECODES[kind] += n


def decode_stats() -> Dict[str, int]:
    with _DECODES_LOCK:
        return dict(_DECODES)


def reset_decode_stats() -> None:
    with _DECODES_LOCK:
        _DECODES.clear()


# ---------------------------------------------------------------------------
# Pytree registration (the packed._ensure_registered discipline)
# ---------------------------------------------------------------------------

_REGISTERED: set = set()
_REGISTER_LOCK = threading.Lock()


def _register(cls, flatten, unflatten) -> None:
    with _REGISTER_LOCK:
        if cls in _REGISTERED:
            return
        import jax

        jax.tree_util.register_pytree_node(cls, flatten, unflatten)
        _REGISTERED.add(cls)


# ---------------------------------------------------------------------------
# RleColumn
# ---------------------------------------------------------------------------

class RleColumn:
    """Run-length-encoded int column: run values + EXCLUSIVE run ends —
    ends[j] is the index one past run j's last row (start of the next
    run; the final entry equals n_rows) — both int32, pow2-padded; pad
    entries repeat the final end so the side="right" searchsorted decode
    stays monotone. rows beyond n_rows decode to the staging pad fill
    (0), exactly like decoded staging.

    `n_rows` rides as a DEVICE SCALAR leaf, not treedef aux: a
    per-segment raw row count in the aux would give every segment its
    own treedef and silently retrace the shared jitted program (the
    DeltaColumn.first rule)."""

    cascade_kind = "rle"
    __slots__ = ("values", "ends", "n_rows", "padded_rows", "dtype_str")

    def __init__(self, values, ends, n_rows, padded_rows: int,
                 dtype_str: str = "int32"):
        _register(RleColumn,
                  lambda c: ((c.values, c.ends, c.n_rows),
                             (c.padded_rows, c.dtype_str)),
                  lambda aux, leaves: RleColumn(leaves[0], leaves[1],
                                                leaves[2], *aux))
        self.values = values
        self.ends = ends
        self.n_rows = n_rows
        self.padded_rows = int(padded_rows)
        self.dtype_str = dtype_str

    @property
    def nbytes(self) -> int:
        return int(getattr(self.values, "nbytes", 0)
                   + getattr(self.ends, "nbytes", 0)
                   + getattr(self.n_rows, "nbytes", 0))

    @property
    def logical_nbytes(self) -> int:
        return int(self.padded_rows * np.dtype(self.dtype_str).itemsize)

    def __repr__(self):
        return (f"RleColumn(runs={self.values.shape[0]}, "
                f"rows={self.padded_rows}, {self.dtype_str})")


def rle_encode(values: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(run values, EXCLUSIVE run ends — start-of-next-run indices, last
    entry = row count) of a RAW (unpadded) 1-D column."""
    v = np.asarray(values)
    if v.shape[0] == 0:
        return (np.zeros(0, dtype=np.int32), np.zeros(0, dtype=np.int32))
    b = np.empty(v.shape[0], dtype=bool)
    b[0] = True
    np.not_equal(v[1:], v[:-1], out=b[1:])
    starts = np.flatnonzero(b)
    ends = np.concatenate(
        [starts[1:], [v.shape[0]]]).astype(np.int32)
    return v[starts].astype(np.int32), ends


def rle_decode_device(rc: RleColumn):
    """Traced: expand runs to the padded decoded column. Exact: real rows
    gather their run's value, pad rows read the staging fill (0)."""
    import jax.numpy as jnp

    record_decode("rle")
    iota = jnp.arange(rc.padded_rows, dtype=jnp.int32)
    idx = jnp.searchsorted(rc.ends, iota, side="right")
    idx = jnp.clip(idx, 0, rc.ends.shape[0] - 1)
    v = jnp.where(iota < rc.n_rows, rc.values[idx], 0)
    dt = jnp.dtype(rc.dtype_str)
    return v.astype(dt) if v.dtype != dt else v


# ---------------------------------------------------------------------------
# ForColumn (base-biased range-packing — PackedColumn with cascade identity)
# ---------------------------------------------------------------------------

class ForColumn(packed_mod.PackedColumn):
    """Frame-of-reference rung: exactly PackedColumn mechanics (width/base
    words, tile-planar layout, in-kernel unpack eligibility) but planned by
    the cascade ladder for columns packed.plan_column never claims —
    `__time_offset` and the derived `__key`/`__bucket` columns — and
    counted by the pool's cascade accounting."""

    cascade_kind = "for"

    def __init__(self, words, width: int, base: int, rows: int,
                 dtype_str: str = "int32"):
        _register(ForColumn,
                  lambda pc: ((pc.words,),
                              (pc.width, pc.base, pc.rows, pc.dtype_str)),
                  lambda aux, leaves: ForColumn(leaves[0], *aux))
        super().__init__(words, width, base, rows, dtype_str)


# ---------------------------------------------------------------------------
# DeltaColumn
# ---------------------------------------------------------------------------

class DeltaColumn:
    """Width-packed non-negative consecutive deltas + the first value as a
    device scalar leaf (per-segment bases must not ride the treedef, or
    every segment would compile its own program). Decode = first +
    cumsum(unpacked deltas). Monotone non-decreasing columns only
    (time-ordered `__time_offset`); pad rows repeat the last value, which
    every consumer masks."""

    cascade_kind = "delta"
    __slots__ = ("words", "first", "width", "rows", "dtype_str")

    def __init__(self, words, first, width: int, rows: int,
                 dtype_str: str = "int32"):
        _register(DeltaColumn,
                  lambda c: ((c.words, c.first),
                             (c.width, c.rows, c.dtype_str)),
                  lambda aux, leaves: DeltaColumn(leaves[0], leaves[1],
                                                  *aux))
        self.words = words
        self.first = first
        self.width = int(width)
        self.rows = int(rows)
        self.dtype_str = dtype_str

    @property
    def vpw(self) -> int:
        return _contracts().PACK_WORD_BITS // self.width

    @property
    def nbytes(self) -> int:
        return int(getattr(self.words, "nbytes", 0)
                   + getattr(self.first, "nbytes", 0))

    @property
    def logical_nbytes(self) -> int:
        return int(self.rows * np.dtype(self.dtype_str).itemsize)

    def __repr__(self):
        return f"DeltaColumn(w{self.width}, rows={self.rows})"


def delta_encode(padded: np.ndarray, n_rows: int,
                 width: int) -> Tuple[np.ndarray, np.ndarray]:
    """(packed delta words, first value) for a PADDED monotone column.
    delta[0] = 0 and pad-region deltas are forced to 0, so the decode's
    pad rows repeat the last real value deterministically."""
    v = np.asarray(padded).astype(np.int64)
    d = np.zeros_like(v)
    if v.shape[0] > 1:
        d[1:] = v[1:] - v[:-1]
    if n_rows < v.shape[0]:
        d[n_rows:] = 0
    assert d.min() >= 0 and d.max() < (1 << width), \
        "delta_encode planned on stale stats (delta out of width range)"
    return (packed_mod.pack_padded(d.astype(np.int32), width, 0),
            np.asarray(int(v[0]) if v.shape[0] else 0, dtype=np.int32))


def delta_decode_device(dc: DeltaColumn):
    """Traced: exact inverse of delta_encode (int32 cumsum; prefixes are
    value − first, which fits int32 whenever the values do)."""
    import jax.numpy as jnp

    record_decode("delta")
    width, vpw = dc.width, dc.vpw
    m = jnp.int32((1 << width) - 1)
    w2 = dc.words.reshape(-1, _LANE)
    sh = jnp.int32(width) * jnp.arange(vpw, dtype=jnp.int32)
    d = ((w2[:, None, :] >> sh[None, :, None]) & m).reshape(dc.rows)
    v = dc.first + jnp.cumsum(d, dtype=jnp.int32)
    dt = jnp.dtype(dc.dtype_str)
    return v.astype(dt) if v.dtype != dt else v


# ---------------------------------------------------------------------------
# Lz4Column
# ---------------------------------------------------------------------------

class Lz4Column:
    """An LZ4-block-compressed float column resident in HBM: the literal
    byte stream plus per-sequence token arrays (all pow2-padded). The
    traced decoder reconstructs the raw bytes exactly — literals by
    position arithmetic, matches by a pointer-doubling shift window —
    then bitcasts to the column dtype and zero-pads to the staged row
    count (bit-identical to decoded staging, padding included)."""

    cascade_kind = "lz4"
    __slots__ = ("literals", "lit_lens", "match_lens", "offsets",
                 "n_values", "padded_rows", "dtype_str")

    def __init__(self, literals, lit_lens, match_lens, offsets,
                 n_values: int, padded_rows: int, dtype_str: str):
        _register(Lz4Column,
                  lambda c: ((c.literals, c.lit_lens, c.match_lens,
                              c.offsets),
                             (c.n_values, c.padded_rows, c.dtype_str)),
                  lambda aux, leaves: Lz4Column(*leaves, *aux))
        self.literals = literals
        self.lit_lens = lit_lens
        self.match_lens = match_lens
        self.offsets = offsets
        self.n_values = int(n_values)
        self.padded_rows = int(padded_rows)
        self.dtype_str = dtype_str

    @property
    def out_bytes(self) -> int:
        return self.n_values * np.dtype(self.dtype_str).itemsize

    @property
    def nbytes(self) -> int:
        return int(sum(getattr(a, "nbytes", 0)
                       for a in (self.literals, self.lit_lens,
                                 self.match_lens, self.offsets)))

    @property
    def logical_nbytes(self) -> int:
        return int(self.padded_rows * np.dtype(self.dtype_str).itemsize)

    def __repr__(self):
        return (f"Lz4Column({self.dtype_str}[{self.n_values}], "
                f"{self.nbytes}B compressed)")


def lz4_decode_device(col: Lz4Column):
    """Traced LZ4 block decode. Match back-references resolve by pointer
    doubling: ptr[i] = i for literal bytes, i − offset for match bytes;
    log2(out_bytes) rounds of ptr = ptr[ptr] reach the literal fixpoint
    every chain ends at (overlapping matches included — the chain is the
    sequential copy's data dependency, followed transitively)."""
    import jax
    import jax.numpy as jnp

    record_decode("lz4")
    nb = col.out_bytes
    T = int(col.lit_lens.shape[0])
    ll = col.lit_lens
    tok_total = ll + col.match_lens
    csum = jnp.cumsum(tok_total, dtype=jnp.int32)
    out_start = csum - tok_total
    tok_end = csum
    lit_start = jnp.cumsum(ll, dtype=jnp.int32) - ll
    i = jnp.arange(nb, dtype=jnp.int32)
    t = jnp.clip(jnp.searchsorted(tok_end, i, side="right"), 0, T - 1)
    rel = i - out_start[t]
    is_lit = rel < ll[t]
    litpos = jnp.where(is_lit, lit_start[t] + rel, 0)
    ptr = jnp.where(is_lit, i, i - col.offsets[t])
    ptr = jnp.clip(ptr, 0, nb - 1)
    for _ in range(max(int(nb - 1).bit_length(), 1)):
        ptr = ptr[ptr]
    raw = col.literals[jnp.clip(litpos[ptr], 0,
                                col.literals.shape[0] - 1)]
    itemsize = np.dtype(col.dtype_str).itemsize
    b = raw.astype(jnp.uint32).reshape(-1, itemsize)
    if itemsize == 4:
        word = b[:, 0]
        for s in range(1, 4):
            word = word | (b[:, s] << jnp.uint32(8 * s))
        v = jax.lax.bitcast_convert_type(word, jnp.dtype(col.dtype_str))
    else:
        # float64 needs real uint64 lanes — x64 is globally on
        # (engine/__init__), asserted so a silent 32-bit truncation can
        # never corrupt the reconstruction
        assert jax.config.jax_enable_x64, "lz4 float64 decode needs x64"
        u64 = b[:, 0].astype(jnp.uint64)
        for s in range(1, 8):
            u64 = u64 | (b[:, s].astype(jnp.uint64) << jnp.uint64(8 * s))
        v = jax.lax.bitcast_convert_type(u64, jnp.dtype(col.dtype_str))
    pad = col.padded_rows - col.n_values
    if pad:
        v = jnp.concatenate([v, jnp.zeros((pad,), v.dtype)])
    return v


# ---------------------------------------------------------------------------
# Cached column stats + encodings (host, per segment)
# ---------------------------------------------------------------------------

def column_run_count(segment, name: str) -> int:
    """Cached run count of a column's RAW values (dims: dictionary ids)."""
    def _compute():
        col = segment.dims.get(name)
        v = col.ids if col is not None else segment.metrics[name].values
        if v.shape[0] == 0:
            return 0
        return 1 + int(np.count_nonzero(v[1:] != v[:-1]))
    return segment.aux_cached(("cascade_runs", name), _compute)


def _rle_encoded(segment, name: str) -> Tuple[np.ndarray, np.ndarray]:
    """Cached (values, ends) of a column's raw run encoding."""
    def _compute():
        col = segment.dims.get(name)
        v = col.ids if col is not None else segment.metrics[name].values
        return rle_encode(v)
    return segment.aux_cached(("cascade_rleenc", name), _compute)


def column_run_info(segment, name: str, max_runs: Optional[int] = None
                    ) -> Optional[Tuple[np.ndarray, np.ndarray, int]]:
    """(run values, EXCLUSIVE run ends, n_runs) when `name` is run-compressible
    (run count within `max_runs`, default n_rows // 8 capped at
    CASCADE_MAX_RUNS), else None. The RLE-run-aware filter path and the
    run-domain planner both ask this."""
    if name in segment.dims:
        pass
    elif name not in segment.metrics:
        return None
    nr = column_run_count(segment, name)
    if nr == 0:
        return None
    cap = _contracts().CASCADE_MAX_RUNS
    limit = min(max(segment.n_rows // 8, 1), cap) if max_runs is None \
        else min(max_runs, cap)
    if nr > limit:
        return None
    values, ends = _rle_encoded(segment, name)
    return values, ends, nr


def _time_stats(segment) -> Tuple[int, int, int]:
    """(min offset, max offset, max consecutive delta or -1 when not
    monotone/unknown) — all O(1)-amortized cached stats."""
    t0 = segment.interval.start
    lo = segment.min_time - t0
    hi = segment.max_time - t0

    def _compute():
        if not segment.time_ordered or segment.n_rows < 2:
            return 0 if segment.time_ordered else -1
        return int(np.max(np.diff(segment.time_ms)))
    md = segment.aux_cached(("cascade_tdelta",), _compute)
    return int(lo), int(hi), md


def _lz4_stat(segment, name: str) -> Tuple[int, int, int]:
    """Cached (raw bytes, compressed bytes, padded token count) of a float
    column; compressed = 0 marks a failed/unprofitable codec round-trip
    (the rung silently disables for that column)."""
    def _compute():
        from druid_tpu.native import lz4block
        raw = np.ascontiguousarray(segment.metrics[name].values).tobytes()
        try:
            comp = lz4block.compress(raw)
            if lz4block.decompress(comp, len(raw)) != raw:
                return (len(raw), 0, 0)
            lits, ll, ml, off = lz4block.tokenize(comp)
        except (ValueError, IndexError):
            return (len(raw), 0, 0)
        return (len(raw), len(comp), pad_pow2(ll.shape[0]))
    return segment.aux_cached(("cascade_lz4stat", name), _compute)


def _lz4_encoded(segment, name: str):
    """Cached pow2-padded token arrays (literals, lit_lens, match_lens,
    offsets, n_values) for a planned lz4 column."""
    def _compute():
        from druid_tpu.native import lz4block
        vals = np.ascontiguousarray(segment.metrics[name].values)
        comp = lz4block.compress(vals.tobytes())
        lits, ll, ml, off = lz4block.tokenize(comp)
        tp = pad_pow2(ll.shape[0])
        lp = pad_pow2(max(lits.shape[0], 1))

        def padto(a, n, dt):
            out = np.zeros(n, dtype=dt)
            out[: a.shape[0]] = a
            return out
        return (padto(lits, lp, np.uint8), padto(ll, tp, np.int32),
                padto(ml, tp, np.int32), padto(off, tp, np.int32),
                int(vals.shape[0]))
    return segment.aux_cached(("cascade_lz4enc", name), _compute)


# ---------------------------------------------------------------------------
# Planning (pure functions of cached stats; pow2-quantized shapes)
# ---------------------------------------------------------------------------

def _plan_time(segment) -> Optional[Tuple]:
    if segment.n_rows == 0:
        return None
    lo, hi, md = _time_stats(segment)
    base = (1 << (lo.bit_length() - 1)) if lo > 0 else 0
    wf = packed_mod.width_for(hi, base)
    wd = packed_mod.width_for(md, 0) if md >= 0 else 0
    if wf > TIME_MAX_WIDTH:
        wf = 0
    if wd > TIME_MAX_WIDTH:
        wd = 0
    if wd and (not wf or wd < wf):
        return ("delta", wd)
    if wf:
        return ("for", wf, base)
    return None


def _plan_rle(segment, name: str) -> Optional[Tuple]:
    nr = column_run_count(segment, name)
    if nr == 0:
        return None
    padded_runs = pad_pow2(nr)
    if padded_runs > _contracts().CASCADE_MAX_RUNS:
        return None
    rle_bytes = padded_runs * 8           # two int32 arrays
    p = packed_mod.plan_column(segment, name)
    alt_bytes = segment.n_rows * p[0] // 8 if p is not None \
        else segment.n_rows * 4
    if rle_bytes * RLE_MIN_WIN > alt_bytes:
        return None
    return ("rle", padded_runs)


def _plan_lz4(segment, name: str) -> Optional[Tuple]:
    if lz4_mode() not in ("device", "host"):
        return None
    raw, comp, tpad = _lz4_stat(segment, name)
    if not comp or comp * LZ4_MIN_RATIO > raw:
        return None
    if tpad > _contracts().CASCADE_MAX_RUNS:
        return None
    if lz4_mode() == "host":
        return ("lz4host",)
    lits, ll, ml, off, nv = _lz4_encoded(segment, name)
    # n_values joins the descriptor: it is STATIC decode shape (the
    # byte-domain iota/pointer arrays), so two stagings share a program
    # only when it matches — the recompile is visible in the signature
    # instead of a silent treedef retrace
    return ("lz4", int(lits.shape[0]), int(ll.shape[0]), int(nv))


def plan_column(segment, name: str) -> Optional[Tuple]:
    """Cascade descriptor entry tail for one column, or None. Pure in the
    packed.plan_column sense: identical cached stats give identical plans
    on every execution path."""
    if name == "__time_offset":
        return _plan_time(segment)
    if name in segment.dims:
        return _plan_rle(segment, name)
    m = segment.metrics.get(name)
    if m is None:
        return None
    # plan from COLUMN METADATA, not np.asarray(m.values): lazy format-V2
    # columns must be plannable without materializing decoded rows (the
    # zero-host-decode load path)
    from druid_tpu.data.segment import ValueType
    t = getattr(m, "type", None)
    if t is ValueType.LONG:
        if segment.staged_dtype(name) != np.int32:
            return None
        return _plan_rle(segment, name)
    if t in (ValueType.FLOAT, ValueType.DOUBLE):
        return _plan_lz4(segment, name)
    return None                           # complex states: stage as-is


def plan_columns(segment, columns: Sequence[str],
                 permuted: bool = False) -> Tuple:
    """((name, kind, *params), ...) for the cascade-eligible subset of
    `columns` plus `__time_offset` (always staged), sorted by name; ()
    when cascading is disabled or the staging layout is permuted (a row
    permutation destroys run structure). This tuple IS the cascade
    descriptor: it joins the device-pool staging key, the jit-cache
    structure signature, and batching._Plan.digest alongside the pack
    descriptor."""
    if not _ENABLED or permuted:
        return ()
    out = []
    for c in sorted(set(columns) | {"__time_offset"}):
        p = plan_column(segment, c)
        if p is not None:
            out.append((c,) + p)
    return tuple(out)


def plan_pair(segment, columns: Sequence[str],
              permuted: bool = False) -> Tuple[Tuple, Tuple]:
    """(cascade descriptor, pack descriptor) with cascade claims excluded
    from packing — THE one derivation every path (device_block staging,
    per-segment planning, batching digests) shares, so a column is staged
    under exactly one encoding everywhere."""
    cascades = plan_columns(segment, columns, permuted)
    claimed = {e[0] for e in cascades}
    packs = packed_mod.plan_columns(
        segment, [c for c in columns if c not in claimed])
    return cascades, packs


def descriptor_to_json(entries: Tuple) -> list:
    """JSON form of a cascade/pack descriptor tuple (format V2 persists the
    staging plan alongside the parts, so `segment inspect` and the loader
    can show/validate exactly what was encoded)."""
    return [list(e) for e in entries]


def descriptor_from_json(obj) -> Tuple:
    """Exact inverse of descriptor_to_json (tuples restored, so the result
    is hashable and == the original plan_pair output)."""
    return tuple(tuple(e) for e in obj)


# ---------------------------------------------------------------------------
# Staging-time encoding (data/segment._stage_block)
# ---------------------------------------------------------------------------

def encode_column(segment, name: str, entry: Tuple, padded: np.ndarray,
                  put):
    """Encode one planned column for staging. `padded` is the padded host
    array decoded staging would ship; `put` is the caller's device_put."""
    kind = entry[1]
    if kind == "rle":
        values, ends = _rle_encoded(segment, name)
        rpad = entry[2]

        def padto(a, fill):
            out = np.full(rpad, fill, dtype=np.int32)
            out[: a.shape[0]] = a
            return out
        n_rows = int(ends[-1]) if ends.shape[0] else 0
        return RleColumn(put(padto(values, 0)),
                         put(padto(ends, n_rows)),
                         put(np.asarray(n_rows, dtype=np.int32)),
                         int(padded.shape[0]), str(padded.dtype))
    if kind == "for":
        w, base = entry[2], entry[3]
        words = packed_mod.pack_padded(padded, w, base)
        return ForColumn(put(words), w, base, int(padded.shape[0]),
                         str(padded.dtype))
    if kind == "delta":
        w = entry[2]
        words, first = delta_encode(padded, segment.n_rows, w)
        return DeltaColumn(put(words), put(first), w,
                           int(padded.shape[0]), str(padded.dtype))
    if kind == "lz4":
        lits, ll, ml, off, nv = _lz4_encoded(segment, name)
        return Lz4Column(put(lits), put(ll), put(ml), put(off), nv,
                         int(padded.shape[0]), str(padded.dtype))
    if kind == "lz4host":
        # host-staging comparison fallback: round-trip through the codec
        # on host, then stage decoded — the bus/HBM baseline the device
        # decode is measured against
        from druid_tpu.native import lz4block
        vals = np.ascontiguousarray(segment.metrics[name].values)
        raw = lz4block.decompress(lz4block.compress(vals.tobytes()),
                                  vals.nbytes)
        dec = np.frombuffer(raw, dtype=vals.dtype)
        out = np.zeros(padded.shape[0], dtype=vals.dtype)
        out[: dec.shape[0]] = dec
        return put(out)
    raise AssertionError(f"unknown cascade kind {kind!r}")


def for_encode_derived(lo: int, hi: int) -> Optional[Tuple]:
    """(width, base) when a derived int32 column with values in [lo, hi]
    (the `__key`/`__bucket` projection columns — range known exactly at
    plan time) range-packs, else None."""
    if not _ENABLED:
        return None
    base = int(lo)
    w = packed_mod.width_for(int(hi), base)
    return (w, base) if w else None


# ---------------------------------------------------------------------------
# Program-top decode (the one split every execution path calls)
# ---------------------------------------------------------------------------

def split_resident(arrays: Dict) -> Tuple[Dict, Dict]:
    """Superset of packed.split_packed: (packed columns for the pallas
    word path — ForColumn included, its layout IS the packed layout —,
    dense view with every cascade/packed entry decoded). The ONE decode
    entry point, so the decode story cannot diverge across paths."""
    packed_cols: Dict = {}
    out = dict(arrays)
    changed = False
    for k, v in arrays.items():
        if isinstance(v, RleColumn):
            out[k] = rle_decode_device(v)
            changed = True
        elif isinstance(v, DeltaColumn):
            out[k] = delta_decode_device(v)
            changed = True
        elif isinstance(v, Lz4Column):
            out[k] = lz4_decode_device(v)
            changed = True
        elif isinstance(v, packed_mod.PackedColumn):
            packed_cols[k] = v
            out[k] = packed_mod.unpack_device(v)
            changed = True
    return packed_cols, (out if changed else arrays)


# ---------------------------------------------------------------------------
# Code-domain aggregation stats (query/codeDomain/* metrics)
# ---------------------------------------------------------------------------

class CodeDomainStats:
    """hits = segment executions served fully in run space (no row-width
    array staged or decoded); rows = logical rows those executions
    covered."""

    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0
        self.rows = 0

    def record(self, rows: int) -> None:
        with self._lock:
            self.hits += 1
            self.rows += int(rows)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "rows": self.rows}


_CODE_STATS = CodeDomainStats()


def code_domain_stats() -> CodeDomainStats:
    return _CODE_STATS


class CodeDomainMonitor(Monitor):
    """Emits query/codeDomain/{hits,rows} per tick (deltas over the tick
    window, the FilterBitmapMonitor discipline)."""

    def __init__(self, source: Optional[CodeDomainStats] = None):
        self.source = source or _CODE_STATS
        self._last = self.source.snapshot()

    def do_monitor(self, emitter):
        s = self.source.snapshot()
        last, self._last = self._last, s
        emitter.metric("query/codeDomain/hits", s["hits"] - last["hits"])
        emitter.metric("query/codeDomain/rows", s["rows"] - last["rows"])


# ---------------------------------------------------------------------------
# Run-domain (code-domain) aggregation
# ---------------------------------------------------------------------------

@dataclass
class _RunKernel:
    """Run-space execution plan for one kernel: the kernel itself, the
    run columns it reads (empty for count/const-sum/missing-column
    kernels — the latter aggregate to zeros/identity without any run
    table), plus a re-planned (column-domain, whitelisted) filter tree
    for FilteredKernel chains."""
    kernel: object
    cols: frozenset = frozenset()
    fnode: object = None                  # run-space filter node or None
    child: Optional["_RunKernel"] = None

    def sig(self) -> str:
        if self.child is not None:
            f = self.fnode.signature() if self.fnode is not None else "none"
            return f"rfiltered({f},{self.child.sig()})"
        return self.kernel.signature()

    def aux(self) -> List[np.ndarray]:
        out: List[np.ndarray] = []
        if self.child is not None:
            if self.fnode is not None:
                out.extend(self.fnode.aux_arrays())
            out.extend(self.child.aux())
            return out
        k = self.kernel
        if getattr(k, "const_value", None) is not None:
            out.append(np.asarray(k.const_value, dtype=np.int64))
        return out

    def columns(self) -> set:
        if self.child is not None:
            cols = set(self.child.columns())
            if self.fnode is not None:
                cols |= self.fnode.required_device_columns()
            return cols
        return set(self.cols)


_RUN_JIT_CACHE: "collections.OrderedDict[str, object]" = \
    collections.OrderedDict()
_RUN_JIT_CACHE_CAP = 64
_RUN_JIT_CACHE_LOCK = threading.Lock()


def _run_filter_ok(node) -> bool:
    """Whitelist: node kinds whose build() reads only per-run-constant
    columns (LUT/numeric compares over run values) — no expressions
    (absolute __time is row-space) and no word-domain nodes (bitmap words
    are row-space by definition)."""
    from druid_tpu.engine.filters import (AndNode, ConstNode, LutNode,
                                          NotNode, NumericCmpNode,
                                          NumericEqNode, NumericInNode,
                                          OrNode)
    if node is None:
        return True
    if isinstance(node, (AndNode, OrNode)):
        return all(_run_filter_ok(c) for c in node.children)
    if isinstance(node, NotNode):
        return _run_filter_ok(node.child)
    return isinstance(node, (ConstNode, LutNode, NumericEqNode,
                             NumericInNode, NumericCmpNode))


def _plan_run_kernel(k, segment) -> Optional[_RunKernel]:
    from druid_tpu.engine.filters import plan_filter, simplify_node
    from druid_tpu.engine.kernels import (CountKernel, FilteredKernel,
                                          MinMaxKernel, SumKernel)
    from druid_tpu.data.segment import ValueType
    if isinstance(k, FilteredKernel):
        child = _plan_run_kernel(k.child, segment)
        if child is None:
            return None
        # re-plan from the SPEC with device_bitmap off: the kernel's own
        # planned tree may carry word-domain nodes
        fnode = simplify_node(plan_filter(k.spec.filter, segment,
                                          device_bitmap=False))
        if not _run_filter_ok(fnode):
            return None
        return _RunKernel(kernel=k, fnode=fnode, child=child)
    if isinstance(k, CountKernel):
        return _RunKernel(kernel=k)
    if isinstance(k, SumKernel):
        if k.vtype is not ValueType.LONG:
            return None                   # float sums reorder: row path
        if k.const_value is not None:
            return _RunKernel(kernel=k)
        f = k.spec.field
        if f in segment.dims:
            return None
        m = segment.metrics.get(f)
        if m is None:
            return _RunKernel(kernel=k)   # missing column sums to zeros
        if getattr(m, "type", None) is not ValueType.LONG:
            return None                   # metadata check: lazy V2 columns
        return _RunKernel(kernel=k, cols=frozenset({f}))
    if isinstance(k, MinMaxKernel):
        f = k.spec.field
        if f in segment.dims:
            return None
        if f not in segment.metrics:
            return _RunKernel(kernel=k)   # missing column: identity state
        return _RunKernel(kernel=k, cols=frozenset({f}))
    return None


def _run_update(rk: _RunKernel, arrays: Dict, mask, key, lens,
                num: int, it):
    """Traced per-kernel run-space update; state shapes/dtypes are exactly
    the row path's update() shapes, so host_post/combine/merge compose
    unchanged (the bit-parity contract)."""
    import jax
    import jax.numpy as jnp
    from druid_tpu.engine.kernels import (CountKernel, MinMaxKernel,
                                          SumKernel)

    if rk.child is not None:
        fmask = mask
        if rk.fnode is not None:
            fmask = mask & rk.fnode.build(arrays, it)
        return _run_update(rk.child, arrays, fmask, key, lens, num, it)
    k = rk.kernel
    if isinstance(k, CountKernel):
        # counts fit int32 (≤ n_rows < 2^31): same dtype as the row path
        return jax.ops.segment_sum(
            jnp.where(mask, lens, 0), key, num_segments=num)
    if isinstance(k, SumKernel):
        if k.const_value is not None:
            c = next(it)
            cnt = jax.ops.segment_sum(
                jnp.where(mask, lens, 0), key, num_segments=num)
            return cnt.astype(jnp.int64) * c
        f = k.spec.field
        if f not in arrays:
            return jnp.zeros((num,), dtype=jnp.int64)
        # Σ v·len ≡ per-row Σ v (mod 2^64): identical to the row path even
        # at wraparound; x64 is globally on (engine/__init__)
        v = arrays[f].astype(jnp.int64) * lens.astype(jnp.int64)
        return jax.ops.segment_sum(jnp.where(mask, v, 0), key,
                                   num_segments=num)
    assert isinstance(k, MinMaxKernel)
    f = k.spec.field
    if f not in arrays:
        return jnp.asarray(np.broadcast_to(k.empty_state(1), (num,)))
    v = arrays[f]
    if jnp.issubdtype(v.dtype, jnp.integer):
        info = jnp.iinfo(v.dtype)
        ident = jnp.asarray(info.min if k.is_max else info.max,
                            dtype=v.dtype)
    else:
        ident = jnp.asarray(-jnp.inf if k.is_max else jnp.inf,
                            dtype=v.dtype)
    v = jnp.where(mask, v, ident)
    return (jax.ops.segment_max if k.is_max else jax.ops.segment_min)(
        v, key, num_segments=num)


def _build_run_fn(dim_cols: Tuple, has_remap: Tuple, filter_node,
                  rkernels: List[_RunKernel], num_total: int,
                  has_bucket: bool = False):
    import jax
    import jax.numpy as jnp

    def fn(arrays: Dict, aux: Tuple):
        it = iter(aux)
        lens = arrays["__runlen"]
        mask = lens > 0                   # zero-length pad runs drop out
        arrays = dict(arrays)
        arrays["__valid"] = mask          # ConstNode's shape anchor
        if has_bucket:
            # uniform granularity: the bucket id is run-constant by
            # partition construction — it rides as a staged per-run table
            # (pad runs carry -1) and seeds the fused key exactly like the
            # row program's device bucket math
            key = arrays["__runbucket"]
            mask = mask & (key >= 0)
            key = jnp.maximum(key, 0)
        else:
            key = jnp.zeros(lens.shape, dtype=jnp.int32)
        for col, remap in zip(dim_cols, has_remap):
            if col is None:
                continue
            ids = arrays[col]
            if remap:
                r = next(it)
                ids = r[ids]
                mask = mask & (ids >= 0)
            card = next(it)
            key = key * card + jnp.maximum(ids, 0)
        if filter_node is not None:
            mask = mask & filter_node.build(arrays, it)
        key = jnp.clip(key, 0, num_total - 1).astype(jnp.int32)
        counts = jax.ops.segment_sum(jnp.where(mask, lens, 0), key,
                                     num_segments=num_total)
        states = tuple(_run_update(rk, arrays, mask, key, lens,
                                   num_total, it) for rk in rkernels)
        return counts, states

    return jax.jit(fn)


def run_domain_probe(segment, intervals, granularity, spec, kernels,
                     flt, virtual_columns) -> bool:
    """Cheap eligibility-only check (batching._plan_for routes eligible
    segments to the per-segment path so run_grouped_aggregate can take the
    code-domain shortcut)."""
    return _plan_run_domain(segment, intervals, granularity, spec,
                            kernels, flt, virtual_columns) is not None


def _plan_run_domain(segment, intervals, granularity, spec, kernels,
                     flt, virtual_columns):
    """None, or (dim structure, run filter node, run kernels, run columns,
    partition key) when the whole grouped aggregate can run over run
    metadata. Memoized on the (single-use — grouping.GroupPlan contract)
    spec: batching's eligibility probe and run_grouped_aggregate's
    execution hook share one planning pass instead of re-planning the
    filter and kernels on the hot path."""
    cached = getattr(spec, "_cascade_run_plan", None)
    if cached is not None:
        return cached[0]
    plan = _plan_run_domain_uncached(segment, intervals, granularity,
                                     spec, kernels, flt, virtual_columns)
    spec._cascade_run_plan = (plan,)
    return plan


def _plan_run_domain_uncached(segment, intervals, granularity, spec,
                              kernels, flt, virtual_columns):
    if not _ENABLED or not _RUN_DOMAIN or segment.n_rows == 0 \
            or virtual_columns:
        return None
    if spec.bucket_mode not in ("all", "uniform") \
            or spec.key_mode != "dense":
        return None
    if not any(iv.start <= segment.min_time and iv.end > segment.max_time
               for iv in intervals):
        return None                       # the time mask must be all-true
    if any(d.host_ids is not None for d in spec.dims):
        return None
    # uniform granularities ride run space too, when their bucket
    # boundaries provably align with run boundaries: the per-row bucket id
    # JOINS the joint run partition, so alignment is exactly the condition
    # that the joint run count stays within the profitability cap — a
    # granularity fine enough to split runs row-by-row prices itself out
    # and falls back to the row program (the ROADMAP item-3 rung)
    bucket = None
    if spec.bucket_mode == "uniform":
        if granularity is None or not granularity.is_uniform \
                or spec.num_buckets < 1:
            return None
        first = int(spec.bucket_starts[0])
        bucket = (first, int(granularity.period_ms), int(spec.num_buckets))
    cols = set()
    for d in spec.dims:
        if d.column is not None:
            if d.column not in segment.dims:
                return None
            cols.add(d.column)
    from druid_tpu.engine.filters import plan_filter, simplify_node
    fnode = simplify_node(plan_filter(flt, segment, device_bitmap=False)) \
        if flt is not None else None
    if not _run_filter_ok(fnode):
        return None
    if fnode is not None:
        cols |= fnode.required_device_columns()
    rkernels = []
    for k in kernels:
        rk = _plan_run_kernel(k, segment)
        if rk is None:
            return None
        rkernels.append(rk)
        cols |= rk.columns()
    for c in cols:
        if c not in segment.dims and c not in segment.metrics:
            return None
    pkey = tuple(sorted(cols))
    # the shared run partition: joint change points of EVERY referenced
    # column — and, for uniform granularities, of the bucket id (cached
    # per column set + bucket signature)
    info = _joint_runs(segment, pkey, bucket)
    if info is None:
        return None
    return (tuple(d.column for d in spec.dims),
            tuple(d.remap is not None for d in spec.dims),
            fnode, rkernels, pkey, bucket, info)


def _joint_runs(segment, pkey: Tuple[str, ...],
                bucket: Optional[Tuple[int, int, int]] = None):
    """Cached (starts, lengths, n_runs) of the joint run partition over
    the named columns (plus, when `bucket` = (first, period, B), the
    uniform-granularity bucket id), or None when too fine-grained to
    pay."""
    def _col_change_starts(c) -> np.ndarray:
        # RLE fast path: a column's change points ARE its run starts, so a
        # column with (cached or format-V2-seeded) run tables contributes
        # them directly — no row scan, no lazy-column materialization
        info = column_run_info(segment, c)
        if info is not None:
            _, ends, nr = info
            return ends[:nr - 1].astype(np.int64) if nr > 1 \
                else np.zeros(0, dtype=np.int64)
        col = segment.dims.get(c)
        v = col.ids if col is not None else segment.metrics[c].values
        return (np.flatnonzero(v[1:] != v[:-1]) + 1).astype(np.int64)

    def _compute():
        n = segment.n_rows
        chunks = [np.zeros(1, dtype=np.int64)]
        chunks.extend(_col_change_starts(c) for c in pkey)
        if bucket is not None:
            first, period, _ = bucket
            bid = (segment.time_ms - first) // period
            chunks.append(
                (np.flatnonzero(bid[1:] != bid[:-1]) + 1).astype(np.int64))
        starts = np.unique(np.concatenate(chunks)).astype(np.int32)
        lengths = np.diff(np.concatenate(
            [starts, [n]])).astype(np.int32)
        return starts, lengths, int(starts.shape[0])
    # cache identity = what the change points actually depend on: bucket
    # BOUNDARIES are (first mod period, period) — a rolling covering
    # window whose start shifts by whole periods reuses the partition
    # instead of re-scanning n_rows and duplicating aux entries
    bkey = None if bucket is None else (bucket[0] % bucket[1], bucket[1])
    starts, lengths, nr = segment.aux_cached(
        ("cascade_runpart", pkey, bkey), _compute)
    cap = _contracts().CASCADE_MAX_RUNS
    if nr > cap or nr * RUN_DOMAIN_MIN_ROWS_PER_RUN > segment.n_rows:
        return None
    return starts, lengths, nr


def _values_at_starts(segment, name: str, starts: np.ndarray, dt):
    """Per-run value of a run-constant column at the joint-partition run
    starts. Columns with run tables (cached, or format-V2-seeded on a lazy
    column) answer via searchsorted over the tables — the mmap-to-HBM path
    never touches decoded rows; everything else gathers from the host
    column. The table path only serves int32-staged columns: rle_encode
    narrows run values to int32, which is exact only there."""
    if dt == np.int32:
        info = column_run_info(segment, name)
        if info is not None:
            rv, ends, nr = info
            idx = np.searchsorted(ends[:nr], starts, side="right")
            return rv[np.minimum(idx, nr - 1)].astype(np.int32)
    col = segment.dims.get(name)
    v = (col.ids if col is not None
         else segment.metrics[name].values)[starts]
    return v.astype(dt) if v.dtype != dt else v


def try_run_domain(segment, intervals, granularity, spec, kernels, flt,
                   virtual_columns):
    """Execute one segment's grouped aggregation fully in run space when
    eligible; returns (counts, device states) or None. Zero decode, zero
    row-width staging — the run tables (a few KB) are the only device
    data, resident in the pool like any derived column."""
    plan = _plan_run_domain(segment, intervals, granularity, spec,
                            kernels, flt, virtual_columns)
    if plan is None:
        return None
    dim_cols, has_remap, fnode, rkernels, pkey, bucket, info = plan
    starts, lengths, nr = info
    rpad = pad_pow2(nr)

    import jax

    # the staging identity must name the PARTITION, not just the column
    # set: a uniform-granularity partition of the same columns has
    # different run tables than the all-granularity one
    part_key = (pkey, bucket)

    def _staged(colname: str, values: np.ndarray, fill=0):
        def _build(v=values):
            out = np.full(rpad, fill, dtype=v.dtype)
            out[: v.shape[0]] = v
            return jax.device_put(out)
        return segment.device_cached(("rundom", part_key, rpad, colname),
                                     _build)

    arrays: Dict[str, object] = {
        "__runlen": _staged("__runlen", lengths)}
    if bucket is not None:
        first, period, _nb = bucket
        bid = ((segment.time_ms[starts] - first) // period).astype(np.int32)
        arrays["__runbucket"] = _staged("__runbucket", bid, fill=-1)
    cols = set(pkey)
    for c in cols:
        dt = np.int32 if c in segment.dims else segment.staged_dtype(c)
        arrays[c] = _staged(c, _values_at_starts(segment, c, starts, dt))

    aux: List[np.ndarray] = []
    for d in spec.dims:
        if d.column is None:
            continue
        if d.remap is not None:
            aux.append(d.remap.astype(np.int32))
        aux.append(np.asarray(d.cardinality, dtype=np.int32))
    if fnode is not None:
        aux.extend(fnode.aux_arrays())
    for rk in rkernels:
        aux.extend(rk.aux())

    sig = "|".join([
        "rundomain",
        f"dims={','.join(f'{c}:{int(r)}' for c, r in zip(dim_cols, has_remap))}",
        f"filt={fnode.signature() if fnode is not None else 'none'}",
        f"aggs={';'.join(rk.sig() for rk in rkernels)}",
        f"total={spec.num_total}", f"R={rpad}",
        f"ub={int(bucket is not None)}",
    ])
    with _RUN_JIT_CACHE_LOCK:
        fn = _RUN_JIT_CACHE.get(sig)
        compiled = fn is None
        if fn is None:
            fn = _build_run_fn(dim_cols, has_remap, fnode, rkernels,
                               spec.num_total,
                               has_bucket=bucket is not None)
            _RUN_JIT_CACHE[sig] = fn
            while len(_RUN_JIT_CACHE) > _RUN_JIT_CACHE_CAP:
                _RUN_JIT_CACHE.popitem(last=False)
        else:
            _RUN_JIT_CACHE.move_to_end(sig)

    from druid_tpu.obs import dispatch as dispatch_mod
    from druid_tpu.obs.trace import span as trace_span
    from druid_tpu.obs.trace import span_when as trace_span_when
    with trace_span("engine/dispatch", strategy="runDomain",
                    rows=segment.n_rows, runs=nr, compile=compiled), \
            trace_span_when(compiled, "engine/compile", kind="segment",
                            strategy="runDomain"):
        counts, states = fn(arrays, tuple(aux))
    dispatch_mod.record("runDomain")
    _CODE_STATS.record(segment.n_rows)
    return counts, states
