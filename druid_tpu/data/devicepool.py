"""Process-wide byte-budgeted pool of device-resident segment data.

Reference analog: the historicals keeping segments mmapped and page-cached
under one OS-level memory budget (SegmentLoaderLocalCacheManager + the page
cache), rather than each segment bounding its own little cache. TPU-first
translation: staged DeviceBlocks and derived padded device arrays pin HBM;
the pool LRU-evicts by ACTUAL array bytes against one configurable budget,
so cache pressure is a single observable number instead of per-segment
entry counts (the old count-capped Segment._device_cache).

Entries are owned by a Segment (via an opaque owner token); a segment being
garbage-collected purges its entries through a weakref finalizer, so dropped
segment generations release HBM without any explicit unload call.

Stats (hits/misses/evictions/evictedBytes/residentBytes) feed the
`segment/devicePool/*` emitter metrics (DevicePoolMonitor below, wired by
cluster/dataserver.py).
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import weakref
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Set, Tuple

from druid_tpu.obs.trace import span as trace_span
from druid_tpu.utils.emitter import Monitor

#: key[0] marker for stacked sharded-execution blocks
#: (parallel/distributed.py stack owner) — entries so marked feed the
#: PoolStats.stacked_* accounting alongside the shared byte budget
STACKED_KIND = "shardStack"


def _default_budget() -> int:
    # capacity bound only: the budget sizes the pool and its eviction,
    # it never reaches a traced program (catalog: live, no key_member)
    env = os.environ.get("DRUID_TPU_DEVICE_POOL_BYTES")  # druidlint: disable=env-flag-latch
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    # lazy: importing the engine package at module-import time would cycle
    # (engine -> data.segment -> devicepool); at first-use time the engine
    # is importable and its x64 side effect is the intended global anyway
    from druid_tpu.engine.contracts import DEVICE_POOL_BUDGET_BYTES
    return DEVICE_POOL_BUDGET_BYTES


def _fold_entry(value, measure) -> int:
    """THE one recursive walker over a pool entry's structure —
    DeviceBlocks (their array dict), dicts, tuples/lists — summing
    `measure(leaf)`; `measure` returns None to recurse into a node, and
    unmeasurable leaves count 0. Every accounting view (actual bytes,
    decoded-equivalent bytes, cascade bytes) folds through here, so a new
    container shape added once covers all of them."""
    if value is None:
        return 0
    got = measure(value)
    if got is not None:
        return int(got)
    arrays = getattr(value, "arrays", None)
    if isinstance(arrays, dict):
        value = arrays
    if isinstance(value, dict):
        return sum(_fold_entry(v, measure) for v in value.values())
    if isinstance(value, (tuple, list)):
        return sum(_fold_entry(v, measure) for v in value)
    return 0


def _measure_nbytes(v):
    # containers have no nbytes; anything that does is a leaf
    if isinstance(v, (dict, tuple, list)) or hasattr(v, "arrays"):
        return None
    return getattr(v, "nbytes", None)


class LogicalBytes:
    """Accounting-only leaf: contributes `logical_nbytes` to the
    decoded-equivalent accounting and zero actual bytes. Builders of
    BATCHED entries ride one in their value: a stacked sharded block's
    column objects carry per-SEGMENT aux (rows=R — the vmapped decode
    needs it), so their logical_nbytes describes one segment while their
    leaves hold K; this leaf restores the missing (K-1) share so
    packed/stacked ratios stay honest."""

    __slots__ = ("logical_nbytes",)
    nbytes = 0

    def __init__(self, logical_nbytes: int):
        self.logical_nbytes = int(logical_nbytes)


def entry_bytes(value) -> int:
    """Actual device bytes a pool entry pins: DeviceBlocks count their
    array dict, containers count their leaves, arrays their nbytes.
    PackedColumn/cascade entries (and any pytree mixing compressed words
    with aux arrays) count their COMPRESSED bytes — the pool budgets what
    HBM actually holds, so effective capacity multiplies by the ratio."""
    return _fold_entry(value, _measure_nbytes)


def entry_logical_bytes(value) -> int:
    """Decoded-equivalent bytes of a pool entry: what the same data would
    pin if staged fully decoded. Equals entry_bytes for plain arrays;
    packed/cascade columns report rows × element width. logical / actual
    is the pool's packedRatio — the effective-capacity multiplier."""
    def measure(v):
        logical = getattr(v, "logical_nbytes", None)
        if logical is not None:
            return logical
        return _measure_nbytes(v)
    return _fold_entry(value, measure)


def entry_cascade_bytes(value) -> Tuple[int, int]:
    """(actual, decoded-equivalent) bytes of the CASCADE-encoded leaves of
    a pool entry (data/cascade.py RLE/delta/FOR/LZ4 columns, marked by
    `cascade_kind`). Their ratio is the pool's cascadeRatio — the
    capacity multiplier the cascade rungs specifically add on top of
    bit-packing."""
    def cascade_leaf(attr):
        def measure(v):
            if getattr(v, "cascade_kind", None) is not None:
                return getattr(v, attr, 0)
            return None if isinstance(v, (dict, tuple, list)) \
                or hasattr(v, "arrays") else 0
        return measure
    return (_fold_entry(value, cascade_leaf("nbytes")),
            _fold_entry(value, cascade_leaf("logical_nbytes")))


@dataclass
class PoolStats:
    hits: int = 0
    misses: int = 0
    evictions: int = 0
    evicted_bytes: int = 0
    resident_bytes: int = 0
    logical_bytes: int = 0
    cascade_bytes: int = 0
    cascade_logical_bytes: int = 0
    stacked_bytes: int = 0
    stacked_logical_bytes: int = 0
    stacked_entries: int = 0
    entries: int = 0
    budget_bytes: int = 0

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    @property
    def packed_ratio(self) -> float:
        """Decoded-equivalent bytes / actual resident bytes: 1.0 when
        nothing is packed, the effective-capacity multiplier otherwise."""
        return self.logical_bytes / self.resident_bytes \
            if self.resident_bytes else 1.0

    @property
    def cascade_ratio(self) -> float:
        """Decoded-equivalent / actual bytes over CASCADE-encoded entries
        only (1.0 when nothing cascade-encoded is resident)."""
        return self.cascade_logical_bytes / self.cascade_bytes \
            if self.cascade_bytes else 1.0

    @property
    def stacked_ratio(self) -> float:
        """Decoded-equivalent / actual bytes over the STACKED sharded
        blocks only (query/sharded/packedRatio — 1.0 when nothing is
        stacked): how much HBM the compressed-resident stacking saves a
        pod versus the old decoded host-stack."""
        return self.stacked_logical_bytes / self.stacked_bytes \
            if self.stacked_bytes else 1.0


class DeviceSegmentPool:
    """Byte-budgeted LRU over (owner, key) -> device value."""

    def __init__(self, budget_bytes: Optional[int] = None):
        self._budget = budget_bytes            # None -> resolve lazily
        self._lock = threading.Lock()
        # key -> (value, actual_bytes, logical_bytes,
        #         cascade_actual_bytes, cascade_logical_bytes)
        self._entries: "collections.OrderedDict[Tuple, Tuple]" \
            = collections.OrderedDict()
        self._owner_keys: Dict[int, Set[Tuple]] = {}
        self._owner_seq = itertools.count(1)
        # weakref finalizers ONLY append here (deque.append is atomic and
        # takes no lock): a finalizer can fire at any allocation point —
        # including while this thread already holds self._lock — so a
        # finalizer that acquired the lock would self-deadlock. Dead owners
        # are drained under the lock at the next pool operation.
        self._dead_owners: "collections.deque[int]" = collections.deque()
        self._resident = 0
        self._logical = 0
        self._cascade = 0
        self._cascade_logical = 0
        self._stacked = 0
        self._stacked_logical = 0
        self._stacked_entries = 0
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._evicted_bytes = 0

    # ---- configuration --------------------------------------------------
    @property
    def budget_bytes(self) -> int:
        """Resolved budget; <= 0 means unbounded (no eviction)."""
        if self._budget is None:
            self._budget = _default_budget()
        return self._budget

    def configure(self, budget_bytes: Optional[int]) -> None:
        """Set the byte budget (None re-resolves env/contract default;
        <= 0 disables eviction) and trims immediately."""
        with self._lock:
            self._drain_dead_locked()
            self._budget = budget_bytes
            budget = self.budget_bytes
            if budget > 0:
                self._evict_to(budget, keep=None)

    # ---- owner registry -------------------------------------------------
    def register_owner(self, obj) -> int:
        """Opaque token for `obj`'s entries; a weakref finalizer marks it
        dead when `obj` is collected (dropped segments release HBM at the
        next pool touch). The token's presence in the owner registry IS the
        liveness bit get_or_build checks before caching."""
        with self._lock:
            self._drain_dead_locked()
            token = next(self._owner_seq)
            self._owner_keys.setdefault(token, set())
        weakref.finalize(obj, self._note_dead, token)
        return token

    def _note_dead(self, owner: int) -> None:
        """Finalizer target. MUST NOT touch self._lock: finalizers run at
        arbitrary allocation points, including under this very lock."""
        # the lock-free write is the point: deque.append is atomic, and a
        # finalizer taking self._lock would self-deadlock when GC fires
        # inside a locked region
        self._dead_owners.append(owner)  # druidlint: disable=unguarded-shared-write

    def _drain_dead_locked(self) -> int:
        """Caller holds the lock. Purge every finalizer-reported owner."""
        freed = 0
        while True:
            try:
                owner = self._dead_owners.popleft()
            except IndexError:
                break
            freed += self._purge_locked(owner)
        return freed

    @staticmethod
    def _is_stacked(full_key: Tuple) -> bool:
        # full_key = (owner,) + key; stacked blocks lead their key with
        # STACKED_KIND (the distributed.py stack owner's convention)
        return len(full_key) > 1 and full_key[1] == STACKED_KIND

    def _forget_stacked(self, full_key: Tuple, entry: Tuple) -> None:
        """Caller holds the lock and just removed `entry` under
        `full_key` — every removal path (purge, take, evict, replace)
        funnels here so the stacked counters cannot drift."""
        if self._is_stacked(full_key):
            self._stacked -= entry[1]
            self._stacked_logical -= entry[2]
            self._stacked_entries -= 1

    def _purge_locked(self, owner: int) -> int:
        freed = 0
        for key in self._owner_keys.pop(owner, ()):
            value = self._entries.pop(key, None)
            if value is not None:
                freed += value[1]
                self._logical -= value[2]
                self._cascade -= value[3]
                self._cascade_logical -= value[4]
                self._forget_stacked(key, value)
        self._resident -= freed
        return freed

    def purge_owner(self, owner: int) -> int:
        """Drop every entry owned by `owner` NOW; returns bytes released.
        Purges are bookkeeping, not cache pressure: they do not count as
        evictions. Removing the owner's registry slot also marks it dead,
        so an in-flight get_or_build cannot resurrect its entries (a late
        insert after the owner died would pin HBM forever)."""
        with self._lock:
            return self._purge_locked(owner)

    # ---- cache surface --------------------------------------------------
    def peek(self, owner: int, key: Tuple) -> bool:
        """Residency probe WITHOUT touching LRU order or hit/miss stats —
        callers keeping their own cache metrics (the filter-bitmap cache's
        query/filter/* counters) ask this before get_or_build so the pool's
        segment/devicePool/* accounting is not double-counted."""
        with self._lock:
            return ((owner,) + tuple(key)) in self._entries

    def get_or_build(self, owner: int, key: Tuple, build: Callable[[], object]):
        """LRU get; on miss, `build()` runs OUTSIDE the lock (staging does
        device_put) — a concurrent duplicate build wastes work but cannot
        corrupt the accounting (the replaced entry's bytes are subtracted)."""
        full_key = (owner,) + tuple(key)
        with self._lock:
            self._drain_dead_locked()
            hit = self._entries.get(full_key)
            if hit is not None:
                self._entries.move_to_end(full_key)
                self._hits += 1
                return hit[0]
            self._misses += 1
        # cold miss: the H2D staging cost a warm pool hides. The span times
        # the whole build (host prep + device_put) at its existing boundary
        with trace_span("pool/h2d",
                        kind=str(key[0]) if key else "") as sp:
            value = build()
            nbytes = entry_bytes(value)
            logical = entry_logical_bytes(value)
            casc, casc_logical = entry_cascade_bytes(value)
            if sp is not None:
                # "bytes" is what actually crossed the bus (compressed for
                # packed entries); logicalBytes the decoded-equivalent size
                sp.attrs["bytes"] = nbytes
                sp.attrs["logicalBytes"] = logical
        with self._lock:
            self._drain_dead_locked()
            keys = self._owner_keys.get(owner)
            if keys is None:
                # owner purged while build() ran (segment GC'd mid-query):
                # hand the value back WITHOUT caching — its finalizer will
                # never run again, so a cached entry would leak HBM
                return value
            old = self._entries.pop(full_key, None)
            if old is not None:
                self._resident -= old[1]
                self._logical -= old[2]
                self._cascade -= old[3]
                self._cascade_logical -= old[4]
                self._forget_stacked(full_key, old)
            self._entries[full_key] = (value, nbytes, logical, casc,
                                       casc_logical)
            keys.add(full_key)
            self._resident += nbytes
            self._logical += logical
            self._cascade += casc
            self._cascade_logical += casc_logical
            if self._is_stacked(full_key):
                self._stacked += nbytes
                self._stacked_logical += logical
                self._stacked_entries += 1
            budget = self.budget_bytes
            if budget > 0:
                self._evict_to(budget, keep=full_key)
        return value

    def take(self, owner: int, key: Tuple):
        """Remove and return an entry's value (None when absent). The
        megakernel's donated-carry handoff: the previous execution's
        partial buffers pop out so they can be DONATED back into the next
        program — on accelerator backends donation invalidates the
        buffers, so they must leave the pool before the call. Stats-free
        like peek(): carry probes are handoff mechanics, not staging-cache
        outcomes, and must not skew segment/devicePool hit/miss series.
        Never counts as an eviction either.

        Ownership contract (donorguard): a successful take POPS ownership
        to the caller, who owes a re-park (get_or_build/device_cached), a
        return, or an explicit discard on every path — the static
        take-without-repark rule and the DRUID_TPU_DONOR_WITNESS=1
        dynamic witness (tools/druidlint/donorwitness.py) both enforce
        it, the witness by tracking the popped leaves' identity."""
        full_key = (owner,) + tuple(key)
        with self._lock:
            self._drain_dead_locked()
            entry = self._entries.pop(full_key, None)
            if entry is None:
                return None
            self._owner_keys.get(owner, set()).discard(full_key)
            self._resident -= entry[1]
            self._logical -= entry[2]
            self._cascade -= entry[3]
            self._cascade_logical -= entry[4]
            self._forget_stacked(full_key, entry)
            return entry[0]

    def _evict_to(self, budget: int, keep: Optional[Tuple]) -> None:
        """Caller holds the lock. `keep` (the just-inserted entry) survives
        even when it alone exceeds the budget — the query running right now
        must not have its own block evicted from under it."""
        while self._resident > budget and self._entries:
            key = next(iter(self._entries))
            if key == keep:
                if len(self._entries) == 1:
                    return
                self._entries.move_to_end(key)
                continue
            entry = self._entries.pop(key)
            _, nbytes, logical, casc, casc_logical = entry
            # key[0] is the owner token (get_or_build prefixes it)
            self._owner_keys.get(key[0], set()).discard(key)
            self._resident -= nbytes
            self._logical -= logical
            self._cascade -= casc
            self._cascade_logical -= casc_logical
            self._forget_stacked(key, entry)
            self._evictions += 1
            self._evicted_bytes += nbytes

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            # keep owner slots (liveness bits) — only their key sets drop;
            # clearing slots would permanently refuse live segments' inserts
            for keys in self._owner_keys.values():
                keys.clear()
            self._resident = 0
            self._logical = 0
            self._cascade = 0
            self._cascade_logical = 0
            self._stacked = 0
            self._stacked_logical = 0
            self._stacked_entries = 0

    # ---- observability --------------------------------------------------
    def snapshot(self) -> PoolStats:
        with self._lock:
            self._drain_dead_locked()
            return PoolStats(hits=self._hits, misses=self._misses,
                             evictions=self._evictions,
                             evicted_bytes=self._evicted_bytes,
                             resident_bytes=self._resident,
                             logical_bytes=self._logical,
                             cascade_bytes=self._cascade,
                             cascade_logical_bytes=self._cascade_logical,
                             stacked_bytes=self._stacked,
                             stacked_logical_bytes=self._stacked_logical,
                             stacked_entries=self._stacked_entries,
                             entries=len(self._entries),
                             budget_bytes=self.budget_bytes)


_POOL = DeviceSegmentPool()


def device_pool() -> DeviceSegmentPool:
    """The process-wide pool every Segment stages through."""
    return _POOL


class DevicePoolMonitor(Monitor):
    """Emits `segment/devicePool/*` metrics per tick: the hit RATE over the
    tick window (only when there was traffic — an idle pool emits no rate),
    delta hit/miss/evicted counters, and resident gauges."""

    def __init__(self, pool: Optional[DeviceSegmentPool] = None):
        self.pool = pool or device_pool()
        self._last = PoolStats()

    def do_monitor(self, emitter):
        s = self.pool.snapshot()
        last, self._last = self._last, s
        d_hits = s.hits - last.hits
        d_misses = s.misses - last.misses
        if d_hits + d_misses > 0:
            emitter.metric("segment/devicePool/hitRate",
                           d_hits / (d_hits + d_misses))
        emitter.metric("segment/devicePool/hits", d_hits)
        emitter.metric("segment/devicePool/misses", d_misses)
        emitter.metric("segment/devicePool/evictedBytes",
                       s.evicted_bytes - last.evicted_bytes)
        emitter.metric("segment/devicePool/residentBytes", s.resident_bytes)
        emitter.metric("segment/devicePool/entries", s.entries)
        emitter.metric("segment/devicePool/packedRatio", s.packed_ratio)
        emitter.metric("segment/devicePool/cascadeRatio", s.cascade_ratio)
