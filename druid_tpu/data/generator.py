"""Synthetic segment generator for tests and benchmarks.

Capability parity with the reference's BenchmarkDataGenerator
(benchmarks/src/main/java/org/apache/druid/benchmark/datagen/BenchmarkDataGenerator.java
+ SegmentGenerator.java): distribution-controlled column value generation used
by the JMH suites (GroupByBenchmark.java:118-136 schema "basic.A").
Vectorized with numpy instead of per-row Java generators.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data.dictionary import Dictionary
from druid_tpu.data.segment import (NumericColumn, Segment, SegmentBuilder,
                                    SegmentId, StringDimColumn, ValueType)
from druid_tpu.utils.intervals import Interval


@dataclass(frozen=True)
class ColumnSpec:
    """One generated column.

    kind: "string" (dictionary dim), "long", "float", "double"
    distribution: "uniform" | "zipf" | "sequential" | "normal" | "enumerated"
    """
    name: str
    kind: str = "string"
    cardinality: int = 100          # for string dims
    distribution: str = "uniform"
    zipf_exponent: float = 1.5
    low: float = 0.0
    high: float = 100.0
    mean: float = 0.0
    std: float = 1.0
    values: Tuple[str, ...] = ()    # for enumerated
    probabilities: Tuple[float, ...] = ()


# "basic.A"-style default schema (reference GroupByBenchmark schemas)
BASIC_SCHEMA = (
    ColumnSpec("dimSequential", "string", cardinality=1000, distribution="sequential"),
    ColumnSpec("dimZipf", "string", cardinality=101, distribution="zipf"),
    ColumnSpec("dimUniform", "string", cardinality=100000, distribution="uniform"),
    ColumnSpec("metLongUniform", "long", low=0, high=500),
    ColumnSpec("metFloatNormal", "float", distribution="normal", mean=5000.0, std=1.0),
    ColumnSpec("sumLongSequential", "long", distribution="sequential", low=0, high=10000),
    ColumnSpec("sumFloatNormal", "float", distribution="normal", mean=0.0, std=100.0),
)


def _string_dictionary(card: int, width: int = 8) -> Dictionary:
    # zero-padded decimal strings sort lexicographically == numerically
    return Dictionary([f"v{idx:0{width}d}" for idx in range(card)])


class DataGenerator:
    def __init__(self, columns: Sequence[ColumnSpec] = BASIC_SCHEMA, seed: int = 9999):
        self.columns = list(columns)
        self.rng = np.random.default_rng(seed)
        self._dicts: Dict[str, Dictionary] = {
            c.name: (Dictionary(sorted(set(c.values))) if c.distribution == "enumerated"
                     else _string_dictionary(c.cardinality))
            for c in self.columns if c.kind == "string"
        }

    @property
    def dictionaries(self) -> Dict[str, Dictionary]:
        return dict(self._dicts)

    def _gen_ids(self, spec: ColumnSpec, n: int, card: int) -> np.ndarray:
        rng = self.rng
        if spec.distribution == "sequential":
            return (np.arange(n, dtype=np.int64) % card).astype(np.int32)
        if spec.distribution == "zipf":
            # bounded zipf over [0, card)
            ranks = np.arange(1, card + 1, dtype=np.float64)
            probs = ranks ** (-spec.zipf_exponent)
            probs /= probs.sum()
            return rng.choice(card, size=n, p=probs).astype(np.int32)
        if spec.distribution == "enumerated":
            probs = np.asarray(spec.probabilities, dtype=np.float64)
            probs /= probs.sum()
            return rng.choice(card, size=n, p=probs).astype(np.int32)
        return rng.integers(0, card, size=n).astype(np.int32)

    def _gen_numeric(self, spec: ColumnSpec, n: int) -> np.ndarray:
        rng = self.rng
        if spec.distribution == "sequential":
            span = max(int(spec.high - spec.low), 1)
            vals = spec.low + (np.arange(n, dtype=np.int64) % span)
        elif spec.distribution == "normal":
            vals = rng.normal(spec.mean, spec.std, size=n)
        elif spec.distribution == "zipf":
            vals = rng.zipf(spec.zipf_exponent, size=n).astype(np.float64)
        else:
            vals = rng.uniform(spec.low, spec.high, size=n)
        if spec.kind == "long":
            return np.asarray(vals, dtype=np.int64)
        if spec.kind == "float":
            return np.asarray(vals, dtype=np.float32)
        return np.asarray(vals, dtype=np.float64)

    def segment(self, n_rows: int, interval: Interval,
                datasource: str = "bench", version: str = "v1",
                partition: int = 0, sort_by_dims: bool = False) -> Segment:
        """Generate one segment with rows spread uniformly over `interval`.

        sort_by_dims=True writes rows in the reference's rollup sort order
        (IndexMergerV9 orders rows by dimension values within a time bucket,
        segment/IndexMergerV9.java:729; with a coarse queryGranularity that
        is dimension-first order) — the layout our ingestion path produces
        and the one the windowed grouped-reduction strategy exploits."""
        span = max(interval.width, 1)
        time_ms = interval.start + (
            np.sort(self.rng.integers(0, span, size=n_rows)).astype(np.int64))
        dims: Dict[str, StringDimColumn] = {}
        metrics: Dict[str, NumericColumn] = {}
        for spec in self.columns:
            if spec.kind == "string":
                d = self._dicts[spec.name]
                ids = self._gen_ids(spec, n_rows, d.cardinality)
                dims[spec.name] = StringDimColumn(ids, d)
            else:
                vtype = ValueType(spec.kind)
                metrics[spec.name] = NumericColumn(self._gen_numeric(spec, n_rows), vtype)
        if sort_by_dims and dims:
            order = np.lexsort(tuple(
                d.ids for d in reversed(list(dims.values()))))
            time_ms = time_ms[order]
            for d in dims.values():
                d.ids = d.ids[order]
            for m in metrics.values():
                m.values = m.values[order]
        sid = SegmentId(datasource, interval, version, partition)
        # sorted_by_time=True skips Segment's time re-sort; dim-sorted
        # layouts are flagged time_ordered=False so nothing mistakes them
        # for time-monotonic data
        return Segment(sid, time_ms, dims, metrics, sorted_by_time=True,
                       time_ordered=not sort_by_dims)

    def segments(self, n_segments: int, rows_per_segment: int,
                 start: Interval, datasource: str = "bench",
                 sort_by_dims: bool = False) -> List[Segment]:
        """Generate n segments over consecutive sub-intervals sharing dictionaries
        (shared dictionaries enable the on-device collective merge path)."""
        width = start.width // n_segments
        out = []
        for i in range(n_segments):
            iv = Interval(start.start + i * width, start.start + (i + 1) * width)
            out.append(self.segment(rows_per_segment, iv, datasource=datasource,
                                    partition=0, version="v1",
                                    sort_by_dims=sort_by_dims))
        return out
