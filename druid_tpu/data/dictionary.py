"""Sorted string dictionary for dictionary-encoded dimension columns.

Capability parity with the reference's GenericIndexed<String> dictionary
(processing/src/main/java/org/apache/druid/segment/data/GenericIndexed.java:79
— binary-searchable sorted value index). TPU-first difference: the dictionary
lives host-side only; the device only ever sees int32 id columns. All string
predicates (selector/bound/in/like/regex/search) are evaluated host-side
against the (small) dictionary to produce a boolean lookup table that the
device applies via one gather — see druid_tpu/engine/filters.py.
"""
from __future__ import annotations

import bisect
from typing import Iterable, List, Optional, Sequence

import numpy as np

NULL = ""  # reference treats null and empty string equivalently (pre-0.13 semantics)


class Dictionary:
    """Immutable sorted list of unique strings with O(log n) lookup."""

    __slots__ = ("values", "_index")

    def __init__(self, sorted_values: Sequence[str]):
        self.values: List[str] = list(sorted_values)
        self._index = {v: i for i, v in enumerate(self.values)}

    @staticmethod
    def from_values(values: Iterable[Optional[str]]) -> "Dictionary":
        uniq = {NULL if v is None else str(v) for v in values}
        return Dictionary(sorted(uniq))

    @property
    def cardinality(self) -> int:
        return len(self.values)

    def id_of(self, value: Optional[str]) -> int:
        """id of value, or -1 if absent."""
        if value is None:
            value = NULL
        return self._index.get(value, -1)

    def value_of(self, idx: int) -> str:
        return self.values[idx]

    def encode(self, values: Iterable[Optional[str]]) -> np.ndarray:
        """Encode values to int32 ids (must all be present)."""
        idx = self._index
        return np.fromiter(
            (idx[NULL if v is None else str(v)] for v in values),
            dtype=np.int32,
        )

    def id_range(self, lower: Optional[str], upper: Optional[str],
                 lower_strict: bool = False, upper_strict: bool = False):
        """[lo, hi) id range for a lexicographic bound — bound filters on
        sorted dictionaries become id-range predicates (the same trick as
        the reference's BoundFilter + GenericIndexed.indexOf)."""
        lo = 0
        hi = len(self.values)
        if lower is not None:
            lo = (bisect.bisect_right if lower_strict else bisect.bisect_left)(
                self.values, lower)
        if upper is not None:
            hi = (bisect.bisect_left if upper_strict else bisect.bisect_right)(
                self.values, upper)
        return lo, max(hi, lo)

    def __len__(self):
        return len(self.values)

    def __iter__(self):
        return iter(self.values)

    def __contains__(self, v):
        return v in self._index

    def __eq__(self, other):
        return isinstance(other, Dictionary) and self.values == other.values

    def __hash__(self):
        return hash(tuple(self.values))


def merge_dictionaries(dicts: Sequence[Dictionary]):
    """Merge per-segment dictionaries into one global dictionary plus per-input
    id remap tables (old_id -> new_id), the role DimensionMergerV9 plays during
    segment merge (reference: processing/.../segment/DimensionMergerV9.java).
    """
    merged = sorted(set().union(*[set(d.values) for d in dicts])) if dicts else []
    out = Dictionary(merged)
    remaps = []
    for d in dicts:
        remaps.append(np.asarray([out.id_of(v) for v in d.values], dtype=np.int32))
    return out, remaps
