from druid_tpu.data.dictionary import Dictionary
from druid_tpu.data.bitmap import BitmapIndex
from druid_tpu.data.segment import (
    Segment, SegmentBuilder, SegmentSchema, ColumnCapabilities, ValueType,
    SegmentId, DeviceBlock,
)
from druid_tpu.data.generator import DataGenerator, ColumnSpec

__all__ = [
    "Dictionary", "BitmapIndex", "Segment", "SegmentBuilder", "SegmentSchema",
    "ColumnCapabilities", "ValueType", "SegmentId", "DeviceBlock",
    "DataGenerator", "ColumnSpec",
]
