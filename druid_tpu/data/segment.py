"""Segments: immutable columnar data blocks, host-resident with device staging.

Capability parity with the reference's QueryableIndex / StorageAdapter surface
(processing/src/main/java/org/apache/druid/segment/QueryableIndex.java:38,
StorageAdapter.java:33) and the V9 column model (segment/column/Column.java:27-52).

TPU-first design, replacing the per-row Cursor pull model:
  * A Segment holds host numpy columns: int32 dictionary ids for string dims
    (sorted dictionary, host-side only), int64/float32/float64 numerics, and
    an int64 `__time` column sorted ascending.
  * `device_block(block_rows)` stages the segment as a DeviceBlock — dense
    jax arrays padded to a static shape (a multiple of the TPU lane tiling)
    plus a validity mask — so XLA compiles exactly one program per
    (query shape, schema, block shape). This replaces Cursor iteration; the
    jit cache plays the role of the reference's ASM monomorphic
    specialization (query/monomorphicprocessing/SpecializationService.java:65).
  * Time on device is an int32 offset from the segment interval start, so no
    64-bit arithmetic is needed in kernels; bucketing for uniform
    granularities is one integer divide on device.
"""
from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data.bitmap import BitmapIndex
from druid_tpu.data.dictionary import Dictionary, NULL
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval

# f32 min tile is (8, 128); pad row counts to a multiple of 8*128 so 1-D
# columns reshape cleanly into (sublane, lane) tiles on device.
DEFAULT_ROW_ALIGN = 1024


class ValueType(enum.Enum):
    STRING = "string"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    COMPLEX = "complex"

    @property
    def numpy_dtype(self):
        return {
            ValueType.LONG: np.int64,
            ValueType.FLOAT: np.float32,
            ValueType.DOUBLE: np.float64,
        }[self]


@dataclass(frozen=True)
class ColumnCapabilities:
    """Reference analog: segment/column/ColumnCapabilities.java."""
    type: ValueType
    dictionary_encoded: bool = False
    has_bitmap_index: bool = False
    has_multiple_values: bool = False


@dataclass(frozen=True)
class SegmentId:
    """Reference analog: DataSegment identity (api/.../DataSegment)."""
    datasource: str
    interval: Interval
    version: str
    partition: int = 0

    def __str__(self):
        return (f"{self.datasource}_{self.interval}_{self.version}"
                f"_{self.partition}")


@dataclass(frozen=True)
class SegmentSchema:
    """Ordered dim names + metric (name -> type) map."""
    dimensions: Tuple[str, ...]
    metrics: Tuple[Tuple[str, ValueType], ...]

    @property
    def metric_types(self) -> Dict[str, ValueType]:
        return dict(self.metrics)


class StringDimColumn:
    """Dictionary-encoded single-value string dimension."""

    __slots__ = ("ids", "dictionary", "_bitmap_index", "_lock")

    def __init__(self, ids: np.ndarray, dictionary: Dictionary):
        assert ids.dtype == np.int32
        self.ids = ids
        self.dictionary = dictionary
        self._bitmap_index: Optional[BitmapIndex] = None
        self._lock = threading.Lock()

    @property
    def cardinality(self) -> int:
        return self.dictionary.cardinality

    def bitmap_index(self) -> BitmapIndex:
        # built lazily, like the reference mmaps bitmaps on demand
        with self._lock:
            if self._bitmap_index is None:
                self._bitmap_index = BitmapIndex.build(self.ids, self.cardinality)
            return self._bitmap_index

    def set_bitmap_index(self, index: BitmapIndex):
        # same lock as the lazy build: an unlocked store here could be
        # overwritten by a concurrent bitmap_index() builder (or hand a
        # half-published index to it)
        with self._lock:
            self._bitmap_index = index

    def capabilities(self) -> ColumnCapabilities:
        return ColumnCapabilities(ValueType.STRING, dictionary_encoded=True,
                                  has_bitmap_index=True)


class NumericColumn:
    __slots__ = ("values", "type")

    def __init__(self, values: np.ndarray, vtype: ValueType):
        self.values = values
        self.type = vtype

    def capabilities(self) -> ColumnCapabilities:
        return ColumnCapabilities(self.type)


class ComplexColumn:
    """Fixed-width complex metric column: one row = one state vector
    (e.g. HLL registers int8[2^log2m]). Reference analog: ComplexColumn +
    ComplexColumnPartSerde (segment/serde/ComplexColumnPartSerde.java) —
    here states are dense 2-D arrays so device kernels reduce them directly
    (HLL merge = segment_max over rows)."""

    __slots__ = ("values", "type_name")
    type = ValueType.COMPLEX

    def __init__(self, values: np.ndarray, type_name: str):
        assert values.ndim == 2
        self.values = values
        self.type_name = type_name

    def capabilities(self) -> ColumnCapabilities:
        return ColumnCapabilities(ValueType.COMPLEX)


class _ShapeStub:
    """Stands in for a padded host array during staging when the encoder
    needs only its shape/dtype (cascade rle/lz4 columns encode from cached
    run/token tables; persisted format-V2 pack words upload directly).
    Keeps lazy columns lazy: the decoded rows are never built."""

    __slots__ = ("shape", "dtype")

    def __init__(self, n: int, dtype):
        self.shape = (n,)
        self.dtype = np.dtype(dtype)


@dataclass
class DeviceBlock:
    """A segment staged on device as padded dense arrays (all length `padded_rows`).

    arrays:
      "__time_offset": int32 millis from `time0`
      "<dim>":         int32 dictionary ids
      "<metric>":      int64 / float32 / float64 values
      "__valid":       bool row-validity mask (False on padding rows)

    Pack-eligible dim/metric entries may instead be data/packed.py
    PackedColumn values (bit-packed int32 words + descriptor, a jax
    pytree): compressed in HBM, decoded inside the traced program.
    """
    segment_id: SegmentId
    n_rows: int
    padded_rows: int
    time0: int
    arrays: Dict[str, object]
    dictionaries: Dict[str, Dictionary]


class Segment:
    """Immutable columnar segment (host representation)."""

    def __init__(self, segment_id: SegmentId, time_ms: np.ndarray,
                 dims: Dict[str, StringDimColumn],
                 metrics: Dict[str, NumericColumn],
                 sorted_by_time: bool = True,
                 time_ordered: Optional[bool] = None):
        """sorted_by_time=False re-sorts rows by timestamp. sorted_by_time=True
        means "do not re-sort"; pass time_ordered=False alongside it when the
        preserved layout is NOT time-monotonic (e.g. dimension-sorted rollup
        order) so time-pruning optimizations cannot assume monotonicity."""
        self.id = segment_id
        self.time_ms = np.asarray(time_ms, dtype=np.int64)
        self.dims = dims
        self.metrics = metrics
        self.n_rows = int(self.time_ms.shape[0])
        if not sorted_by_time and self.n_rows:
            order = np.argsort(self.time_ms, kind="stable")
            self.time_ms = self.time_ms[order]
            for d in dims.values():
                d.ids = d.ids[order]
            for m in metrics.values():
                m.values = m.values[order]
            time_ordered = True
        #: rows are time-monotonic (safe for searchsorted-style pruning)
        self.time_ordered = True if time_ordered is None else bool(time_ordered)
        self.min_time = int(self.time_ms.min()) if self.n_rows else 0
        self.max_time = int(self.time_ms.max()) if self.n_rows else 0
        # device-resident data (staged blocks, padded device keys) lives in
        # the process-wide byte-budgeted pool: one HBM budget across all
        # segments, LRU by actual bytes, entries purged when this segment
        # is collected (data/devicepool.py)
        from druid_tpu.data.devicepool import device_pool
        self._pool = device_pool()
        self._pool_owner = self._pool.register_owner(self)
        self._aux_cache: Dict[Tuple, object] = {}
        self._lock = threading.Lock()

    # ---- schema/introspection -----------------------------------------
    @property
    def schema(self) -> SegmentSchema:
        return SegmentSchema(tuple(self.dims.keys()),
                             tuple((k, v.type) for k, v in self.metrics.items()))

    @property
    def interval(self) -> Interval:
        return self.id.interval

    def column_capabilities(self, name: str) -> Optional[ColumnCapabilities]:
        if name == "__time":
            return ColumnCapabilities(ValueType.LONG)
        if name in self.dims:
            return self.dims[name].capabilities()
        if name in self.metrics:
            return self.metrics[name].capabilities()
        return None

    def dictionary(self, dim: str) -> Optional[Dictionary]:
        col = self.dims.get(dim)
        return col.dictionary if col else None

    def numeric_values(self, name: str) -> Optional[np.ndarray]:
        col = self.metrics.get(name)
        return col.values if col else None

    # ---- device staging ------------------------------------------------
    def device_block(self, columns: Optional[Sequence[str]] = None,
                     row_align: int = DEFAULT_ROW_ALIGN,
                     device=None, perm: Optional[np.ndarray] = None,
                     perm_key=None) -> DeviceBlock:
        """Stage (a subset of) columns to device, padded to static shape.

        Staging is cached per (columns, row_align, device, perm_key, pack
        descriptor) in the process-wide byte-budgeted device pool; repeated
        queries over the same segment hit HBM-resident arrays — the analog
        of the reference keeping segments mmapped and page-cached
        (server/.../SegmentLoaderLocalCacheManager.java).

        Cascade-eligible columns (data/cascade.py — low-run-count dims and
        int32 metrics as RLE, near-constant `__time_offset` as delta/FOR,
        compressible floats as LZ4 tokens) stage under their cascade
        encoding; pack-eligible columns (data/packed.py — narrow
        dictionary ids, small-range int32-staged longs) stage as
        bit-packed PackedColumn words. Both selections are pure functions
        of column stats (cascade.plan_pair, cascade claims first):
        compressed in HBM, so the pool's byte budget holds ratio more
        segments and a cold miss ships ratio fewer H2D bytes. The traced
        programs decode on-device (cascade.split_resident at the program
        top; the pallas kernel per-tile for packed words). Both
        descriptors join the cache key, so flipping either enable switch
        never serves a mismatched representation.

        `perm` applies a row permutation host-side before staging (the sorted
        projection path); callers must pass a stable hashable `perm_key`
        identifying it so the cache can distinguish layouts.

        `row_align` also serves the batched multi-segment path: staging with
        row_align >= n_rows pads to EXACTLY row_align rows, so batch-mates on
        the same ladder rung stack into one [K, R] program.
        """
        from druid_tpu.data import cascade as cascade_mod
        if perm is not None and perm_key is None:
            raise ValueError("device_block(perm=...) requires perm_key")
        if columns is None:
            columns = list(self.dims.keys()) + list(self.metrics.keys())
        # the shared encode derivation (data/cascade.plan_pair): cascade
        # rungs claim their columns first, bit-packing covers the rest —
        # both descriptors join the pool key, so flipping either switch
        # never serves a mismatched representation
        cascades, packs = cascade_mod.plan_pair(self, columns,
                                                permuted=perm is not None)
        key = ("block", tuple(sorted(set(columns))), row_align,
               getattr(device, "id", None), perm_key, packs, cascades)
        return self._pool.get_or_build(
            self._pool_owner, key,
            lambda: self._stage_block(columns, row_align, device, perm,
                                      packs, cascades))

    def _stage_block(self, columns: Sequence[str], row_align: int,
                     device, perm: Optional[np.ndarray],
                     packs: Tuple = (), cascades: Tuple = ()) -> DeviceBlock:
        import jax

        from druid_tpu.data import cascade as cascade_mod
        from druid_tpu.data import packed as packed_mod
        pack_for = {name: (w, base) for name, w, base in packs}
        cascade_for = {e[0]: e for e in cascades}

        pad_n = max(row_align, ((self.n_rows + row_align - 1) // row_align) * row_align)
        time0 = self.interval.start
        off = (self.time_ms - time0)
        if off.size and (off.min() < 0 or off.max() >= 2**31):
            raise ValueError(
                f"segment rows outside int32 ms-offset range of interval {self.interval}")
        arrays: Dict[str, object] = {}

        def _pad(a: np.ndarray, fill=0):
            if perm is not None:
                a = a[perm]
            out = np.full((pad_n,) + a.shape[1:], fill, dtype=a.dtype)
            out[: a.shape[0]] = a
            return out

        arrays["__time_offset"] = _pad(off.astype(np.int32))
        valid = np.zeros((pad_n,), dtype=bool)
        valid[: self.n_rows] = True
        arrays["__valid"] = valid
        dictionaries: Dict[str, Dictionary] = {}
        packwords: Dict[str, np.ndarray] = {}

        def _cascade_stub(name: str):
            # rle/lz4 encoders read only cached run/token tables plus the
            # padded shape — never the decoded rows, so lazy format-V2
            # columns stage without a host decode
            c = cascade_for.get(name)
            return c is not None and c[1] in ("rle", "lz4")

        def _pack_hint(col_obj, name: str):
            # persisted pack words (format V2) upload as-is when the plan
            # and padded shape match what was written at persist time
            if perm is not None:
                return None
            hint = getattr(col_obj, "_v2_pack", None)
            p = pack_for.get(name)
            if hint is not None and p is not None \
                    and tuple(hint[1:]) == (p[0], p[1], pad_n):
                return hint[0]
            return None

        for name in columns:
            if name in self.dims:
                col = self.dims[name]
                dictionaries[name] = col.dictionary
                if _cascade_stub(name):
                    arrays[name] = _ShapeStub(pad_n, np.int32)
                    continue
                words = _pack_hint(col, name)
                if words is not None:
                    packwords[name] = words
                    arrays[name] = _ShapeStub(pad_n, np.int32)
                    continue
                arrays[name] = _pad(col.ids)
            elif name in self.metrics:
                m = self.metrics[name]
                dt = self.staged_dtype(name)
                if _cascade_stub(name):
                    arrays[name] = _ShapeStub(pad_n, dt)
                    continue
                words = _pack_hint(m, name)
                if words is not None:
                    packwords[name] = words
                    arrays[name] = _ShapeStub(pad_n, dt)
                    continue
                vals = m.values if m.values.dtype == dt \
                    else m.values.astype(dt)
                arrays[name] = _pad(vals)
            elif name in ("__time", "__time_offset", "__valid"):
                continue
            else:
                raise KeyError(f"no such column {name!r} in segment {self.id}")

        put = (lambda a: jax.device_put(a, device)) if device is not None \
            else jax.device_put

        def _stage(name: str, v):
            c = cascade_for.get(name)
            if c is not None:
                return cascade_mod.encode_column(self, name, c, v, put)
            p = pack_for.get(name)
            if p is None:
                return put(v)
            w, base = p
            words = packwords[name] if name in packwords \
                else packed_mod.pack_padded(v, w, base)
            return packed_mod.PackedColumn(put(np.asarray(words)), w, base,
                                           v.shape[0], str(v.dtype))

        return DeviceBlock(
            segment_id=self.id, n_rows=self.n_rows, padded_rows=pad_n,
            time0=time0, arrays={k: _stage(k, v) for k, v in arrays.items()},
            dictionaries=dictionaries,
        )

    def device_cached(self, key: Tuple, fn):
        """Memoize a derived DEVICE array through the same byte-budgeted
        pool as staged blocks (HBM entries must not accumulate per query
        shape)."""
        return self._pool.get_or_build(self._pool_owner, ("aux",) + key, fn)

    def device_contains(self, key: Tuple) -> bool:
        """Residency probe for a device_cached entry (no stats/LRU touch) —
        the filter-bitmap cache's own hit/miss accounting."""
        return self._pool.peek(self._pool_owner, ("aux",) + key)

    def device_take(self, key: Tuple):
        """Pop a device_cached entry (None when absent) — the megakernel's
        donated-carry handoff (the buffers must leave the pool before
        donation invalidates them)."""
        return self._pool.take(self._pool_owner, ("aux",) + key)

    def adopt_carries_from(self, donor: "Segment") -> None:
        """Standing-query carry bridge (engine/standing.py): a live sink's
        snapshot is a FRESH Segment every generation, so the megakernel's
        per-segment donated carries would never be reused across ticks.
        Naming the previous snapshot here lets run_grouped_aggregate's
        carry take fall back to the donor's parked grids. ONLY carries may
        bridge — they are content-free HBM allocations the kernel re-inits
        at grid step 0; staged data never transfers between segments.
        This is one of the PARK verbs in donorguard's ownership
        vocabulary (tools/druidlint/donorguard.py): a popped carry handed
        to the bridge counts as discharged, same as put/device_cached."""
        import weakref
        self._carry_donor = weakref.ref(donor)

    def carry_donor(self) -> Optional["Segment"]:
        ref = getattr(self, "_carry_donor", None)
        return ref() if ref is not None else None

    def column_minmax(self, name: str) -> Tuple[int, int]:
        """Cached (min, max) of a numeric column (0, 0 when empty)."""
        def _compute():
            v = self.metrics[name].values
            if v.size == 0:
                return (0, 0)
            return (v.min().item(), v.max().item())
        return self.aux_cached(("minmax", name), _compute)

    def column_finite(self, name: str) -> bool:
        """Cached: True when a float column contains no NaN/Inf. Gates the
        one-hot-matmul float path, where a single non-finite value would
        poison every group (NaN·0 = NaN in the one-hot contraction)."""
        def _compute():
            m = self.metrics.get(name)
            if m is None or not np.issubdtype(m.values.dtype, np.floating):
                return True
            return bool(np.isfinite(m.values).all())
        return self.aux_cached(("finite", name), _compute)

    def staged_dtype(self, name: str):
        """Device dtype a column stages as. LONG columns whose values fit
        int32 stage narrow: 64-bit ops are limb-emulated on TPU (~5x cost),
        and almost all real long metrics fit 32 bits. Aggregation kernels
        restore exact 64-bit semantics at group granularity."""
        if name in self.dims:
            return np.int32
        if name in ("__time_offset",):
            return np.int32
        m = self.metrics.get(name)
        if m is None:
            return None
        if m.type is ValueType.LONG:
            lo, hi = self.column_minmax(name)
            if -(2**31) <= lo and hi < 2**31:
                return np.int32
            return np.int64
        if m.type in (ValueType.FLOAT, ValueType.DOUBLE):
            # from type metadata, not m.values.dtype: lazy format-V2
            # columns answer without materializing
            return np.dtype(m.type.numpy_dtype)
        return m.values.dtype             # complex states

    def aux_cached(self, key: Tuple, fn):
        """Memoize derived host arrays (e.g. calendar bucket ids, fused
        group keys) per segment — the analog of the reference's per-segment
        column caches."""
        with self._lock:
            if key in self._aux_cache:
                return self._aux_cache[key]
        value = fn()
        with self._lock:
            self._aux_cache[key] = value
        return value

    def size_bytes(self) -> int:
        # logical_nbytes hint first: lazy format-V2 columns report decoded
        # size without materializing (it equals .nbytes by construction)
        n = self.time_ms.nbytes
        for d in self.dims.values():
            hint = getattr(d, "logical_nbytes", None)
            n += hint if hint is not None else d.ids.nbytes
        for m in self.metrics.values():
            hint = getattr(m, "logical_nbytes", None)
            n += hint if hint is not None else m.values.nbytes
        return int(n)

    def __repr__(self):
        return f"Segment({self.id}, rows={self.n_rows})"


class SegmentBuilder:
    """Builds an immutable Segment from rows or columns.

    Reference analog: IncrementalIndex + IndexMergerV9.persist for the
    "make a queryable segment" capability (segment/IndexMergerV9.java:729) —
    the streaming-ingest IncrementalIndex analog with rollup lives in
    druid_tpu/ingest/incremental.py.
    """

    def __init__(self, datasource: str, interval: Interval, version: str = "v0",
                 partition: int = 0,
                 shared_dictionaries: Optional[Dict[str, Dictionary]] = None):
        self.segment_id = SegmentId(datasource, interval, version, partition)
        self._time: List[int] = []
        self._dim_values: Dict[str, List[str]] = {}
        self._metric_values: Dict[str, List] = {}
        self._metric_types: Dict[str, ValueType] = {}
        self._shared_dicts = shared_dictionaries or {}
        self._n = 0

    def add_row(self, ts_ms: int, dims: Dict[str, Optional[str]],
                metrics: Dict[str, float]):
        for name in dims:
            if name not in self._dim_values:
                # null backfill for a newly-seen dim: _n is the shared
                # row count, identical for every column by construction
                self._dim_values[name] = [NULL] * self._n  # druidlint: disable=unkeyed-trace-input
        for name in metrics:
            if name not in self._metric_values:
                # same backfill invariant as the dim columns above
                self._metric_values[name] = [0] * self._n  # druidlint: disable=unkeyed-trace-input
                self._metric_types.setdefault(
                    name, ValueType.LONG if isinstance(metrics[name], int)
                    else ValueType.DOUBLE)
            elif (self._metric_types.get(name) == ValueType.LONG
                  and isinstance(metrics.get(name), float)):
                # widen LONG -> DOUBLE when a float arrives later, instead of
                # silently truncating at build time
                self._metric_types[name] = ValueType.DOUBLE
        self._time.append(int(ts_ms))
        for name, vals in self._dim_values.items():
            v = dims.get(name)
            vals.append(NULL if v is None else str(v))
        for name, vals in self._metric_values.items():
            vals.append(metrics.get(name, 0))
        self._n += 1

    def add_columns(self, time_ms: np.ndarray,
                    dims: Dict[str, Sequence[str]],
                    metrics: Dict[str, np.ndarray],
                    metric_types: Optional[Dict[str, ValueType]] = None):
        if self._n:
            raise ValueError("add_columns on non-empty builder unsupported")
        self._time = list(np.asarray(time_ms, dtype=np.int64))
        for k, v in dims.items():
            self._dim_values[k] = [NULL if x is None else str(x) for x in v]
        for k, v in metrics.items():
            arr = np.asarray(v)
            self._metric_values[k] = arr
            if metric_types and k in metric_types:
                self._metric_types[k] = metric_types[k]
            else:
                self._metric_types[k] = (
                    ValueType.LONG if np.issubdtype(arr.dtype, np.integer)
                    else ValueType.DOUBLE if arr.dtype == np.float64
                    else ValueType.FLOAT)
        self._n = len(self._time)

    def build(self) -> Segment:
        time_ms = np.asarray(self._time, dtype=np.int64)
        dims: Dict[str, StringDimColumn] = {}
        for name, values in self._dim_values.items():
            d = self._shared_dicts.get(name) or Dictionary.from_values(values)
            dims[name] = StringDimColumn(d.encode(values), d)
        metrics: Dict[str, NumericColumn] = {}
        for name, values in self._metric_values.items():
            vtype = self._metric_types[name]
            arr = np.asarray(values, dtype=vtype.numpy_dtype)
            metrics[name] = NumericColumn(arr, vtype)
        return Segment(self.segment_id, time_ms, dims, metrics,
                       sorted_by_time=False)
