"""Bit-packed device columns: compressed-in-HBM staging for narrow columns.

The HBM wall and the H2D bus are the cold-query taxes (ROADMAP open item 2):
a fully-decoded int32 dictionary-id column spends 32 bits per row on values
that need ceil(log2(cardinality)). Following the compressed-domain operator
design of *GPU Acceleration of SQL Analytics on Compressed Data* (PAPERS.md),
eligible columns stage as bit-packed int32 words and stay packed in HBM; the
traced program unpacks them on-device (XLA fuses the shift/mask into the
consumers, so the full-width array exists only transiently inside the
program), and the pallas aggregation kernel consumes the words directly,
unpacking per VMEM tile (engine/pallas_agg.py packed-input variant).

Encoding (one canonical layout shared by the XLA and pallas decoders):
  * width w ∈ contracts.PACK_WIDTHS (4/8/16 bits; each divides the 32-bit
    word, so vpw = 32 // w values share one word and no value crosses a
    word boundary);
  * values are stored biased: stored = value - base, base a pow2-quantized
    lower bound (0 for dictionary ids) so negatives pack without sign bits;
  * tile-planar order: view the padded column [n] as the device tile layout
    [n // 128, 128]; vpw CONSECUTIVE ROWS of that view share a word row —
    word[q, l] packs rows q*vpw .. q*vpw+vpw-1 at lane l. A pallas block of
    R = BLK // 128 value rows therefore maps to exactly R // vpw word rows,
    and the in-kernel unpack is a pure VPU shift/mask/reshape (no gather).

Eligibility is a PURE FUNCTION of column stats (dictionary cardinality,
cached column min/max): plan signatures stay stable across executions and
identical stats yield identical pack descriptors on every path (per-segment,
batched, scheduler-fused). Columns that do not benefit — floats, int64-staged
longs, cardinality above 2^16 — fall back to decoded staging.

Pack ratio = 32 / width ≥ 2x, so a byte-budgeted device pool holds that many
more segments and every cold miss ships that many fewer PCIe bytes.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

#: lane width of the device tile layout the packing is planar over; must
#: match contracts.LANE (asserted lazily in _widths to keep this module
#: importable without triggering the engine package import).
_LANE = 128

_ENABLED = os.environ.get("DRUID_TPU_PACKED", "1").lower() \
    not in ("0", "false", "no")
_ENABLED_LOCK = threading.Lock()


def set_enabled(on: bool) -> bool:
    """Flip the process-wide packing default; returns the previous value
    (bench/test toggle, the batching.set_enabled discipline)."""
    global _ENABLED
    with _ENABLED_LOCK:
        prev = _ENABLED
        _ENABLED = bool(on)
        return prev


def enabled() -> bool:
    return _ENABLED


def _contracts():
    # lazy: importing the engine package at data-module import time would
    # cycle (engine -> data.segment -> packed); the submodule import is
    # safe once anything engine-side is loading (same pattern as
    # devicepool._default_budget)
    from druid_tpu.engine import contracts
    return contracts


def _widths() -> Tuple[int, ...]:
    c = _contracts()
    assert c.LANE == _LANE
    return c.PACK_WIDTHS


def _word_bits() -> int:
    return _contracts().PACK_WORD_BITS


# ---------------------------------------------------------------------------
# PackedColumn: the staged representation (a jax pytree)
# ---------------------------------------------------------------------------

_REGISTERED = False
_REGISTER_LOCK = threading.Lock()


def _ensure_registered():
    """Register PackedColumn as a jax pytree on first construction: `words`
    is the only leaf; the pack descriptor rides the treedef, so jit
    programs specialize per descriptor exactly like they do per dtype.
    Construction happens only at staging time, so the uncontended lock
    acquisition per instance is noise next to the device_put."""
    global _REGISTERED
    with _REGISTER_LOCK:
        if _REGISTERED:
            return
        import jax

        jax.tree_util.register_pytree_node(
            PackedColumn,
            lambda pc: ((pc.words,),
                        (pc.width, pc.base, pc.rows, pc.dtype_str)),
            lambda aux, leaves: PackedColumn(leaves[0], *aux),
        )
        _REGISTERED = True


class PackedColumn:
    """A bit-packed column: int32 `words` (device or host) + descriptor.

    rows is the DECODED length (the staged padded row count); words has
    rows // vpw entries. dtype_str names the decoded dtype ("int32" for
    dictionary ids and int32-staged longs)."""

    __slots__ = ("words", "width", "base", "rows", "dtype_str")

    def __init__(self, words, width: int, base: int, rows: int,
                 dtype_str: str = "int32"):
        _ensure_registered()
        self.words = words
        self.width = int(width)
        self.base = int(base)
        self.rows = int(rows)
        self.dtype_str = dtype_str

    @property
    def vpw(self) -> int:
        return _word_bits() // self.width

    @property
    def nbytes(self) -> int:
        """ACTUAL bytes pinned (the device pool's accounting unit)."""
        return int(getattr(self.words, "nbytes", 0))

    @property
    def logical_nbytes(self) -> int:
        """Decoded-equivalent bytes (the pool's packedRatio numerator)."""
        return int(self.rows * np.dtype(self.dtype_str).itemsize)

    def descriptor(self) -> Tuple[int, int, int, str]:
        return (self.width, self.base, self.rows, self.dtype_str)

    def __repr__(self):
        return (f"PackedColumn(w{self.width}, base={self.base}, "
                f"rows={self.rows}, {self.dtype_str})")


# ---------------------------------------------------------------------------
# Planning (pure functions of column stats)
# ---------------------------------------------------------------------------

def width_for(hi: int, base: int) -> int:
    """Smallest contract width holding values in [base, hi], or 0."""
    span = max(int(hi) - int(base), 0)
    bits = max(span.bit_length(), 1)
    for w in _widths():
        if bits <= w:
            return w
    return 0


def plan_column(segment, name: str) -> Optional[Tuple[int, int]]:
    """(width, base) when `name` pack-benefits in `segment`, else None.

    Pure function of the column's stats: dictionary cardinality for string
    dims, cached min/max for int32-staged long metrics. Floats, int64-staged
    longs (range needs >16 bits anyway), and high-cardinality dims (> 2^16)
    return None — decoded staging."""
    dim = segment.dims.get(name)
    if dim is not None:
        w = width_for(max(int(dim.cardinality) - 1, 0), 0)
        return (w, 0) if w else None
    m = segment.metrics.get(name)
    if m is None:
        return None
    # metadata check (not np.asarray(m.values)): format-V2 lazy columns
    # plan without materializing decoded rows. Non-LONG metrics — floats
    # and 2-D complex states (HLL registers et al.) — stage as-is: the
    # packer and both decoders are 1-D integer tile-planar only.
    t = getattr(m, "type", None)
    if t is None or getattr(t, "value", None) != "long":
        return None
    if segment.staged_dtype(name) != np.int32:
        return None
    lo, hi = segment.column_minmax(name)
    # pow2-quantized base: an exact base would split batching shape buckets
    # on every per-segment min; quantization keeps descriptors coarse
    base = 0 if lo >= 0 else -(1 << ((-int(lo) - 1).bit_length()))
    w = width_for(hi, base)
    return (w, base) if w else None


def plan_columns(segment, columns: Sequence[str]) -> Tuple:
    """((name, width, base), ...) for the pack-eligible subset of `columns`,
    sorted by name; () when packing is disabled. This tuple IS the pack
    descriptor: it joins the device-pool staging key, the per-segment plan
    signature, and the batching shape-bucket digest, so every execution
    path shares one decode story."""
    if not _ENABLED:
        return ()
    out = []
    for c in sorted(set(columns)):
        p = plan_column(segment, c)
        if p is not None:
            out.append((c, p[0], p[1]))
    return tuple(out)


# ---------------------------------------------------------------------------
# Host-side pack / unpack
# ---------------------------------------------------------------------------

def pack_padded(padded: np.ndarray, width: int, base: int) -> np.ndarray:
    """Pack a PADDED decoded column (length a multiple of 128 * vpw — any
    DEFAULT_ROW_ALIGN-padded staging array qualifies) into int32 words in
    the canonical tile-planar layout. Stored values are masked to the
    width, so padding-row fill that falls outside [base, hi] wraps
    deterministically instead of corrupting neighbor slots; every consumer
    masks padding rows out, so their decoded values never matter."""
    vpw = _word_bits() // width
    n = int(padded.shape[0])
    assert n % (_LANE * vpw) == 0, \
        f"packed column length {n} not a multiple of {_LANE * vpw}"
    mask = np.uint32((1 << width) - 1)
    u = ((padded.astype(np.int64) - base)
         & np.int64((1 << width) - 1)).astype(np.uint32)
    v3 = u.reshape(-1, vpw, _LANE)
    words = np.zeros((v3.shape[0], _LANE), dtype=np.uint32)
    for s in range(vpw):
        words |= (v3[:, s, :] & mask) << np.uint32(s * width)
    return words.reshape(-1).view(np.int32)


def unpack_host(pc_or_words, width: Optional[int] = None,
                base: Optional[int] = None, rows: Optional[int] = None,
                dtype="int32") -> np.ndarray:
    """Exact host inverse of pack_padded (tests / debugging)."""
    if isinstance(pc_or_words, PackedColumn):
        pc = pc_or_words
        words, width, base = np.asarray(pc.words), pc.width, pc.base
        rows, dtype = pc.rows, pc.dtype_str
    else:
        words = np.asarray(pc_or_words)
    vpw = _word_bits() // width
    w2 = words.view(np.uint32).reshape(-1, _LANE)
    out = np.empty((w2.shape[0], vpw, _LANE), dtype=np.uint32)
    for s in range(vpw):
        out[:, s, :] = (w2 >> np.uint32(s * width)) \
            & np.uint32((1 << width) - 1)
    return (out.reshape(rows).astype(np.int64) + base).astype(dtype)


# ---------------------------------------------------------------------------
# Device-side (traced) unpack
# ---------------------------------------------------------------------------

def unpack_device(pc: PackedColumn):
    """Traced: decode a PackedColumn to its full-width 1-D array. Pure
    int32 shift/mask/reshape — XLA fuses it into the consumers, so outside
    pallas the decoded array never materializes in HBM on its own."""
    import jax.numpy as jnp

    # trace-time decode accounting (data/cascade.py): the code-domain
    # paths' zero-unpack contract is asserted against this counter. Lazy
    # import: cascade imports this module at load time.
    from druid_tpu.data import cascade
    cascade.record_decode(getattr(pc, "cascade_kind", "packed"))

    width, vpw = pc.width, pc.vpw
    m = jnp.int32((1 << width) - 1)
    w2 = pc.words.reshape(-1, _LANE)
    sh = jnp.int32(width) * jnp.arange(vpw, dtype=jnp.int32)
    # arithmetic >> then & mask: sign-extension bits are cut off, so int32
    # words with the top bit set (width-16 slot 1) decode exactly
    v = (w2[:, None, :] >> sh[None, :, None]) & m
    if pc.base:
        v = v + jnp.int32(pc.base)
    v = v.reshape(pc.rows)
    dt = jnp.dtype(pc.dtype_str)
    return v.astype(dt) if v.dtype != dt else v


def unpack_columns(arrays: Dict) -> Dict:
    """Traced: dict with every PackedColumn entry decoded (others pass
    through). The ONE decode entry point the per-segment and stacked
    program builders call, so the decode story cannot diverge."""
    out = dict(arrays)
    for k, v in arrays.items():
        if isinstance(v, PackedColumn):
            out[k] = unpack_device(v)
    return out


def split_packed(arrays: Dict) -> Tuple[Dict, Dict]:
    """(packed entries, dense view of everything): the program-top helper —
    the dense view feeds filters/keys/XLA strategies, the packed dict feeds
    pallas_reduce's packed-input variant."""
    packed = {k: v for k, v in arrays.items() if isinstance(v, PackedColumn)}
    if not packed:
        return packed, arrays
    return packed, unpack_columns(arrays)
