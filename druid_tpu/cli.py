"""CLI: node commands + tools.

Reference analog: services/src/main/java/org/apache/druid/cli/Main.java:52-112
— server commands (historical, broker, coordinator, overlord, …) and tools
(DumpSegment, ValidateSegments, CreateTables, ResetCluster).

`python -m druid_tpu <command>`:
  server  — one process hosting the whole stack (metadata + coordinator +
            data nodes + broker + overlord + HTTP endpoints); the
            in-process analog of a single-server deployment
  dump-segment     — segment introspection (cli/DumpSegment.java)
  validate-segment — verify an on-disk segment loads and self-checks
  version
"""
from __future__ import annotations

import argparse
import json
import sys
import threading

VERSION = "druid-tpu-0.1"

#: process-wide stop signal for the duty loops below. The loops park on
#: it (stop-responsive bounded waits) instead of time.sleep: SIGINT still
#: interrupts the wait on the main thread, and anything that sets the
#: event (tests, an embedding process) ends the duty loop within one
#: iteration — no thread ever parks un-wakeably.
_STOP = threading.Event()


def _scheduler_from_config(cfg):
    """`server.querySlots` bounds concurrent queries (0/unset = unbounded);
    `server.lanes` caps named lanes, e.g. "reports=1,adhoc=4"
    (DruidProcessingConfig numThreads + laning)."""
    slots = cfg.get_int("server.querySlots", 0)
    if not slots:
        return None
    from druid_tpu.server.querymanager import QueryScheduler
    lanes = {}
    for part in (cfg.get("server.lanes") or "").split(","):
        name, _, cap = part.partition("=")
        if name.strip() and cap.strip().isdigit():
            lanes[name.strip()] = int(cap)
    return QueryScheduler(total_slots=slots, lanes=lanes)


def cmd_server(args) -> int:
    from druid_tpu.cluster import (Broker, Coordinator, DataNode,
                                   DynamicConfig, InventoryView, LruCache,
                                   MetadataStore)
    from druid_tpu.indexing import Overlord
    from druid_tpu.server import QueryHttpServer, QueryLifecycle, RequestLogger
    from druid_tpu.sql import SqlExecutor
    from druid_tpu.storage.deep import LocalDeepStorage
    from druid_tpu.utils.config import Config
    from druid_tpu.utils.emitter import (MonitorScheduler, ProcessMonitor,
                                         ServiceEmitter, SysMonitor,
                                         emitter_from_config)
    import druid_tpu.ext  # noqa: F401  (activate extensions)

    cfg = Config.load(args.config)
    metadata = MetadataStore(cfg.get("metadata.path", ":memory:"))
    deep = LocalDeepStorage(cfg.get("storage.dir", "./deep-storage"))
    view = InventoryView()
    n_nodes = cfg.get_int("server.dataNodes", 1)
    for i in range(n_nodes):
        view.register(DataNode(f"data{i}", cache=LruCache()))
    coordinator = Coordinator(metadata, view, deep.pull, DynamicConfig())
    broker = Broker(view, cache=LruCache())
    overlord = Overlord(metadata, deep)

    emitter = ServiceEmitter(
        "druid-tpu/server", "localhost",
        emitter_from_config(cfg.get("emitter.type", "noop"),
                            **cfg.subtree("emitter")
                            if cfg.get("emitter.type") == "file" else {}))
    logger = RequestLogger(cfg.get("request.log.path"))
    lifecycle = QueryLifecycle(broker, emitter, logger,
                               scheduler=_scheduler_from_config(cfg))
    sql = SqlExecutor(broker)
    http = QueryHttpServer(lifecycle, sql, port=cfg.get_int("server.port",
                                                            8082))
    monitors = MonitorScheduler(emitter, [SysMonitor(), ProcessMonitor()],
                                cfg.get_float("monitor.period", 60.0))

    # ordered bring-up/teardown (java-util Lifecycle): monitors and the
    # overlord pool before the HTTP server accepts, HTTP down first on stop
    from druid_tpu.utils.lifecycle import Lifecycle, Stage
    lc = Lifecycle()
    lc.add(monitors, stage=Stage.NORMAL, name="monitors")
    lc.add(start=None, stop=overlord.shutdown, stage=Stage.NORMAL,
           name="overlord")
    lc.add(http, stage=Stage.SERVER, name="http")
    lc.start()
    print(f"druid-tpu server listening on :{http.port} "
          f"({n_nodes} data node(s))", flush=True)

    period = cfg.get_float("coordinator.period", 10.0)
    try:
        while not _STOP.is_set():
            coordinator.run_once()
            _STOP.wait(period)
    except KeyboardInterrupt:
        pass
    lc.stop()
    return 0


# ---------------------------------------------------------------------------
# Per-node-type servers (services/src/main/java/org/apache/druid/cli/
# CliHistorical.java, CliBroker.java, CliCoordinator.java, CliRouter.java) —
# each runs ONE role so deployments scale roles independently; `server`
# remains the single-process everything node.
# ---------------------------------------------------------------------------

def build_historical(name: str, segments_dir=None, port: int = 8083,
                     tier: str = "_default_tier"):
    """DataNode + its HTTP query endpoint; optionally preload every
    persisted segment under segments_dir."""
    import os
    from druid_tpu.cluster import DataNode, DataNodeServer, LruCache
    node = DataNode(name, tier=tier, cache=LruCache())
    loaded = 0
    if segments_dir and os.path.isdir(segments_dir):
        from druid_tpu.storage.format import load_segment
        from druid_tpu.storage.smoosh import CorruptSegmentError
        for entry in sorted(os.listdir(segments_dir)):
            d = os.path.join(segments_dir, entry)
            if os.path.isfile(os.path.join(d, "version.bin")):
                try:
                    node.load_segment(load_segment(d))
                except CorruptSegmentError as e:
                    # skip-and-log: one damaged directory must not keep a
                    # historical from serving its healthy segments
                    print(f"skipping corrupt segment: {e}", file=sys.stderr,
                          flush=True)
                    continue
                loaded += 1
    server = DataNodeServer(node, port=port).start()
    return node, server, loaded


def cmd_historical(args) -> int:
    node, server, loaded = build_historical(
        args.name, args.segments_dir, args.port, args.tier)
    print(f"historical [{args.name}] listening on :{server.port} "
          f"({loaded} segments preloaded)", flush=True)
    try:
        while not _STOP.wait(3600):
            pass
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


def build_broker(data_node_urls, port: int = 8082, query_slots: int = 0,
                 lanes: str = ""):
    """Broker over remote data nodes discovered via /status sync."""
    from druid_tpu.cluster import (Broker, InventoryView, LruCache,
                                   RemoteDataNodeClient)
    from druid_tpu.server import QueryHttpServer, QueryLifecycle
    from druid_tpu.sql import SqlExecutor
    from druid_tpu.utils.config import Config
    view = InventoryView()
    for i, url in enumerate(data_node_urls):
        view.register(RemoteDataNodeClient(f"data{i}", url))
    view.sync_all()
    broker = Broker(view, cache=LruCache())
    sched = _scheduler_from_config(Config.load(
        None, env={}, overrides={"server.querySlots": str(query_slots),
                                 "server.lanes": lanes}))
    lifecycle = QueryLifecycle(broker, scheduler=sched)
    http = QueryHttpServer(lifecycle, SqlExecutor(broker), port=port)
    http.start()
    return view, broker, http


def _reregister_missing(view, urls) -> None:
    """Configured nodes that were dropped by liveness re-register when
    they come back — a blip must not remove a statically-configured URL
    until process restart."""
    from druid_tpu.cluster import RemoteDataNodeClient
    for i, url in enumerate(urls):
        name = f"data{i}"
        if view.node(name) is None:
            client = RemoteDataNodeClient(name, url)
            if client.ping():
                view.register(client)


def cmd_broker(args) -> int:
    urls = args.data_node or []
    view, broker, http = build_broker(urls, args.port,
                                      query_slots=args.query_slots,
                                      lanes=args.lanes)
    print(f"broker listening on :{http.port} "
          f"({len(urls)} data node(s))", flush=True)
    try:
        while not _STOP.is_set():
            view.check_liveness(failures_required=3)
            _reregister_missing(view, urls)
            view.sync_all()
            _STOP.wait(args.sync_period)
    except KeyboardInterrupt:
        pass
    http.stop()
    return 0


def cmd_coordinator(args) -> int:
    from druid_tpu.cluster import (Coordinator, DynamicConfig, InventoryView,
                                   MetadataStore, RemoteDataNodeClient)
    from druid_tpu.storage.deep import LocalDeepStorage
    metadata = MetadataStore(args.metadata)
    deep = LocalDeepStorage(args.storage_dir)
    view = InventoryView()
    for i, url in enumerate(args.data_node or []):
        view.register(RemoteDataNodeClient(f"data{i}", url))
    view.sync_all()
    leader = None
    if args.ha:
        # leader-elected HA: several coordinator processes share one
        # metadata file; the lease latch picks one, the rest stand by
        if args.metadata == ":memory:":
            # a private in-memory store per process = every process wins
            # its own election — the exact split-brain HA exists to prevent
            raise SystemExit(
                "--ha needs a SHARED lease store: pass --metadata "
                "/path/to/metadata.db (':memory:' is per-process)")
        from druid_tpu.coordination import (LeaderParticipant,
                                            MetadataLeaseStore)
        import socket
        node_id = args.node_id or f"{socket.gethostname()}-{id(view):x}"
        leader = LeaderParticipant(
            MetadataLeaseStore(metadata), "coordinator", node_id,
            lease_ms=args.lease_ms).start()
    coord = Coordinator(metadata, view, deep.pull, DynamicConfig(),
                        async_loading=True, leader=leader)
    print(f"coordinator running (period {args.period}s, "
          f"{len(args.data_node or [])} node(s)"
          + (f", HA node [{leader.node_id}]" if leader else "") + ")",
          flush=True)
    from druid_tpu.cluster import StaleTermError
    try:
        while not _STOP.is_set():
            try:
                stats = coord.run_once()
            except StaleTermError as e:
                # deposed mid-cycle: the successor holds the term now —
                # drop back to standby and keep heartbeating, don't die
                print(f"deposed mid-cycle, standing by: {e}", flush=True)
                _STOP.wait(args.period)
                continue
            if not stats.skipped_not_leader:
                _reregister_missing(view, args.data_node or [])
                view.sync_all()
            if stats.assigned or stats.dropped or stats.nodes_removed:
                print(f"cycle: assigned={stats.assigned} "
                      f"dropped={stats.dropped} "
                      f"dead={stats.nodes_removed}", flush=True)
            _STOP.wait(args.period)
    except KeyboardInterrupt:
        pass
    if leader is not None:
        leader.stop()           # release the lease for fast failover
    coord.stop()
    return 0


def cmd_router(args) -> int:
    from druid_tpu.server.router import RouterHttpServer, TieredBrokerSelector
    tiers = {}
    for spec in args.broker or []:
        tier, _, url = spec.partition("=")
        if not url:
            tier, url = "_default", spec
        tiers.setdefault(tier, []).append(url)
    if "_default" not in tiers:
        raise SystemExit("router needs at least one --broker [tier=]URL")
    selector = TieredBrokerSelector(tiers, default_tier="_default")
    http = RouterHttpServer(selector, port=args.port).start()
    print(f"router listening on :{http.port} "
          f"(tiers: {', '.join(sorted(tiers))})", flush=True)
    try:
        while not _STOP.wait(3600):
            pass
    except KeyboardInterrupt:
        pass
    http.stop()
    return 0


def cmd_dump_segment(args) -> int:
    """Segment forensics (cli/DumpSegment.java)."""
    from druid_tpu.storage.format import load_segment, read_segment_meta
    meta = read_segment_meta(args.directory)
    out = {"metadata": meta}
    if args.rows:
        args.full = True   # --rows implies loading the segment
    if args.full:
        seg = load_segment(args.directory)
        cols = {}
        for name, col in seg.dims.items():
            cols[name] = {"type": "string",
                          "cardinality": col.cardinality,
                          "hasBitmapIndex": True}
        for name, m in seg.metrics.items():
            t = m.type.value if hasattr(m.type, "value") else str(m.type)
            cols[name] = {"type": t}
        out["columns"] = cols
        out["numRows"] = seg.n_rows
        out["interval"] = str(seg.interval)
        if args.rows:
            from druid_tpu.query.model import ScanQuery
            from druid_tpu.engine.engines import run_scan
            batches = run_scan(
                ScanQuery.of(seg.id.datasource, [seg.interval],
                             limit=args.rows), [seg])
            out["rows"] = [e for b in batches for e in b["events"]]
    print(json.dumps(out, indent=2, default=str))
    return 0


def cmd_segment_inspect(args) -> int:
    """Per-column storage forensics: encoding, descriptor, on-disk vs
    logical (decoded-equivalent) bytes — V1 and format-V2 segments."""
    import numpy as np
    from druid_tpu.storage.format import (FORMAT_VERSION_V2,
                                          read_format_version,
                                          read_segment_meta)
    from druid_tpu.storage.smoosh import SmooshedFileMapper
    version = read_format_version(args.directory)
    meta = read_segment_meta(args.directory)
    n_rows = int(meta["n_rows"])
    specs = (meta.get("v2") or {}).get("columns", {})
    fmt = 2 if version == FORMAT_VERSION_V2 else 1

    def logical(dtype_str):
        try:
            return n_rows * np.dtype(dtype_str).itemsize
        except TypeError:
            return None

    _TYPE_DTYPE = {"long": "int64", "float": "float32", "double": "float64"}
    columns = {}
    with SmooshedFileMapper(args.directory) as mapper:
        def size_of(*parts):
            return sum(mapper.part_size(p) for p in parts if mapper.has(p))

        for name in meta["dimensions"]:
            spec = specs.get(name, {"enc": "block", "dtype": "int32"})
            enc = spec["enc"]
            parts = {"rle": (f"col.{name}.rle.values",
                             f"col.{name}.rle.ends"),
                     "pack": (f"col.{name}.pack",),
                     "block": (f"dim.{name}.ids",)}[enc]
            desc = {k: v for k, v in spec.items() if k not in ("enc",)}
            columns[name] = {
                "kind": "dimension", "enc": enc, "descriptor": desc,
                "onDiskBytes": size_of(*parts),
                "logicalBytes": logical(spec.get("dtype", "int32")),
                "dictBytes": size_of(f"dim.{name}.dict"),
                "bitmapBytes": size_of(f"dim.{name}.bitmaps")}
        for name, tname in meta["metrics"].items():
            dt = _TYPE_DTYPE.get(tname)
            spec = specs.get(name, {"enc": "block", "dtype": dt})
            enc = spec["enc"]
            parts = {"rle": (f"col.{name}.rle.values",
                             f"col.{name}.rle.ends"),
                     "pack": (f"col.{name}.pack",),
                     "lz4": (f"col.{name}.lz4",),
                     "block": (f"met.{name}",)}[enc]
            desc = {k: v for k, v in spec.items() if k not in ("enc",)}
            columns[name] = {
                "kind": "metric", "type": tname, "enc": enc,
                "descriptor": desc, "onDiskBytes": size_of(*parts),
                "logicalBytes": logical(spec.get("dtype", dt))}
        time_disk = size_of("__time")
    out = {"directory": args.directory, "format": fmt, "numRows": n_rows,
           "columns": columns,
           "time": {"onDiskBytes": time_disk, "logicalBytes": n_rows * 8}}
    if fmt == 2:
        out["staging"] = meta["v2"].get("staging")
    disk = sum(c["onDiskBytes"] for c in columns.values()) + time_disk
    logi = sum(c["logicalBytes"] or 0 for c in columns.values()) + n_rows * 8
    out["totals"] = {"onDiskBytes": disk, "logicalBytes": logi,
                     "ratio": round(logi / disk, 2) if disk else None}
    print(json.dumps(out, indent=2, default=str))
    return 0


def cmd_validate_segment(args) -> int:
    """Load + self-check an on-disk segment (cli/ValidateSegments.java)."""
    from druid_tpu.storage.format import load_segment
    try:
        seg = load_segment(args.directory)
    except Exception as e:
        print(f"INVALID: cannot load: {e}", file=sys.stderr)
        return 1
    problems = []
    n = seg.n_rows
    if len(seg.time_ms) != n:
        problems.append("time column length mismatch")
    for name, col in seg.dims.items():
        if len(col.ids) != n:
            problems.append(f"dim {name}: id column length {len(col.ids)}")
        if n and (col.ids.max() >= col.cardinality or col.ids.min() < 0):
            problems.append(f"dim {name}: id out of dictionary range")
        vals = col.dictionary.values
        if list(vals) != sorted(vals):
            problems.append(f"dim {name}: dictionary not sorted")
    for name, m in seg.metrics.items():
        if len(m.values) != n:
            problems.append(f"metric {name}: length {len(m.values)}")
    if n and not (seg.time_ms[:-1] <= seg.time_ms[1:]).all():
        problems.append("rows not time-sorted")
    if problems:
        print("INVALID: " + "; ".join(problems), file=sys.stderr)
        return 1
    print(f"OK: {seg.id} rows={n} dims={len(seg.dims)} "
          f"metrics={len(seg.metrics)}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="druid_tpu",
                                description="TPU-native analytics engine")
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("server", help="run the single-process cluster")
    s.add_argument("--config", default=None, help="properties/json file")
    s.set_defaults(fn=cmd_server)

    s = sub.add_parser("historical", help="run one data-serving node")
    s.add_argument("--name", default="historical0")
    s.add_argument("--port", type=int, default=8083)
    s.add_argument("--tier", default="_default_tier")
    s.add_argument("--segments-dir", default=None,
                   help="preload persisted segments from this directory")
    s.set_defaults(fn=cmd_historical)

    s = sub.add_parser("broker", help="run the scatter-gather broker")
    s.add_argument("--port", type=int, default=8082)
    s.add_argument("--data-node", action="append",
                   help="data node base URL (repeatable)")
    s.add_argument("--sync-period", type=float, default=10.0)
    s.add_argument("--query-slots", type=int, default=0,
                   help="bound concurrent queries (0 = unbounded)")
    s.add_argument("--lanes", default="",
                   help='per-lane caps, e.g. "reports=1,adhoc=4"')
    s.set_defaults(fn=cmd_broker)

    s = sub.add_parser("coordinator", help="run the coordinator loop")
    s.add_argument("--metadata", default=":memory:",
                   help="sqlite path for the metadata store")
    s.add_argument("--storage-dir", default="./deep-storage")
    s.add_argument("--data-node", action="append")
    s.add_argument("--period", type=float, default=10.0)
    s.add_argument("--ha", action="store_true",
                   help="leader-elected HA over the shared metadata store")
    s.add_argument("--node-id", default=None,
                   help="this coordinator's latch identity (default: "
                        "hostname-derived)")
    s.add_argument("--lease-ms", type=int, default=15_000,
                   help="leader lease duration; failover bound")
    s.set_defaults(fn=cmd_coordinator)

    s = sub.add_parser("router", help="run the query router")
    s.add_argument("--port", type=int, default=8888)
    s.add_argument("--broker", action="append",
                   help="broker URL or tier=URL (repeatable)")
    s.set_defaults(fn=cmd_router)

    s = sub.add_parser("dump-segment", help="inspect an on-disk segment")
    s.add_argument("directory")
    s.add_argument("--full", action="store_true", help="load + column stats")
    s.add_argument("--rows", type=int, default=0, help="dump first N rows")
    s.set_defaults(fn=cmd_dump_segment)

    s = sub.add_parser("validate-segment", help="check an on-disk segment")
    s.add_argument("directory")
    s.set_defaults(fn=cmd_validate_segment)

    s = sub.add_parser("segment", help="segment storage tools")
    seg_sub = s.add_subparsers(dest="segment_command", required=True)
    si = seg_sub.add_parser(
        "inspect", help="per-column encoding/descriptor/size report")
    si.add_argument("directory")
    si.set_defaults(fn=cmd_segment_inspect)

    s = sub.add_parser("version")
    s.set_defaults(fn=lambda a: (print(VERSION), 0)[1])

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
