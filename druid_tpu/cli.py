"""CLI: node commands + tools.

Reference analog: services/src/main/java/org/apache/druid/cli/Main.java:52-112
— server commands (historical, broker, coordinator, overlord, …) and tools
(DumpSegment, ValidateSegments, CreateTables, ResetCluster).

`python -m druid_tpu <command>`:
  server  — one process hosting the whole stack (metadata + coordinator +
            data nodes + broker + overlord + HTTP endpoints); the
            in-process analog of a single-server deployment
  dump-segment     — segment introspection (cli/DumpSegment.java)
  validate-segment — verify an on-disk segment loads and self-checks
  version
"""
from __future__ import annotations

import argparse
import json
import sys
import time

VERSION = "druid-tpu-0.1"


def cmd_server(args) -> int:
    from druid_tpu.cluster import (Broker, Coordinator, DataNode,
                                   DynamicConfig, InventoryView, LruCache,
                                   MetadataStore)
    from druid_tpu.indexing import Overlord
    from druid_tpu.server import QueryHttpServer, QueryLifecycle, RequestLogger
    from druid_tpu.sql import SqlExecutor
    from druid_tpu.storage.deep import LocalDeepStorage
    from druid_tpu.utils.config import Config
    from druid_tpu.utils.emitter import (MonitorScheduler, ProcessMonitor,
                                         ServiceEmitter, SysMonitor,
                                         emitter_from_config)
    import druid_tpu.ext  # noqa: F401  (activate extensions)

    cfg = Config.load(args.config)
    metadata = MetadataStore(cfg.get("metadata.path", ":memory:"))
    deep = LocalDeepStorage(cfg.get("storage.dir", "./deep-storage"))
    view = InventoryView()
    n_nodes = cfg.get_int("server.dataNodes", 1)
    for i in range(n_nodes):
        view.register(DataNode(f"data{i}", cache=LruCache()))
    coordinator = Coordinator(metadata, view, deep.pull, DynamicConfig())
    broker = Broker(view, cache=LruCache())
    overlord = Overlord(metadata, deep)

    emitter = ServiceEmitter(
        "druid-tpu/server", "localhost",
        emitter_from_config(cfg.get("emitter.type", "noop"),
                            **cfg.subtree("emitter")
                            if cfg.get("emitter.type") == "file" else {}))
    logger = RequestLogger(cfg.get("request.log.path"))
    lifecycle = QueryLifecycle(broker, emitter, logger)
    sql = SqlExecutor(broker)
    http = QueryHttpServer(lifecycle, sql, port=cfg.get_int("server.port",
                                                            8082))
    http.start()
    monitors = MonitorScheduler(emitter, [SysMonitor(), ProcessMonitor()],
                                cfg.get_float("monitor.period", 60.0))
    monitors.start()
    print(f"druid-tpu server listening on :{http.port} "
          f"({n_nodes} data node(s))", flush=True)

    period = cfg.get_float("coordinator.period", 10.0)
    try:
        while True:
            coordinator.run_once()
            time.sleep(period)
    except KeyboardInterrupt:
        http.stop()
        overlord.shutdown()
        return 0


def cmd_dump_segment(args) -> int:
    """Segment forensics (cli/DumpSegment.java)."""
    from druid_tpu.storage.format import load_segment, read_segment_meta
    meta = read_segment_meta(args.directory)
    out = {"metadata": meta}
    if args.rows:
        args.full = True   # --rows implies loading the segment
    if args.full:
        seg = load_segment(args.directory)
        cols = {}
        for name, col in seg.dims.items():
            cols[name] = {"type": "string",
                          "cardinality": col.cardinality,
                          "hasBitmapIndex": True}
        for name, m in seg.metrics.items():
            t = m.type.value if hasattr(m.type, "value") else str(m.type)
            cols[name] = {"type": t}
        out["columns"] = cols
        out["numRows"] = seg.n_rows
        out["interval"] = str(seg.interval)
        if args.rows:
            from druid_tpu.query.model import ScanQuery
            from druid_tpu.engine.engines import run_scan
            batches = run_scan(
                ScanQuery.of(seg.id.datasource, [seg.interval],
                             limit=args.rows), [seg])
            out["rows"] = [e for b in batches for e in b["events"]]
    print(json.dumps(out, indent=2, default=str))
    return 0


def cmd_validate_segment(args) -> int:
    """Load + self-check an on-disk segment (cli/ValidateSegments.java)."""
    from druid_tpu.storage.format import load_segment
    try:
        seg = load_segment(args.directory)
    except Exception as e:
        print(f"INVALID: cannot load: {e}", file=sys.stderr)
        return 1
    problems = []
    n = seg.n_rows
    if len(seg.time_ms) != n:
        problems.append("time column length mismatch")
    for name, col in seg.dims.items():
        if len(col.ids) != n:
            problems.append(f"dim {name}: id column length {len(col.ids)}")
        if n and (col.ids.max() >= col.cardinality or col.ids.min() < 0):
            problems.append(f"dim {name}: id out of dictionary range")
        vals = col.dictionary.values
        if list(vals) != sorted(vals):
            problems.append(f"dim {name}: dictionary not sorted")
    for name, m in seg.metrics.items():
        if len(m.values) != n:
            problems.append(f"metric {name}: length {len(m.values)}")
    if n and not (seg.time_ms[:-1] <= seg.time_ms[1:]).all():
        problems.append("rows not time-sorted")
    if problems:
        print("INVALID: " + "; ".join(problems), file=sys.stderr)
        return 1
    print(f"OK: {seg.id} rows={n} dims={len(seg.dims)} "
          f"metrics={len(seg.metrics)}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="druid_tpu",
                                description="TPU-native analytics engine")
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("server", help="run the single-process cluster")
    s.add_argument("--config", default=None, help="properties/json file")
    s.set_defaults(fn=cmd_server)

    s = sub.add_parser("dump-segment", help="inspect an on-disk segment")
    s.add_argument("directory")
    s.add_argument("--full", action="store_true", help="load + column stats")
    s.add_argument("--rows", type=int, default=0, help="dump first N rows")
    s.set_defaults(fn=cmd_dump_segment)

    s = sub.add_parser("validate-segment", help="check an on-disk segment")
    s.add_argument("directory")
    s.set_defaults(fn=cmd_validate_segment)

    s = sub.add_parser("version")
    s.set_defaults(fn=lambda a: (print(VERSION), 0)[1])

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
