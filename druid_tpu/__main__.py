import sys

from druid_tpu.cli import main

sys.exit(main())
