"""Task model: batch index, compaction, kill.

Reference analogs (indexing-service/src/main/java/org/apache/druid/indexing/):
  common/task/Task.java      — JSON-polymorphic task SPI
  common/task/IndexTask.java:406 — batch index: determineShardSpecs (:435)
    then generateAndPublishSegments (:872)
  common/task/CompactionTask.java — re-index an interval into fewer/newer
    segments (drives auto-compaction)
  common/task/KillTask.java  — permanently delete unused segments
  §3.3 call stack: firehose → IncrementalIndex.add (rollup hot loop) →
    persist → merge → push → SegmentTransactionalInsertAction

TPU-first: the ingest hot loop is the vectorized IncrementalIndex; shard
determination is a single pass over parsed batches (numpy bucketing), not a
separate M/R-style cardinality job.
"""
from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, TYPE_CHECKING

import numpy as np

from druid_tpu.cluster.metadata import SegmentDescriptor
from druid_tpu.cluster.shardspec import (HashBasedNumberedShardSpec,
                                         NoneShardSpec, NumberedShardSpec)
from druid_tpu.data.segment import Segment, SegmentId
from druid_tpu.ingest.incremental import IncrementalIndex
from druid_tpu.ingest.input import (Firehose, InputRowParser, RowBatch,
                                    TransformSpec)
from druid_tpu.ingest.merger import merge_segments
from druid_tpu.query import aggregators as A
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval, condense

if TYPE_CHECKING:
    from druid_tpu.indexing.overlord import TaskToolbox


@dataclass
class TaskStatus:
    task_id: str
    state: str                   # RUNNING | SUCCESS | FAILED
    error: Optional[str] = None

    @staticmethod
    def success(task_id):
        return TaskStatus(task_id, "SUCCESS")

    @staticmethod
    def failure(task_id, error):
        return TaskStatus(task_id, "FAILED", str(error))


class Task:
    """SPI: id, type, datasource, priority; run(toolbox) does the work."""
    task_type = "base"
    priority = 0

    def __init__(self, task_id: Optional[str], datasource: str):
        self.id = task_id or f"{self.task_type}_{datasource}_{uuid.uuid4().hex[:8]}"
        self.datasource = datasource

    def run(self, toolbox: "TaskToolbox") -> TaskStatus:
        raise NotImplementedError

    def to_json(self) -> dict:
        return {"type": self.task_type, "id": self.id,
                "dataSource": self.datasource}


@dataclass
class IndexTuningConfig:
    max_rows_per_segment: int = 5_000_000
    max_rows_in_memory: int = 1_000_000
    partition_dimensions: Sequence[str] = ()   # hash partitioning dims


class IndexTask(Task):
    """Single-phase batch ingest (reference IndexTask 'index' type).

    determineShardSpecs + generateAndPublishSegments collapse into one
    vectorized pass: parse → transform → bucket by segment granularity →
    split buckets over max_rows_per_segment into hash partitions → rollup
    per partition → push + transactional publish under the task lock's
    version."""
    task_type = "index"
    priority = 50   # batch replaces: above compaction, below streaming

    def __init__(self, datasource: str, firehose: Firehose,
                 parser: Optional[InputRowParser],
                 metric_specs: Sequence[A.AggregatorSpec],
                 dimensions: Optional[Sequence[str]] = None,
                 transform: Optional[TransformSpec] = None,
                 segment_granularity: str = "day",
                 query_granularity: str = "none",
                 rollup: bool = True,
                 tuning: Optional[IndexTuningConfig] = None,
                 task_id: Optional[str] = None,
                 appending: bool = False):
        super().__init__(task_id, datasource)
        self.firehose = firehose
        self.parser = parser
        self.metric_specs = list(metric_specs)
        self.dimensions = list(dimensions) if dimensions else None
        self.transform = transform
        self.segment_granularity = Granularity.of(segment_granularity)
        self.query_granularity = query_granularity
        self.rollup = rollup
        self.tuning = tuning or IndexTuningConfig()
        self.appending = appending

    def to_json(self) -> dict:
        schema = {
            "dataSource": self.datasource,
            "metricsSpec": [a.to_json() for a in self.metric_specs],
            "granularitySpec": {
                "segmentGranularity": str(self.segment_granularity),
                "queryGranularity": self.query_granularity,
                "rollup": self.rollup},
        }
        if self.parser is not None:
            schema["parser"] = self.parser.to_json()
        if self.dimensions is not None:
            schema["dimensionsSpec"] = {"dimensions": list(self.dimensions)}
        if self.transform is not None:
            schema["transformSpec"] = self.transform.to_json()
        return {"type": "index", "id": self.id, "appending": self.appending,
                "spec": {
                    "ioConfig": {"type": "index",
                                 "firehose": self.firehose.to_json()},
                    "dataSchema": schema,
                    "tuningConfig": {
                        "maxRowsPerSegment": self.tuning.max_rows_per_segment,
                        "maxRowsInMemory": self.tuning.max_rows_in_memory,
                        "partitionDimensions":
                            list(self.tuning.partition_dimensions)}}}

    def _parse(self, raw: List) -> RowBatch:
        if self.parser is not None:
            batch = self.parser.parse_batch(raw)
        else:
            ts = [r["timestamp"] for r in raw]
            keys = {k for r in raw for k in r if k != "timestamp"}
            batch = RowBatch(ts, {k: [r.get(k) for r in raw]
                                  for k in sorted(keys)})
        if self.transform is not None:
            batch = self.transform.apply(batch)
        return batch

    def run(self, toolbox: "TaskToolbox") -> TaskStatus:
        # phase 1: read + bucket (determineShardSpecs analog)
        buckets: Dict[int, List[RowBatch]] = {}
        bucket_rows: Dict[int, int] = {}
        for raw in self.firehose.batches(self.tuning.max_rows_in_memory):
            batch = self._parse(raw)
            if not len(batch):
                continue
            ts = np.asarray(batch.timestamps, dtype=np.int64)
            starts = self.segment_granularity.bucket_start_array(ts)
            for st in np.unique(starts):
                sel = starts == st
                sub = RowBatch(
                    ts[sel].tolist(),
                    {k: [v for v, m in zip(col, sel) if m]
                     for k, col in batch.columns.items()})
                buckets.setdefault(int(st), []).append(sub)
                bucket_rows[int(st)] = bucket_rows.get(int(st), 0) + len(sub)
        if not buckets:
            return TaskStatus.success(self.id)

        intervals = condense([
            Interval(st, self.segment_granularity.next_bucket(st))
            for st in buckets])
        from druid_tpu.indexing.locks import LockType
        lock = toolbox.lock(self, intervals,
                            lock_type=LockType.SHARED if self.appending
                            else LockType.EXCLUSIVE)
        if lock is None:
            return TaskStatus.failure(self.id, "could not acquire lock")

        # phase 2: build + publish per bucket
        published: List[SegmentDescriptor] = []
        pushed_segments: List[Segment] = []
        for st, batches in sorted(buckets.items()):
            iv = Interval(st, self.segment_granularity.next_bucket(st))
            n_parts = max(1, -(-bucket_rows[st] //
                               self.tuning.max_rows_per_segment))
            part_batches: List[List[RowBatch]] = [[] for _ in range(n_parts)]
            for b in batches:
                if n_parts == 1:
                    part_batches[0].append(b)
                    continue
                pids = self._partition_ids(b, n_parts)
                for p in range(n_parts):
                    sel = pids == p
                    if not sel.any():
                        continue
                    part_batches[p].append(RowBatch(
                        [t for t, m in zip(b.timestamps, sel) if m],
                        {k: [v for v, m in zip(col, sel) if m]
                         for k, col in b.columns.items()}))
            hash_partitioned = (n_parts > 1
                                and bool(self.tuning.partition_dimensions)
                                and not self.appending)
            for p, pbs in enumerate(part_batches):
                if not pbs and not hash_partitioned:
                    continue
                # hash partitioning publishes EMPTY partitions too — the
                # timeline only shows a numbered set once it is complete
                index = IncrementalIndex(
                    self.datasource, iv, self.metric_specs,
                    dimensions=self.dimensions,
                    query_granularity=self.query_granularity,
                    rollup=self.rollup,
                    max_rows_in_memory=10 ** 12)
                for b in pbs:
                    index.add_batch(b)
                if self.appending:
                    version, pnum = toolbox.metadata.allocate_segment(
                        self.datasource, iv)
                else:
                    version, pnum = lock.version, p
                seg = index.to_segment(version, pnum)
                if n_parts == 1 and not self.appending:
                    spec = NoneShardSpec(0)
                elif self.tuning.partition_dimensions and not self.appending:
                    spec = HashBasedNumberedShardSpec(
                        pnum, n_parts,
                        tuple(self.tuning.partition_dimensions))
                else:
                    spec = NumberedShardSpec(pnum,
                                             0 if self.appending else n_parts)
                desc = SegmentDescriptor(self.datasource, iv, version, pnum,
                                         spec, num_rows=seg.n_rows)
                desc = toolbox.push(seg, desc)
                published.append(desc)
                pushed_segments.append(seg)
        if toolbox.lockbox.is_revoked(self.id):
            return TaskStatus.failure(self.id, "lock revoked")
        if not toolbox.publish(self, published):
            return TaskStatus.failure(self.id, "transactional publish failed")
        return TaskStatus.success(self.id)

    def _partition_ids(self, batch: RowBatch, n_parts: int) -> np.ndarray:
        dims = list(self.tuning.partition_dimensions)
        if dims:
            # MUST match HashBasedNumberedShardSpec's routing hash, or the
            # broker's shard pruning drops rows the spec claims aren't here
            from druid_tpu.cluster.shardspec import _hash_row
            cols = [batch.columns.get(d, [None] * len(batch)) for d in dims]
            return np.asarray(
                [_hash_row([None if v is None else str(v)
                            for v in (col[i] for col in cols)]) % n_parts
                 for i in range(len(batch))], dtype=np.int64)
        return np.arange(len(batch), dtype=np.int64) % n_parts


class ParallelIndexTask(IndexTask):
    """Parallel single-phase batch ingest (reference:
    indexing-service/.../parallel/ParallelIndexSupervisorTask.java, dynamic
    partitioning mode): the supervisor splits the firehose, fans sub-
    IndexTasks out over the task runner (forked peons under
    ForkingTaskRunner), and each sub-task allocates + transactionally
    publishes its own appended segments — the same per-bucket allocator
    streaming uses, so concurrent sub-tasks get sibling partitions, never
    overshadowing ones.

    Retry contract: resubmitting with the SAME task id is idempotent —
    sub-task ids are deterministic and the overlord's publish marker makes
    an already-committed sub-task's publish a no-op (a resubmission under
    a NEW id re-appends everything a previous partial run committed)."""
    task_type = "index_parallel"
    priority = 50

    def __init__(self, *args, max_num_subtasks: int = 4, **kwargs):
        kwargs.pop("appending", None)
        super().__init__(*args, appending=False, **kwargs)
        self.max_num_subtasks = max_num_subtasks

    def _subtasks(self) -> List[IndexTask]:
        return [IndexTask(
            self.datasource, split, self.parser, self.metric_specs,
            dimensions=self.dimensions, transform=self.transform,
            segment_granularity=str(self.segment_granularity),
            query_granularity=self.query_granularity, rollup=self.rollup,
            tuning=self.tuning, task_id=f"{self.id}_sub{i}", appending=True)
            for i, split in enumerate(
                self.firehose.splits(self.max_num_subtasks))]

    def run(self, toolbox: "TaskToolbox") -> TaskStatus:
        subtasks = self._subtasks()
        runner = getattr(toolbox, "task_runner", None)
        if runner is not None:
            for t in subtasks:
                runner.submit(t)
            statuses = [runner.await_task(t.id) for t in subtasks]
        else:
            # no runner surface: degrade to sequential in-process
            # execution — same results, no fan-out. Sub-task lock ids must
            # be released here; no runner will ever do it for them.
            statuses = []
            for t in subtasks:
                try:
                    statuses.append(t.run(toolbox))
                finally:
                    release = getattr(toolbox.lockbox, "release_all", None)
                    if callable(release):
                        release(t.id)
        failed = [s for s in statuses if s.state != "SUCCESS"]
        if failed:
            return TaskStatus.failure(
                self.id, f"{len(failed)}/{len(statuses)} sub-tasks failed: "
                f"{failed[0].error}")
        return TaskStatus.success(self.id)

    def to_json(self) -> dict:
        j = super().to_json()
        j["type"] = "index_parallel"
        j["spec"]["tuningConfig"]["maxNumConcurrentSubTasks"] = \
            self.max_num_subtasks
        del j["appending"]
        return j


class CompactionTask(Task):
    """Merge an interval's segments into one new-version segment
    (reference CompactionTask; scheduled by the coordinator's
    auto-compaction — NewestSegmentFirstPolicy)."""
    task_type = "compact"
    priority = 25   # below batch/streaming: loses lock races to fresh data

    def __init__(self, datasource: str, interval: Interval,
                 metric_specs: Sequence[A.AggregatorSpec],
                 query_granularity: str = "none",
                 task_id: Optional[str] = None):
        super().__init__(task_id, datasource)
        self.interval = interval
        self.metric_specs = list(metric_specs)
        self.query_granularity = query_granularity

    def to_json(self) -> dict:
        return {"type": "compact", "id": self.id,
                "dataSource": self.datasource,
                "interval": str(self.interval),
                "metricsSpec": [a.to_json() for a in self.metric_specs],
                "queryGranularity": self.query_granularity}

    def run(self, toolbox: "TaskToolbox") -> TaskStatus:
        # lock FIRST, then snapshot: reading before the lock races a batch
        # replace — the stale snapshot would republish replaced data under
        # a newer version, silently reverting the replacement
        lock = toolbox.lock(self, [self.interval])
        if lock is None:
            return TaskStatus.failure(self.id, "could not acquire lock")
        # only MVCC-visible segments: merging a not-yet-cleaned overshadowed
        # version would resurrect replaced data
        descs = [d for d in
                 toolbox.metadata.visible_segments(self.datasource,
                                                   self.interval)
                 if self.interval.contains_interval(d.interval)]
        if not descs:
            return TaskStatus.success(self.id)
        segments = [toolbox.pull(d) for d in descs]
        if any(s is None for s in segments):
            return TaskStatus.failure(self.id, "segment missing from deep storage")
        merged = merge_segments(
            segments, self.metric_specs, datasource=self.datasource,
            interval=self.interval, version=lock.version, partition=0,
            query_granularity=self.query_granularity)
        desc = SegmentDescriptor(self.datasource, self.interval, lock.version,
                                 0, NoneShardSpec(0), num_rows=merged.n_rows)
        desc = toolbox.push(merged, desc)
        if toolbox.lockbox.is_revoked(self.id):
            return TaskStatus.failure(self.id, "lock revoked")
        if not toolbox.publish(self, [desc]):
            return TaskStatus.failure(self.id, "transactional publish failed")
        return TaskStatus.success(self.id)


class KillTask(Task):
    """Permanently remove UNUSED segments in an interval: metadata rows and
    deep-storage files (reference KillTask)."""
    task_type = "kill"
    priority = 0

    def __init__(self, datasource: str, interval: Interval,
                 task_id: Optional[str] = None):
        super().__init__(task_id, datasource)
        self.interval = interval

    def to_json(self) -> dict:
        return {"type": "kill", "id": self.id,
                "dataSource": self.datasource,
                "interval": str(self.interval)}

    def run(self, toolbox: "TaskToolbox") -> TaskStatus:
        # exclusive lock: without it a concurrent move/restore over the
        # same interval interleaves with the deletes (kill misses the
        # moved files, then deletes their metadata rows — orphaned files)
        lock = toolbox.lock(self, [self.interval])
        if lock is None:
            return TaskStatus.failure(self.id, "could not acquire lock")
        descs = toolbox.metadata.unused_segments(self.datasource,
                                                 self.interval)
        for d in descs:
            toolbox.deep_storage.kill(d)
        toolbox.metadata.delete_segments([d.id for d in descs])
        return TaskStatus.success(self.id)


class MoveTask(Task):
    """Relocate UNUSED segments' deep-storage files to a target location
    and rewrite their loadSpecs (reference MoveTask: unused data migrates
    to cheaper storage without leaving the metadata catalog)."""
    task_type = "move"
    priority = 0

    def __init__(self, datasource: str, interval: Interval, target: str,
                 task_id: Optional[str] = None):
        super().__init__(task_id, datasource)
        self.interval = interval
        self.target = target

    def to_json(self) -> dict:
        return {"type": self.task_type, "id": self.id,
                "dataSource": self.datasource,
                "interval": str(self.interval), "target": self.target}

    def run(self, toolbox: "TaskToolbox") -> TaskStatus:
        # exclusive lock: a concurrent kill/restore over the same interval
        # must not interleave with the file moves
        lock = toolbox.lock(self, [self.interval])
        if lock is None:
            return TaskStatus.failure(self.id, "could not acquire lock")
        missing = []
        for d in toolbox.metadata.unused_segments(self.datasource,
                                                  self.interval):
            nd = toolbox.deep_storage.move(d, self.target)
            if nd is None or \
                    not toolbox.metadata.update_segment_payload(nd):
                # files absent, or the metadata row vanished underneath
                # (concurrent kill) leaving the moved files orphaned
                missing.append(d.id)
        if missing:
            # a green move over unpullable segments would hide data loss
            return TaskStatus.failure(
                self.id, f"segments missing from deep storage: {missing}")
        return TaskStatus.success(self.id)


class ArchiveTask(MoveTask):
    """MoveTask specialization targeting the configured archive location
    (reference ArchiveTask / DataSegmentArchiver)."""
    task_type = "archive"
    ARCHIVE_LOCATION = "archive"

    def __init__(self, datasource: str, interval: Interval,
                 task_id: Optional[str] = None):
        super().__init__(datasource, interval, self.ARCHIVE_LOCATION,
                         task_id)

    def to_json(self) -> dict:
        return {"type": "archive", "id": self.id,
                "dataSource": self.datasource,
                "interval": str(self.interval)}


class RestoreTask(Task):
    """Bring archived (unused) segments back: move files to the base
    location and mark the segments used so load rules serve them again
    (reference RestoreTask)."""
    task_type = "restore"
    priority = 0

    def __init__(self, datasource: str, interval: Interval,
                 task_id: Optional[str] = None):
        super().__init__(task_id, datasource)
        self.interval = interval

    def to_json(self) -> dict:
        return {"type": "restore", "id": self.id,
                "dataSource": self.datasource,
                "interval": str(self.interval)}

    def run(self, toolbox: "TaskToolbox") -> TaskStatus:
        from druid_tpu.storage.deep import DeepStorage
        lock = toolbox.lock(self, [self.interval])
        if lock is None:
            return TaskStatus.failure(self.id, "could not acquire lock")
        restored = []
        for d in toolbox.metadata.unused_segments(self.datasource,
                                                  self.interval):
            nd = toolbox.deep_storage.move(d, DeepStorage.BASE_LOCATION)
            if nd is None:
                return TaskStatus.failure(
                    self.id, f"segment {d.id} missing from deep storage")
            toolbox.metadata.update_segment_payload(nd)
            restored.append(nd.id)
        toolbox.metadata.mark_used(restored)
        return TaskStatus.success(self.id)


def task_from_json(j: dict) -> Task:
    t = j["type"]
    if t == "index_parallel":
        base = task_from_json({**j, "type": "index"})
        return ParallelIndexTask(
            base.datasource, base.firehose, base.parser, base.metric_specs,
            dimensions=base.dimensions, transform=base.transform,
            segment_granularity=str(base.segment_granularity),
            query_granularity=base.query_granularity, rollup=base.rollup,
            tuning=base.tuning,
            max_num_subtasks=j["spec"].get("tuningConfig", {}).get(
                "maxNumConcurrentSubTasks", 4),
            task_id=j.get("id"))
    if t == "index":
        from druid_tpu.ingest.input import firehose_from_json
        spec = j["spec"]
        io = spec["ioConfig"]
        schema = spec["dataSchema"]
        parser = InputRowParser.from_json(schema["parser"]) \
            if "parser" in schema else None
        gran = schema.get("granularitySpec", {})
        dims_spec = schema.get("dimensionsSpec")
        tuning_j = spec.get("tuningConfig", {})
        tuning = IndexTuningConfig(
            max_rows_per_segment=tuning_j.get("maxRowsPerSegment", 5_000_000),
            max_rows_in_memory=tuning_j.get("maxRowsInMemory", 1_000_000),
            partition_dimensions=tuple(
                tuning_j.get("partitionDimensions", ())))
        transform = TransformSpec.from_json(schema.get("transformSpec")) \
            if schema.get("transformSpec") else None
        return IndexTask(
            schema["dataSource"], firehose_from_json(io["firehose"]), parser,
            [A.agg_from_json(a) for a in schema.get("metricsSpec", [])],
            dimensions=(dims_spec or {}).get("dimensions") or None,
            transform=transform,
            segment_granularity=gran.get("segmentGranularity", "day"),
            query_granularity=gran.get("queryGranularity", "none"),
            rollup=gran.get("rollup", True),
            tuning=tuning,
            task_id=j.get("id"),
            appending=j.get("appending", False))
    if t == "compact":
        return CompactionTask(
            j["dataSource"], Interval.parse(j["interval"]),
            [A.agg_from_json(a) for a in j.get("metricsSpec", [])],
            query_granularity=j.get("queryGranularity", "none"),
            task_id=j.get("id"))
    if t == "kill":
        return KillTask(j["dataSource"], Interval.parse(j["interval"]),
                        task_id=j.get("id"))
    if t == "move":
        return MoveTask(j["dataSource"], Interval.parse(j["interval"]),
                        j["target"], task_id=j.get("id"))
    if t == "archive":
        return ArchiveTask(j["dataSource"], Interval.parse(j["interval"]),
                           task_id=j.get("id"))
    if t == "restore":
        return RestoreTask(j["dataSource"], Interval.parse(j["interval"]),
                           task_id=j.get("id"))
    raise ValueError(f"unknown task type {t!r}")
