"""Process-isolated task execution: forked peons + overlord action server.

Reference analogs (indexing-service/src/main/java/org/apache/druid/indexing/):
  overlord/ForkingTaskRunner.java — one OS process per task, task spec
    handed over on disk, logs captured, exit code = task outcome
  worker/WorkerTaskMonitor.java + overlord/RemoteTaskRunner.java — the
    worker heartbeat / dead-worker restart loop (single-host here: the
    runner monitors its own child processes and re-forks)
  common/actions/RemoteTaskActionClient.java — peon-side task actions
    (lock, allocate, publish) POSTed to the overlord, which executes them
    against the one authoritative lockbox + metadata store

Why processes: a task that OOMs or segfaults must not take down query
serving (the round-4 review's top structural gap). The TPU-side query path
never runs in peons — ingest is numpy-bound host work — so peons are forced
onto the CPU backend and the serving process keeps the chip.
"""
from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence

from druid_tpu.cluster.metadata import MetadataStore, SegmentDescriptor
from druid_tpu.indexing.locks import TaskLockbox
from druid_tpu.indexing.task import Task, TaskStatus
from druid_tpu.storage.deep import DeepStorage, LocalDeepStorage
from druid_tpu.utils.intervals import Interval


class TaskActionServer:
    """The overlord's task-action endpoint: every metadata/lock mutation a
    peon needs runs HERE, in the overlord process, against the one lockbox
    (TaskActionClient boundary). Actions and statuses are recorded for
    observability and tests."""

    def __init__(self, metadata: MetadataStore, lockbox: TaskLockbox,
                 host: str = "127.0.0.1", port: int = 0, runner=None):
        self.metadata = metadata
        self.lockbox = lockbox
        #: the runner sub-task submissions fan out on (set by the runner
        #: that owns this server)
        self.runner = runner
        self.actions: List[dict] = []          # received action log
        self.statuses: Dict[str, TaskStatus] = {}
        self.heartbeats: Dict[str, float] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                try:
                    self.wfile.write(data)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                try:
                    if self.path == "/action":
                        self._reply(200, outer._do_action(payload))
                    elif self.path == "/status":
                        outer._record_status(payload)
                        self._reply(200, {"ok": True})
                    elif self.path == "/heartbeat":
                        with outer._lock:
                            outer.heartbeats[payload["worker"]] = time.time()
                        self._reply(200, {"ok": True})
                    else:
                        self._reply(404, {"error": "no such path"})
                except Exception as e:   # action failure → structured error
                    self._reply(500, {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        h, p = self._httpd.server_address[:2]
        return f"http://{h}:{p}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def status(self, task_id: str) -> Optional[TaskStatus]:
        """Locked read of a peon-reported status — monitors poll this
        while handler threads record into the same dict."""
        with self._lock:
            return self.statuses.get(task_id)

    def live_workers(self, ttl: float = 30.0) -> List[str]:
        """Workers whose heartbeat arrived within `ttl` seconds — the
        overlord's view of peon liveness (WorkerTaskMonitor's periodic
        status report; process exit remains the authoritative single-host
        death signal, heartbeats are the observable)."""
        now = time.time()
        with self._lock:
            return sorted(w for w, t in self.heartbeats.items()
                          if now - t <= ttl)

    def _record_status(self, payload: dict) -> None:
        st = TaskStatus(payload["task"], payload["state"],
                        payload.get("error"))
        with self._lock:
            self.statuses[st.task_id] = st

    def _do_action(self, payload: dict) -> dict:
        task_id = payload["task"]
        action = payload["action"]
        args = payload.get("args", {})
        with self._lock:
            self.actions.append({"task": task_id, "action": action})
        if action == "lock":
            from druid_tpu.indexing.locks import LockType
            lt = LockType(args.get("lockType", "exclusive"))
            out = []
            for iv_s in args["intervals"]:
                lk = self.lockbox.acquire(task_id, args["datasource"],
                                          Interval.parse(iv_s),
                                          priority=args.get("priority", 50),
                                          lock_type=lt)
                if lk is None:
                    self.lockbox.release_all(task_id)
                    return {"lock": None}
                out.append(lk)
            return {"lock": {"version": out[0].version} if out else None}
        if action == "is_revoked":
            return {"revoked": self.lockbox.is_revoked(task_id)}
        if action == "publish":
            # idempotent per task id: a peon that died AFTER its publish
            # committed but BEFORE reporting status is re-forked, re-reads,
            # and calls publish again with freshly-allocated partitions —
            # the marker makes the retry a no-op success instead of a
            # duplicate append (exactly-once for crash-retried sub-tasks)
            marker = f"task_publish:{task_id}"
            if self.metadata.get_config(marker):
                return {"ok": True}
            descs = [SegmentDescriptor.from_json(d)
                     for d in args["segments"]]
            ok = self.lockbox.critical_section(
                task_id, lambda: self.metadata.publish_segments(descs))
            if ok:
                self.metadata.set_config(
                    marker, {"segments": [d.id for d in descs]})
            return {"ok": bool(ok)}
        if action == "allocate_segment":
            version, pnum = self.metadata.allocate_segment(
                args["datasource"], Interval.parse(args["interval"]))
            return {"version": version, "partition": pnum}
        if action == "visible_segments":
            descs = self.metadata.visible_segments(
                args["datasource"], Interval.parse(args["interval"]))
            return {"segments": [d.to_json() for d in descs]}
        if action == "unused_segments":
            descs = self.metadata.unused_segments(
                args["datasource"], Interval.parse(args["interval"]))
            return {"segments": [d.to_json() for d in descs]}
        if action == "delete_segments":
            self.metadata.delete_segments(args["ids"])
            return {"ok": True}
        if action == "submit_task":
            # supervisor tasks (ParallelIndexTask) fan sub-tasks out
            # through the overlord — each gets its own peon
            if self.runner is None:
                raise ValueError("no task runner attached")
            from druid_tpu.indexing.task import task_from_json
            sub = task_from_json(args["spec"])
            self.runner.submit(sub)
            return {"ok": True, "task": sub.id}
        if action == "task_status":
            if self.runner is None:
                raise ValueError("no task runner attached")
            st = self.runner.status(args["id"])
            if st is None:
                return {"state": "UNKNOWN", "error": None}
            return {"state": st.state, "error": st.error}
        raise ValueError(f"unknown task action {action!r}")


# ---------------------------------------------------------------------------
# Peon side: the toolbox whose actions travel over HTTP
# ---------------------------------------------------------------------------

class _RemoteActions:
    def __init__(self, base_url: str, task_id: str, timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.task_id = task_id
        self.timeout = timeout

    def call(self, action: str, **args) -> dict:
        body = json.dumps({"task": self.task_id, "action": action,
                           "args": args}).encode()
        req = urllib.request.Request(
            self.base_url + "/action", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def post(self, path: str, payload: dict) -> None:
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.base_url + path, data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            r.read()


class _PeonLock:
    def __init__(self, version: str):
        self.version = version


class _RemoteMetadata:
    """The slice of MetadataStore tasks touch, proxied through actions."""

    def __init__(self, actions: _RemoteActions):
        self._a = actions

    def allocate_segment(self, datasource: str, interval: Interval,
                         version=None):
        r = self._a.call("allocate_segment", datasource=datasource,
                         interval=str(interval))
        return r["version"], r["partition"]

    def visible_segments(self, datasource: str, interval: Interval):
        r = self._a.call("visible_segments", datasource=datasource,
                         interval=str(interval))
        return [SegmentDescriptor.from_json(d) for d in r["segments"]]

    def unused_segments(self, datasource: str, interval: Interval):
        r = self._a.call("unused_segments", datasource=datasource,
                         interval=str(interval))
        return [SegmentDescriptor.from_json(d) for d in r["segments"]]

    def delete_segments(self, ids: Sequence[str]) -> None:
        self._a.call("delete_segments", ids=list(ids))


class _RemoteLockbox:
    def __init__(self, actions: _RemoteActions):
        self._a = actions

    def is_revoked(self, task_id: str) -> bool:
        return bool(self._a.call("is_revoked")["revoked"])


class _RemoteTaskRunner:
    """Peon-side sub-task fan-out: submissions go to the overlord's action
    endpoint, which forks a peon per sub-task; await polls status (the
    reference supervisor task's HTTP round to the overlord)."""

    def __init__(self, actions: _RemoteActions, poll_interval: float = 0.2):
        self._a = actions
        self.poll_interval = poll_interval

    def submit(self, task: Task) -> str:
        return self._a.call("submit_task", spec=task.to_json())["task"]

    def await_task(self, task_id: str, timeout: float = 600.0) -> TaskStatus:
        deadline = time.time() + timeout
        while time.time() < deadline:
            r = self._a.call("task_status", id=task_id)
            if r["state"] in ("SUCCESS", "FAILED"):
                return TaskStatus(task_id, r["state"], r.get("error"))
            time.sleep(self.poll_interval)
        raise TimeoutError(f"sub-task {task_id} still running")


class PeonToolbox:
    """TaskToolbox for a forked peon: lock/publish/metadata actions go to
    the overlord over HTTP; segment bytes go straight to shared deep
    storage (exactly the reference's split — peons push to S3/HDFS
    themselves, only the metadata commit runs overlord-side)."""

    def __init__(self, actions: _RemoteActions, deep_storage: DeepStorage):
        self._a = actions
        self.deep_storage = deep_storage
        self.metadata = _RemoteMetadata(actions)
        self.lockbox = _RemoteLockbox(actions)
        self.task_runner = _RemoteTaskRunner(actions)

    def lock(self, task: Task, intervals: Sequence[Interval],
             lock_type=None):
        from druid_tpu.utils.intervals import condense
        r = self._a.call("lock", datasource=task.datasource,
                         intervals=[str(iv) for iv in condense(intervals)],
                         priority=task.priority,
                         lockType=getattr(lock_type, "value", "exclusive"))
        lk = r.get("lock")
        return _PeonLock(lk["version"]) if lk else None

    def push(self, segment, descriptor: SegmentDescriptor):
        return self.deep_storage.push(segment, descriptor)

    def pull(self, descriptor: SegmentDescriptor):
        return self.deep_storage.pull(descriptor)

    def publish(self, task: Task,
                descriptors: Sequence[SegmentDescriptor]) -> bool:
        return bool(self._a.call(
            "publish", segments=[d.to_json() for d in descriptors])["ok"])


def peon_main(spec_path: str) -> int:
    """Entry point of the forked peon process (CliPeon analog): read the
    task spec, run the task against the remote toolbox, report status."""
    with open(spec_path) as f:
        spec = json.load(f)
    from druid_tpu.indexing.task import task_from_json
    task = task_from_json(spec["task"])
    actions = _RemoteActions(spec["actionUrl"], task.id)

    # periodic worker heartbeat for the overlord's liveness view
    stop_hb = threading.Event()

    def beat():
        while not stop_hb.is_set():
            try:
                actions.post("/heartbeat", {"worker": f"peon-{task.id}"})
            except Exception:
                # overlord unreachable: its liveness view ages us out
                logging.getLogger(__name__).debug(
                    "heartbeat for peon-%s failed", task.id, exc_info=True)
            stop_hb.wait(spec.get("heartbeatPeriod", 5.0))

    threading.Thread(target=beat, daemon=True).start()
    toolbox = PeonToolbox(actions,
                          LocalDeepStorage(spec["deepStorageDir"]))
    try:
        status = task.run(toolbox)
    except Exception as e:
        status = TaskStatus.failure(task.id, e)
    finally:
        stop_hb.set()
    actions.post("/status", {"task": task.id, "state": status.state,
                             "error": status.error})
    return 0 if status.state == "SUCCESS" else 1


# ---------------------------------------------------------------------------
# Overlord side: the forking runner
# ---------------------------------------------------------------------------

class ForkingTaskRunner:
    """Run each task in a forked python process. A peon that dies without
    reporting a terminal status (OOM-kill, crash) releases its locks and is
    re-forked up to max_restarts times — the single-host collapse of
    RemoteTaskRunner's dead-worker task restart."""

    def __init__(self, metadata: MetadataStore,
                 deep_storage_dir: Optional[str] = None,
                 lockbox: Optional[TaskLockbox] = None,
                 max_restarts: int = 2,
                 poll_interval: float = 0.1):
        self.metadata = metadata
        self.lockbox = lockbox or TaskLockbox()
        self.deep_storage_dir = deep_storage_dir or tempfile.mkdtemp(
            prefix="druid_tpu_deep_")
        self.deep_storage = LocalDeepStorage(self.deep_storage_dir)
        self.actions = TaskActionServer(metadata, self.lockbox)
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.processes: Dict[str, subprocess.Popen] = {}
        self.attempts: Dict[str, int] = {}
        self._statuses: Dict[str, TaskStatus] = {}
        self._monitors: Dict[str, threading.Thread] = {}
        self._specs: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._listeners: List[Callable[[TaskStatus], None]] = []
        self._shutdown = False
        self.actions.runner = self

    def add_listener(self, fn: Callable[[TaskStatus], None]) -> None:
        self._listeners.append(fn)

    # ---- lifecycle ------------------------------------------------------
    def submit(self, task: Task) -> str:
        with self._lock:
            if task.id in self._monitors:
                return task.id
            # serialize FIRST: a task that cannot round-trip (unserializable
            # firehose, non-JSON payload) must fail the submit, not leave a
            # forever-RUNNING orphan row in the metadata store
            task_json = task.to_json()
            spec_dir = tempfile.mkdtemp(prefix=f"peon_{task.id[:24]}_")
            spec_path = os.path.join(spec_dir, "task.json")
            with open(spec_path, "w") as f:
                json.dump({"task": task_json,
                           "actionUrl": self.actions.url,
                           "deepStorageDir": self.deep_storage_dir}, f)
            self.metadata.insert_task(task.id, task.datasource, "RUNNING",
                                      task_json)
            self._statuses[task.id] = TaskStatus(task.id, "RUNNING")
            self._specs[task.id] = spec_path
            self.attempts[task.id] = 0
            t = threading.Thread(target=self._monitor, args=(task.id,),
                                 daemon=True)
            self._monitors[task.id] = t
        t.start()
        return task.id

    def _fork(self, task_id: str, attempt: int) -> subprocess.Popen:
        env = dict(os.environ)
        # peons never own the TPU: ingest is host-side numpy work, and a
        # crashed peon must not wedge the chip the serving process holds —
        # strip any TPU-plugin site dir and force the CPU backend
        env["JAX_PLATFORMS"] = "cpu"
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        paths = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                 if p and "axon" not in p]
        if repo_root not in paths:
            paths.insert(0, repo_root)
        env["PYTHONPATH"] = os.pathsep.join(paths)
        with self._lock:
            spec_path = self._specs[task_id]
        log_path = spec_path + f".log.{attempt}"
        logf = open(log_path, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "druid_tpu.peon", spec_path],
            stdout=logf, stderr=subprocess.STDOUT, env=env)
        logf.close()
        with self._lock:
            self.processes[task_id] = proc
        return proc

    #: one bounded park quantum on a live peon; the monitor re-checks
    #: shutdown between quanta instead of parking on wait() forever
    PROC_WAIT_POLL_S = 1.0
    #: grace between SIGTERM and SIGKILL when shutdown interrupts a peon
    PROC_KILL_GRACE_S = 5.0

    def _await_proc(self, proc) -> None:
        """Park on the peon in bounded quanta. A shutdown observed between
        quanta escalates terminate → (after PROC_KILL_GRACE_S) kill, so
        the monitor thread can never outlive stop() on a wedged peon —
        the one pre-known stall in the tree (a bare proc.wait() here
        parked the monitor for as long as the peon cared to run)."""
        while True:
            try:
                proc.wait(timeout=self.PROC_WAIT_POLL_S)
                return
            except subprocess.TimeoutExpired:
                pass
            if self._shutdown:
                break
        proc.terminate()
        try:
            proc.wait(timeout=self.PROC_KILL_GRACE_S)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=self.PROC_KILL_GRACE_S)
            except subprocess.TimeoutExpired:
                pass        # unkillable (kernel-stuck): do not hang stop()

    def _monitor(self, task_id: str) -> None:
        while True:
            # snapshot the attempt count under the lock once; unlocked
            # re-reads below would race a concurrent resubmit's reset
            with self._lock:
                self.attempts[task_id] += 1
                attempt = self.attempts[task_id]
            proc = self._fork(task_id, attempt)
            self._await_proc(proc)
            reported = self.actions.status(task_id)
            if reported is not None and reported.state in ("SUCCESS",
                                                           "FAILED"):
                status = reported
                break
            # peon died without a terminal report: free its locks so the
            # retry (or anyone else) can proceed, then maybe re-fork
            self.lockbox.release_all(task_id)
            if self._shutdown:
                status = TaskStatus.failure(task_id, "runner shut down")
                break
            if attempt > self.max_restarts:
                status = TaskStatus.failure(
                    task_id, f"peon died {attempt} times "
                    f"(exit {proc.returncode})")
                break
        self.lockbox.release_all(task_id)
        with self._lock:
            self._statuses[task_id] = status
        self.metadata.update_task_status(task_id, status.state)
        for fn in list(self._listeners):
            fn(status)

    # ---- status ---------------------------------------------------------
    def status(self, task_id: str) -> Optional[TaskStatus]:
        with self._lock:
            st = self._statuses.get(task_id)
        return st

    def await_task(self, task_id: str, timeout: float = 300.0) -> TaskStatus:
        mon = self._monitors.get(task_id)
        if mon is None:
            raise KeyError(task_id)
        mon.join(timeout)
        if mon.is_alive():
            raise TimeoutError(f"task {task_id} still running")
        return self.status(task_id)

    def run_task(self, task: Task, timeout: float = 300.0) -> TaskStatus:
        self.submit(task)
        return self.await_task(task.id, timeout)

    def task_log(self, task_id: str) -> str:
        """The task's captured stdout/stderr across all peon attempts
        (reference: TaskLogStreamer / overlord GET /task/{id}/log)."""
        spec = self._specs.get(task_id)
        if spec is None:
            return ""
        import glob as globlib
        parts = []
        for path in sorted(globlib.glob(spec + ".log.*")):
            attempt = path.rsplit(".", 1)[-1]
            with open(path, "rb") as f:
                parts.append(f"--- attempt {attempt} ---\n"
                             + f.read().decode(errors="replace"))
        return "\n".join(parts)

    def shutdown(self) -> None:
        # order matters: the flag stops monitors from re-forking the peons
        # the kill below makes look dead
        self._shutdown = True
        with self._lock:
            procs = list(self.processes.values())
            monitors = list(self._monitors.values())
        for p in procs:
            if p.poll() is None:
                p.kill()
        # reap the monitor threads before tearing down the action server
        # they report through: each sees its peon dead + the shutdown flag
        # and finishes; an unjoined monitor would race the teardown below
        for t in monitors:
            if t.is_alive():
                t.join(timeout=5.0)
        self.actions.stop()
