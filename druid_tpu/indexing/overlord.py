"""Overlord: task queue, toolbox, and the local task runner.

Reference analogs (indexing-service/.../overlord/):
  TaskMaster/TaskQueue.java — task submission, state machine, persistence
  TaskLockbox.java          — via druid_tpu/indexing/locks.py
  ForkingTaskRunner         — here a thread-pool runner (tasks are
    numpy/JAX-bound; processes add nothing on one host — multi-host
    runners would dispatch over the wire like RemoteTaskRunner)
  TaskToolbox + TaskActionClient — the peon-side service locator whose
    actions (lock acquire, segment push, transactional insert) all land on
    the overlord/metadata exactly like actions/SegmentTransactionalInsertAction
"""
from __future__ import annotations

import logging
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence

from typing import TYPE_CHECKING

if TYPE_CHECKING:        # runtime import would cycle through coordination
    from druid_tpu.coordination.latch import LeaderParticipant

from druid_tpu.cluster.metadata import (MetadataStore, SegmentDescriptor,
                                        StaleTermError)
from druid_tpu.data.segment import Segment
from druid_tpu.indexing.locks import TaskLock, TaskLockbox
from druid_tpu.indexing.task import Task, TaskStatus
from druid_tpu.storage.deep import DeepStorage, InMemoryDeepStorage
from druid_tpu.utils.intervals import Interval, condense

log = logging.getLogger(__name__)


class TaskToolbox:
    """What a running task may touch (reference TaskToolbox): metadata
    actions, the lockbox, deep storage push/pull, and (for supervisor
    tasks) the task runner to fan sub-tasks out on."""

    def __init__(self, metadata: MetadataStore, lockbox: TaskLockbox,
                 deep_storage: DeepStorage, task_runner=None,
                 fence_source: Optional[Callable[[], Optional[tuple]]] = None):
        """fence_source: supplies the overlord's CURRENT (service, term,
        holder) fencing token at publish time — read late, not captured at
        toolbox construction, so a task that outlives a leadership change
        publishes with the stale term and is rejected."""
        self.metadata = metadata
        self.lockbox = lockbox
        self.deep_storage = deep_storage
        self.task_runner = task_runner
        self.fence_source = fence_source

    def lock(self, task: Task, intervals: Sequence[Interval],
             lock_type=None) -> Optional[TaskLock]:
        """LockAcquireAction: one lock covering the task's intervals.
        Appending tasks take SHARED locks so parallel sub-tasks / streaming
        replicas can append to one interval concurrently."""
        from druid_tpu.indexing.locks import LockType
        lt = lock_type or LockType.EXCLUSIVE
        locks = []
        for iv in condense(intervals):
            l = self.lockbox.acquire(task.id, task.datasource, iv,
                                     priority=task.priority, lock_type=lt)
            if l is None:
                self.lockbox.release_all(task.id)
                return None
            locks.append(l)
        return locks[0] if locks else None

    def push(self, segment: Segment, descriptor: SegmentDescriptor
             ) -> SegmentDescriptor:
        return self.deep_storage.push(segment, descriptor)

    def pull(self, descriptor: SegmentDescriptor) -> Optional[Segment]:
        return self.deep_storage.pull(descriptor)

    def publish(self, task: Task,
                descriptors: Sequence[SegmentDescriptor]) -> bool:
        """SegmentTransactionalInsertAction: the revocation check and the
        metadata commit run in one lockbox critical section so a revoke
        cannot interleave between them (TaskLockbox.doInCriticalSection)."""
        fence = self.fence_source() if self.fence_source is not None else None
        return self.lockbox.critical_section(
            task.id, lambda: self.metadata.publish_segments(descriptors,
                                                            fence=fence))


class Overlord:
    """Task queue + local thread runner + status persistence.

    With a `leader` participant attached (coordination.LeaderParticipant —
    the TaskMaster leadership gating) task submission is accepted ONLY on
    the leader (NotLeaderError carries the leader's URL for redirect), and
    every task-metadata write and segment publish is fenced with the
    current term, so tasks started under a deposed overlord cannot commit
    past its successor's takeover."""

    def __init__(self, metadata: MetadataStore,
                 deep_storage: Optional[DeepStorage] = None,
                 max_workers: int = 4,
                 leader: Optional["LeaderParticipant"] = None):
        self.metadata = metadata
        self.deep_storage = deep_storage or InMemoryDeepStorage()
        self.lockbox = TaskLockbox()
        self.leader = leader
        self._pool = ThreadPoolExecutor(max_workers=max_workers)
        self._futures: Dict[str, Future] = {}
        self._statuses: Dict[str, TaskStatus] = {}
        self._lock = threading.Lock()
        self._listeners: List[Callable[[TaskStatus], None]] = []

    def _fence(self) -> Optional[tuple]:
        return self.leader.fence() if self.leader is not None else None

    def _require_leader(self) -> None:
        if self.leader is not None and not self.leader.is_leader():
            from druid_tpu.coordination.latch import NotLeaderError
            url = None
            try:
                lease = self.leader.store.read(self.leader.service)
                if lease is not None:
                    url = lease.url
            except Exception:
                log.debug("could not resolve current leader url for "
                          "redirect", exc_info=True)
            raise NotLeaderError(
                f"overlord [{self.leader.node_id}] is not the leader",
                leader_url=url)

    def toolbox(self) -> TaskToolbox:
        # sub-tasks get DEDICATED threads: a supervisor task blocks one of
        # the bounded pool's workers while awaiting its sub-tasks, so
        # scheduling those on the same pool deadlocks under exhaustion
        return TaskToolbox(self.metadata, self.lockbox, self.deep_storage,
                           task_runner=_DedicatedSubtaskRunner(self),
                           fence_source=self._fence)

    def add_listener(self, fn: Callable[[TaskStatus], None]) -> None:
        self._listeners.append(fn)

    # ---- submission -----------------------------------------------------
    def submit(self, task: Task) -> str:
        self._require_leader()
        with self._lock:
            if task.id in self._futures:
                return task.id
            self.metadata.insert_task(task.id, task.datasource, "RUNNING",
                                      task.to_json(), fence=self._fence())
            self._statuses[task.id] = TaskStatus(task.id, "RUNNING")
            self._futures[task.id] = self._pool.submit(self._run, task)
            return task.id

    def _run(self, task: Task) -> TaskStatus:
        try:
            status = task.run(self.toolbox())
        except Exception as e:          # task crash = failure, not overlord crash
            status = TaskStatus.failure(task.id, e)
        finally:
            self.lockbox.release_all(task.id)
        with self._lock:
            self._statuses[task.id] = status
        try:
            self.metadata.update_task_status(task.id, status.state,
                                             fence=self._fence())
        except StaleTermError as e:
            # a deposed overlord may not record statuses — its successor
            # re-adopts the task row; in-memory status stands
            log.warning("status write for [%s] fenced off: %s", task.id, e)
        for fn in list(self._listeners):
            fn(status)
        return status

    # ---- status ---------------------------------------------------------
    def status(self, task_id: str) -> Optional[TaskStatus]:
        with self._lock:
            return self._statuses.get(task_id)

    def await_task(self, task_id: str, timeout: float = 300.0) -> TaskStatus:
        fut = self._futures.get(task_id)
        if fut is None:
            raise KeyError(task_id)
        return fut.result(timeout=timeout)

    def run_task(self, task: Task, timeout: float = 300.0) -> TaskStatus:
        self.submit(task)
        return self.await_task(task.id, timeout)

    def shutdown(self):
        self._pool.shutdown(wait=True)


class _DedicatedSubtaskRunner:
    """Runs sub-tasks on their own threads (never the overlord's bounded
    pool) — see Overlord.toolbox. Status/lock bookkeeping goes through the
    overlord's _run so sub-tasks are observable like any other task."""

    def __init__(self, overlord: Overlord):
        self.overlord = overlord
        self._threads: Dict[str, threading.Thread] = {}
        self._results: Dict[str, TaskStatus] = {}

    def submit(self, task: Task) -> str:
        if task.id in self._threads:
            return task.id
        self.overlord.metadata.insert_task(task.id, task.datasource,
                                           "RUNNING", task.to_json(),
                                           fence=self.overlord._fence())

        def run():
            self._results[task.id] = self.overlord._run(task)

        t = threading.Thread(target=run, daemon=True)
        self._threads[task.id] = t
        t.start()
        return task.id

    def await_task(self, task_id: str, timeout: float = 600.0) -> TaskStatus:
        t = self._threads[task_id]
        t.join(timeout)
        if t.is_alive():
            raise TimeoutError(f"sub-task {task_id} still running")
        return self._results[task_id]
