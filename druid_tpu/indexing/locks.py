"""Task interval locks.

Reference analog: indexing-service/.../overlord/TaskLockbox.java — per
(datasource, interval) locks with priorities and revocation: a
higher-priority task may revoke a lower-priority task's lock; the revoked
task discovers this at its next action and fails. Lock versions become
segment versions (batch replace = new version over the interval).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional

from druid_tpu.utils.intervals import Interval, ts_to_iso


class LockType(Enum):
    EXCLUSIVE = "exclusive"
    SHARED = "shared"        # streaming appends to one interval share


@dataclass
class TaskLock:
    task_id: str
    datasource: str
    interval: Interval
    version: str
    priority: int = 0
    lock_type: LockType = LockType.EXCLUSIVE
    revoked: bool = False


class LockConflictError(RuntimeError):
    pass


class TaskLockbox:
    def __init__(self):
        self._locks: List[TaskLock] = []
        self._lock = threading.Lock()

    def acquire(self, task_id: str, datasource: str, interval: Interval,
                priority: int = 0,
                lock_type: LockType = LockType.EXCLUSIVE,
                version: Optional[str] = None) -> Optional[TaskLock]:
        """None = conflict with an equal/higher-priority lock. A strictly
        higher priority revokes conflicting lower-priority locks
        (TaskLockbox.revokeLock)."""
        with self._lock:
            conflicts = [l for l in self._locks
                         if l.datasource == datasource
                         and l.interval.overlaps(interval)
                         and l.task_id != task_id
                         and not l.revoked
                         and not (l.lock_type == LockType.SHARED
                                  and lock_type == LockType.SHARED)]
            for c in conflicts:
                if c.priority >= priority:
                    return None
            for c in conflicts:
                c.revoked = True
            # reuse this task's existing covering lock
            for l in self._locks:
                if l.task_id == task_id and l.datasource == datasource \
                        and l.interval.contains_interval(interval) \
                        and not l.revoked:
                    return l
            lock = TaskLock(task_id, datasource, interval,
                            version or ts_to_iso(int(time.time() * 1000)),
                            priority, lock_type)
            self._locks.append(lock)
            return lock

    def critical_section(self, task_id: str, fn):
        """Run fn() under the lockbox lock iff none of the task's locks are
        revoked (TaskLockbox.doInCriticalSection): revocation by a
        higher-priority task cannot interleave between the check and the
        action (e.g. a metadata publish)."""
        with self._lock:
            if any(l.task_id == task_id and l.revoked
                   for l in self._locks):
                return False
            return fn()

    def is_revoked(self, task_id: str) -> bool:
        with self._lock:
            return any(l.task_id == task_id and l.revoked
                       for l in self._locks)

    def locks_for(self, task_id: str) -> List[TaskLock]:
        with self._lock:
            return [l for l in self._locks if l.task_id == task_id]

    def release_all(self, task_id: str) -> None:
        with self._lock:
            self._locks = [l for l in self._locks if l.task_id != task_id]
