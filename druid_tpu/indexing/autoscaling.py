"""Worker autoscaling: provisioning decisions from task-queue pressure.

Reference analogs (indexing-service/src/main/java/org/apache/druid/
indexing/overlord/autoscaling/):
  PendingTaskBasedWorkerProvisioningStrategy.java — provision when pending
    tasks exceed spare capacity, terminate idle workers past the cooldown
  SimpleWorkerProvisioningStrategy.java — the threshold variant
  AutoScaler.java (EC2/GCE impls) — the SPI that actually creates and
    destroys workers; here a callable pair so deployments plug in
    k8s / GCE / anything

The strategy is pure decision logic over (pending tasks, workers) so it is
testable without any cloud; ScalingMonitor drives it from the overlord's
queue state on a period.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence


@dataclass
class WorkerInfo:
    """One worker the scaler manages (reference: Worker + its capacity).
    last_task_time defaults to NOW — a freshly provisioned worker must not
    read as idle-past-cooldown before its first task."""
    worker_id: str
    capacity: int = 1
    running_tasks: int = 0
    last_task_time: float = field(default_factory=time.monotonic)


@dataclass
class ProvisioningConfig:
    """(workerCapacityHints + ProvisioningSchedulerConfig subset)."""
    min_workers: int = 0
    max_workers: int = 8
    worker_capacity: int = 2            # tasks per worker
    scale_up_step: int = 1              # workers per decision
    idle_seconds_before_terminate: float = 600.0


@dataclass
class ScalingDecision:
    provision: int = 0                  # workers to create
    terminate: List[str] = field(default_factory=list)  # worker ids to kill


class PendingTaskProvisioningStrategy:
    """Provision when pending tasks exceed spare slots; terminate workers
    idle past the cooldown, never dropping below min_workers."""

    def __init__(self, config: Optional[ProvisioningConfig] = None):
        self.config = config or ProvisioningConfig()

    def compute(self, pending_tasks: int, workers: Sequence[WorkerInfo],
                now: Optional[float] = None) -> ScalingDecision:
        cfg = self.config
        now = time.monotonic() if now is None else now
        decision = ScalingDecision()

        # the floor provisions itself (reference strategy's minNumWorkers)
        if len(workers) < cfg.min_workers:
            decision.provision = min(cfg.min_workers - len(workers),
                                     cfg.scale_up_step)
            return decision

        spare = sum(max(w.capacity - w.running_tasks, 0) for w in workers)
        if pending_tasks > spare and len(workers) < cfg.max_workers:
            needed = -(-(pending_tasks - spare) // max(cfg.worker_capacity, 1))
            decision.provision = min(needed, cfg.scale_up_step,
                                     cfg.max_workers - len(workers))
            return decision      # never provision and terminate together

        idle = [w for w in workers
                if w.running_tasks == 0
                and now - w.last_task_time >= cfg.idle_seconds_before_terminate]
        # terminate oldest-idle first, keeping min_workers
        can_drop = len(workers) - cfg.min_workers
        if pending_tasks == 0 and can_drop > 0 and idle:
            idle.sort(key=lambda w: w.last_task_time)
            decision.terminate = [w.worker_id for w in idle[:can_drop]]
        return decision


class ScalingMonitor:
    """Drives the strategy on a period and applies decisions through the
    AutoScaler callables (ProvisioningScheduler analog). Callers provide
    `pending()` (e.g. overlord queue depth) and `workers()` snapshots."""

    def __init__(self, strategy: PendingTaskProvisioningStrategy,
                 pending: Callable[[], int],
                 workers: Callable[[], List[WorkerInfo]],
                 provision: Callable[[int], None],
                 terminate: Callable[[List[str]], None]):
        self.strategy = strategy
        self.pending = pending
        self.workers = workers
        self.provision = provision
        self.terminate = terminate
        self.history: List[ScalingDecision] = []

    def run_once(self, now: Optional[float] = None) -> ScalingDecision:
        decision = self.strategy.compute(self.pending(), self.workers(), now)
        if decision.provision:
            self.provision(decision.provision)
        if decision.terminate:
            self.terminate(decision.terminate)
        self.history.append(decision)
        return decision
