from druid_tpu.indexing.locks import LockType, TaskLock, TaskLockbox
from druid_tpu.indexing.task import (CompactionTask, IndexTask, KillTask,
                                     ParallelIndexTask, Task, TaskStatus,
                                     task_from_json)
from druid_tpu.indexing.overlord import Overlord, TaskToolbox
from druid_tpu.indexing.forking import ForkingTaskRunner, TaskActionServer

__all__ = [
    "TaskLockbox", "TaskLock", "LockType", "Task", "TaskStatus", "IndexTask",
    "CompactionTask", "KillTask", "task_from_json", "Overlord", "TaskToolbox",
    "ForkingTaskRunner", "TaskActionServer", "ParallelIndexTask",
]
