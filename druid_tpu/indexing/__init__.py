from druid_tpu.indexing.locks import LockType, TaskLock, TaskLockbox
from druid_tpu.indexing.task import (ArchiveTask, CompactionTask, IndexTask,
                                     KillTask, MoveTask, ParallelIndexTask,
                                     RestoreTask, Task, TaskStatus,
                                     task_from_json)
from druid_tpu.indexing.overlord import Overlord, TaskToolbox
from druid_tpu.indexing.forking import ForkingTaskRunner, TaskActionServer
from druid_tpu.indexing.autoscaling import (PendingTaskProvisioningStrategy,
                                            ProvisioningConfig,
                                            ScalingMonitor, WorkerInfo)

__all__ = [
    "TaskLockbox", "TaskLock", "LockType", "Task", "TaskStatus", "IndexTask",
    "CompactionTask", "KillTask", "MoveTask", "ArchiveTask", "RestoreTask",
    "task_from_json", "Overlord", "TaskToolbox",
    "ForkingTaskRunner", "TaskActionServer", "ParallelIndexTask",
    "PendingTaskProvisioningStrategy", "ProvisioningConfig",
    "ScalingMonitor", "WorkerInfo",
]
