"""ctypes loader for the native host library (native/druid_native.cpp).

The reference's storage hot path rides JVM-native mechanics (lz4-java block
codec, off-heap ByteBuffers — reference:
processing/.../segment/data/CompressionStrategy.java:48). Here it is a real
C++ shared library: built on demand with g++ the first time it's needed,
cached beside the source. Everything degrades gracefully — callers check
`available()` and fall back to zlib/numpy paths if the toolchain is absent.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SRC = os.path.join(_NATIVE_DIR, "druid_native.cpp")
_SO = os.path.join(_NATIVE_DIR, "libdruid_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
             "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        # no toolchain / compile failure: callers fall back to numpy paths
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not (os.path.exists(_SRC) and _build()):
                if not os.path.exists(_SO):
                    return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.druid_lz4_compress_bound.restype = ctypes.c_int64
        lib.druid_lz4_compress_bound.argtypes = [ctypes.c_int64]
        lib.druid_lz4_compress.restype = ctypes.c_int64
        lib.druid_lz4_compress.argtypes = [u8p, ctypes.c_int64, u8p,
                                           ctypes.c_int64]
        lib.druid_lz4_decompress.restype = ctypes.c_int64
        lib.druid_lz4_decompress.argtypes = [u8p, ctypes.c_int64, u8p,
                                             ctypes.c_int64]
        lib.druid_lz4_decompress_batch.restype = ctypes.c_int64
        lib.druid_lz4_decompress_batch.argtypes = [
            u8p, i64p, i64p, u8p, i64p, i64p, ctypes.c_int64, ctypes.c_int64]
        lib.druid_unpack_bits.restype = None
        lib.druid_unpack_bits.argtypes = [u8p, ctypes.c_int64, u8p]
        lib.druid_pack_keys.restype = None
        lib.druid_pack_keys.argtypes = [
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)), i64p,
            ctypes.c_int64, ctypes.c_int64, i64p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def lz4_compress(data: bytes | np.ndarray) -> bytes:
    lib = _load()
    assert lib is not None
    src = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray, memoryview)) \
        else np.ascontiguousarray(data).view(np.uint8).ravel()
    n = src.shape[0]
    dst = np.empty(int(lib.druid_lz4_compress_bound(n)), dtype=np.uint8)
    got = lib.druid_lz4_compress(_u8(src), n, _u8(dst), dst.shape[0])
    if got < 0:
        raise ValueError("lz4 compression overflow")
    return dst[:got].tobytes()


def lz4_decompress(data, decompressed_size: int) -> np.ndarray:
    lib = _load()
    assert lib is not None
    src = np.frombuffer(data, dtype=np.uint8)
    dst = np.empty(decompressed_size, dtype=np.uint8)
    got = lib.druid_lz4_decompress(_u8(src), src.shape[0], _u8(dst),
                                   decompressed_size)
    if got != decompressed_size:
        raise ValueError(f"lz4 malformed block (got {got}, "
                         f"want {decompressed_size})")
    return dst


def lz4_decompress_batch(blob, src_offsets: np.ndarray, src_sizes: np.ndarray,
                         dst_offsets: np.ndarray, dst_sizes: np.ndarray,
                         total_out: int, n_threads: int = 0) -> np.ndarray:
    """Decompress many blocks from one blob into one contiguous buffer,
    multi-threaded in native code (the analog of the reference decompressing
    column chunks on the processing pool)."""
    lib = _load()
    assert lib is not None
    src = np.frombuffer(blob, dtype=np.uint8)
    dst = np.empty(total_out, dtype=np.uint8)
    if n_threads <= 0:
        n_threads = min(8, os.cpu_count() or 1)
    so = np.ascontiguousarray(src_offsets, dtype=np.int64)
    ss = np.ascontiguousarray(src_sizes, dtype=np.int64)
    do = np.ascontiguousarray(dst_offsets, dtype=np.int64)
    ds = np.ascontiguousarray(dst_sizes, dtype=np.int64)
    rc = lib.druid_lz4_decompress_batch(
        _u8(src), _i64(so), _i64(ss), _u8(dst), _i64(do), _i64(ds),
        len(so), n_threads)
    if rc != 0:
        raise ValueError(f"lz4 batch decompression failed at block {-rc - 1}")
    return dst


def unpack_bits(words: np.ndarray, n_rows: int) -> np.ndarray:
    lib = _load()
    if lib is None:
        return np.unpackbits(words, count=n_rows)
    words = np.ascontiguousarray(words, dtype=np.uint8)
    out = np.empty(n_rows, dtype=np.uint8)
    lib.druid_unpack_bits(_u8(words), n_rows, out.ctypes.data_as(
        ctypes.POINTER(ctypes.c_uint8)))
    return out


def pack_keys(cols, cards) -> np.ndarray:
    """Fused group key = horner-scheme pack of int32 id columns."""
    lib = _load()
    n_rows = cols[0].shape[0] if cols else 0
    if lib is None:
        out = np.zeros(n_rows, dtype=np.int64)
        for col, card in zip(cols, cards):
            out = out * int(card) + col.astype(np.int64)
        return out
    cols = [np.ascontiguousarray(c, dtype=np.int32) for c in cols]
    arr_type = ctypes.POINTER(ctypes.c_int32) * len(cols)
    ptrs = arr_type(*[c.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
                      for c in cols])
    cards_a = np.asarray(list(cards), dtype=np.int64)
    out = np.empty(n_rows, dtype=np.int64)
    lib.druid_pack_keys(ptrs, _i64(cards_a), len(cols), n_rows, _i64(out))
    return out
