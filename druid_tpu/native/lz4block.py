"""LZ4 block-format host codec + token view (the cascade float rung's
host half).

The reference compresses float/long column chunks with lz4-java
(processing/.../segment/data/CompressionStrategy.java:48); here the block
codec has three host layers, strongest available wins:

  * the native C++ library (native/druid_native.cpp, loaded by
    druid_tpu/native/__init__.py) when the toolchain built it;
  * a pure-numpy/python encoder+decoder below, producing/consuming the
    SAME standard LZ4 block format (greedy 4-byte hash matcher) — slow but
    exact, so the cascade rung degrades gracefully off-toolchain;
  * `tokenize()`, which parses any LZ4 block into flat token arrays
    (literal stream + per-sequence literal/match lengths and offsets) —
    the DEVICE-decodable form data/cascade.py's XLA shift-window decoder
    consumes (match resolution by pointer doubling instead of the
    sequential byte copy).

Every compress is verified by a host decompress round-trip at the one
call site that caches it (cascade._lz4_encoded), so a codec bug can never
corrupt a column — it just disables the rung for that column.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

#: LZ4 block-format constants
_MINMATCH = 4
#: spec: the last 5 bytes are always literals, and a match must not start
#: within the last 12 bytes of the input
_END_LITERALS = 5
_MFLIMIT = 12
_MAX_OFFSET = 0xFFFF


def _native():
    try:
        from druid_tpu import native as nat
    except ImportError:  # druidlint: disable=swallowed-exception
        # availability probe: no loader package just means "python codec
        # only" — never an error
        return None
    return nat if nat.available() else None


# ---------------------------------------------------------------------------
# Pure-python encoder / decoder (standard LZ4 block format)
# ---------------------------------------------------------------------------

def _emit_length(out: bytearray, n: int) -> None:
    while n >= 255:
        out.append(255)
        n -= 255
    out.append(n)


def py_compress(src: bytes) -> bytes:
    """Greedy hash-matcher LZ4 block encoder (exact, slow — the
    off-toolchain fallback). Emits the standard block format the native
    decoder, py_decompress, and tokenize all accept."""
    src = bytes(src)
    n = len(src)
    out = bytearray()
    if n == 0:
        out.append(0)                     # one empty-literal sequence
        return bytes(out)
    table: dict = {}
    i = 0
    anchor = 0
    # matches may not start within the last MFLIMIT bytes
    limit = n - _MFLIMIT
    while i <= limit - 1 and i + _MINMATCH <= n:
        key = src[i:i + _MINMATCH]
        j = table.get(key)
        table[key] = i
        if j is None or i - j > _MAX_OFFSET or src[j:j + _MINMATCH] != key:
            i += 1
            continue
        # extend the match; it must end at least END_LITERALS from the end
        m = _MINMATCH
        max_m = (n - _END_LITERALS) - i
        while m < max_m and src[j + m] == src[i + m]:
            m += 1
        lit = src[anchor:i]
        ml = m - _MINMATCH
        token = (min(len(lit), 15) << 4) | min(ml, 15)
        out.append(token)
        if len(lit) >= 15:
            _emit_length(out, len(lit) - 15)
        out += lit
        out += (i - j).to_bytes(2, "little")
        if ml >= 15:
            _emit_length(out, ml - 15)
        i += m
        anchor = i
    # final sequence: literals only
    lit = src[anchor:]
    out.append(min(len(lit), 15) << 4)
    if len(lit) >= 15:
        _emit_length(out, len(lit) - 15)
    out += lit
    return bytes(out)


def py_decompress(block: bytes, out_size: int) -> bytes:
    """Sequential reference decoder (verification / off-toolchain path)."""
    src = bytes(block)
    out = bytearray()
    i = 0
    n = len(src)
    while i < n:
        token = src[i]
        i += 1
        ll = token >> 4
        if ll == 15:
            while True:
                b = src[i]
                i += 1
                ll += b
                if b != 255:
                    break
        out += src[i:i + ll]
        i += ll
        if i >= n:
            break                          # last sequence has no match part
        off = int.from_bytes(src[i:i + 2], "little")
        i += 2
        ml = token & 15
        if ml == 15:
            while True:
                b = src[i]
                i += 1
                ml += b
                if b != 255:
                    break
        ml += _MINMATCH
        if off <= 0 or off > len(out):
            raise ValueError("lz4 block: invalid match offset")
        for _ in range(ml):               # byte-at-a-time: overlap-correct
            out.append(out[-off])
    if len(out) != out_size:
        raise ValueError(f"lz4 block: decoded {len(out)} bytes, "
                         f"want {out_size}")
    return bytes(out)


# ---------------------------------------------------------------------------
# Strongest-available entry points
# ---------------------------------------------------------------------------

def compress(data) -> bytes:
    """LZ4 block compress via the native library when built, else python."""
    raw = bytes(data) if isinstance(data, (bytes, bytearray, memoryview)) \
        else np.ascontiguousarray(data).tobytes()
    nat = _native()
    if nat is not None:
        try:
            return nat.lz4_compress(raw)
        except (ValueError, AssertionError):  # pragma: no cover - overflow
            pass
    return py_compress(raw)


def decompress(block: bytes, out_size: int) -> bytes:
    nat = _native()
    if nat is not None:
        try:
            return nat.lz4_decompress(block, out_size).tobytes()
        except (ValueError, AssertionError):
            pass                          # malformed for native: try python
    return py_decompress(block, out_size)


# ---------------------------------------------------------------------------
# Token view (the device-decodable form)
# ---------------------------------------------------------------------------

def tokenize(block: bytes) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                    np.ndarray]:
    """Parse an LZ4 block into (literals uint8[L], lit_lens int32[T],
    match_lens int32[T], offsets int32[T]): one entry per sequence, the
    final literal-only sequence carrying match_len = offset = 0. The
    concatenated literal runs ARE `literals`, so
    Σ lit_lens + Σ match_lens = decoded size and the block is fully
    reconstructable from the four arrays (cascade.lz4_decode_device's
    input contract)."""
    src = bytes(block)
    n = len(src)
    lits = bytearray()
    lit_lens, match_lens, offsets = [], [], []
    i = 0
    while i < n:
        token = src[i]
        i += 1
        ll = token >> 4
        if ll == 15:
            while True:
                b = src[i]
                i += 1
                ll += b
                if b != 255:
                    break
        lits += src[i:i + ll]
        i += ll
        if i >= n:
            lit_lens.append(ll)
            match_lens.append(0)
            offsets.append(0)
            break
        off = int.from_bytes(src[i:i + 2], "little")
        i += 2
        ml = token & 15
        if ml == 15:
            while True:
                b = src[i]
                i += 1
                ml += b
                if b != 255:
                    break
        lit_lens.append(ll)
        match_lens.append(ml + _MINMATCH)
        offsets.append(off)
    return (np.frombuffer(bytes(lits), dtype=np.uint8),
            np.asarray(lit_lens, dtype=np.int32),
            np.asarray(match_lens, dtype=np.int32),
            np.asarray(offsets, dtype=np.int32))
