"""Time min/max aggregators (reference: extensions-contrib/time-min-max —
TimestampMinAggregatorFactory / TimestampMaxAggregatorFactory: the
earliest/latest event __time per group, usable in any aggregation, not
just timeBoundary).

TPU-first: segments stage row time as an int32 offset from the segment
interval start, so the device reduction is a narrow segment-min/max; the
host widens to absolute int64 epoch millis (identity-aware) for
cross-segment merges — the exact narrow-sentinel discipline of the core
MinMaxKernel.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from druid_tpu.engine.kernels import (AggKernel, INT64_MAX, INT64_MIN,
                                      _seg_max, _seg_min, register_kernel)
from druid_tpu.query.aggregators import AggregatorSpec, register_aggregator

INT32_MAX = 2**31 - 1
INT32_MIN = -(2**31)


@dataclass(frozen=True)
class TimeMinAggregator(AggregatorSpec):
    name: str

    def required_columns(self):
        return set()          # __time is always staged

    def combining(self):
        return TimeMinAggregator(self.name)

    def to_json(self):
        return {"type": "timeMin", "name": self.name,
                "fieldName": "__time"}


@dataclass(frozen=True)
class TimeMaxAggregator(AggregatorSpec):
    name: str

    def required_columns(self):
        return set()

    def combining(self):
        return TimeMaxAggregator(self.name)

    def to_json(self):
        return {"type": "timeMax", "name": self.name,
                "fieldName": "__time"}


class TimeMinMaxKernel(AggKernel):
    def __init__(self, spec, segment, is_max: bool):
        super().__init__(spec)
        self.is_max = is_max
        self.reduce_kind = "max" if is_max else "min"

    def signature(self):
        return f"time{'max' if self.is_max else 'min'}()"

    @property
    def identity(self):
        return INT64_MIN if self.is_max else INT64_MAX

    def update(self, cols, mask, keys, num, aux):
        import jax.numpy as jnp
        t = cols["__time_offset"]                       # int32 relative
        ident = jnp.int32(INT32_MIN if self.is_max else INT32_MAX)
        tm = jnp.where(mask, t, ident)
        return _seg_max(tm, keys, num) if self.is_max \
            else _seg_min(tm, keys, num)

    def host_post(self, state, segment):
        st = np.asarray(state).astype(np.int64)
        narrow_ident = INT32_MIN if self.is_max else INT32_MAX
        abs_t = st + segment.interval.start
        return np.where(np.asarray(state) == narrow_ident,
                        self.identity, abs_t)

    def device_post(self, state, time0):
        import jax.numpy as jnp
        narrow_ident = INT32_MIN if self.is_max else INT32_MAX
        t64 = state.astype(jnp.int64) + time0
        return jnp.where(state == jnp.int32(narrow_ident),
                         jnp.int64(self.identity), t64)

    def device_combine(self, a, b):
        import jax.numpy as jnp
        return jnp.maximum(a, b) if self.is_max else jnp.minimum(a, b)

    def host_from_device(self, state):
        return np.asarray(state)

    def combine(self, a, b):
        return np.maximum(a, b) if self.is_max else np.minimum(a, b)

    def empty_state(self, n):
        return np.full(n, self.identity, dtype=np.int64)


register_aggregator("timeMin", lambda j: TimeMinAggregator(j["name"]))
register_aggregator("timeMax", lambda j: TimeMaxAggregator(j["name"]))
register_kernel(TimeMinAggregator,
                lambda spec, seg: TimeMinMaxKernel(spec, seg, False))
register_kernel(TimeMaxAggregator,
                lambda spec, seg: TimeMinMaxKernel(spec, seg, True))
