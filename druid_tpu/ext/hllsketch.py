"""datasketches HLL sketch module (wire-format parity).

Reference analog: extensions-core/datasketches/src/main/java/org/apache/
druid/query/aggregation/datasketches/hll/ — HllSketchBuildAggregatorFactory
("HLLSketchBuild"), HllSketchMergeAggregatorFactory ("HLLSketchMerge"),
HllSketchToEstimatePostAggregator. The capability (mergeable approximate
distinct-count state with configurable precision) is served by the same
device HLL register kernel as hyperUnique (engine/hll.py — scatter-max over
2^lgK registers); these types provide the datasketches JSON surface so
reference queries run unmodified.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from druid_tpu.query.aggregators import (HyperUniqueAggregator,
                                         register_aggregator)
from druid_tpu.query.postaggs import (PostAggregator, postagg_from_json,
                                      register_postagg)


@dataclass(frozen=True)
class HLLSketchBuildAggregator(HyperUniqueAggregator):
    """Build a sketch from a raw column ("HLLSketchBuild"); lgK maps onto
    the register count exactly like hyperUnique's log2m."""

    def to_json(self):
        return {"type": "HLLSketchBuild", "name": self.name,
                "fieldName": self.field, "lgK": self.log2m,
                "round": self.round}


@dataclass(frozen=True)
class HLLSketchMergeAggregator(HyperUniqueAggregator):
    """Merge pre-built sketch columns ("HLLSketchMerge") — our HLL metric
    columns store register arrays, so merge and build share the kernel."""

    def to_json(self):
        return {"type": "HLLSketchMerge", "name": self.name,
                "fieldName": self.field, "lgK": self.log2m,
                "round": self.round}


@dataclass(frozen=True)
class HLLSketchToEstimatePostAgg(PostAggregator):
    name: str
    field: PostAggregator = None
    round: bool = False

    def compute(self, row):
        v = self.field.compute(row)
        if isinstance(v, np.ndarray):
            out = np.asarray([float(x) if x is not None else 0.0
                              for x in v])
            return np.round(out) if self.round else out
        if v is None:
            return None
        return round(float(v)) if self.round else float(v)

    def to_json(self):
        return {"type": "HLLSketchToEstimate", "name": self.name,
                "field": self.field.to_json(), "round": self.round}


def _mk(cls):
    def from_json(j):
        return cls(j["name"], j["fieldName"], log2m=int(j.get("lgK", 12)),
                   round=bool(j.get("round", False)))
    return from_json


register_aggregator("HLLSketchBuild", _mk(HLLSketchBuildAggregator))
register_aggregator("HLLSketchMerge", _mk(HLLSketchMergeAggregator))
register_postagg(
    "HLLSketchToEstimate",
    lambda j: HLLSketchToEstimatePostAgg(
        j["name"], postagg_from_json(j["field"]),
        bool(j.get("round", False))))
