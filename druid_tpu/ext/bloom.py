"""Bloom filter (reference: extensions-core/druid-bloom-filter —
BloomDimFilter for membership-tested filtering and BloomFilterAggregator
for building filters from query results).

TPU-first: the FILTER side is pure host work — membership is tested once
per dictionary value (O(cardinality)), producing an id mask like every
other string filter. The AGGREGATOR builds per-group bit arrays on device:
k hash positions per dictionary value precomputed host-side, bits set via
scatter-add + clamp (merge = elementwise OR ≡ max over ICI).
"""
from __future__ import annotations

import base64
import hashlib
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from druid_tpu.data.segment import Segment
from druid_tpu.engine.kernels import AggKernel, _seg_max, register_kernel
from druid_tpu.query.aggregators import AggregatorSpec, register_aggregator
from druid_tpu.query.filters import DimFilter, register_filter

NUM_HASHES = 7


def _bit_positions(value: str, m_bits: int, k: int = NUM_HASHES) -> np.ndarray:
    """k bit positions via double hashing of md5 halves (Kirsch-Mitzenmacher)."""
    d = hashlib.md5(value.encode()).digest()
    h1 = int.from_bytes(d[:8], "big")
    h2 = int.from_bytes(d[8:], "big") | 1
    return np.asarray([(h1 + i * h2) % m_bits for i in range(k)],
                      dtype=np.int64)


class BloomFilterValue:
    """Serializable bloom filter (bit array + membership test)."""

    __slots__ = ("bits",)

    def __init__(self, bits: np.ndarray):
        self.bits = np.asarray(bits, dtype=np.uint8)

    @property
    def m_bits(self) -> int:
        return len(self.bits)

    def test(self, value: Optional[str]) -> bool:
        v = "" if value is None else str(value)
        return bool(self.bits[_bit_positions(v, self.m_bits)].all())

    def union(self, other: "BloomFilterValue") -> "BloomFilterValue":
        return BloomFilterValue(np.maximum(self.bits, other.bits))

    def serialize(self) -> str:
        return base64.b64encode(np.packbits(self.bits).tobytes()).decode()

    @staticmethod
    def deserialize(b64: str, m_bits: int) -> "BloomFilterValue":
        raw = np.frombuffer(base64.b64decode(b64), dtype=np.uint8)
        return BloomFilterValue(np.unpackbits(raw)[:m_bits])

    def __repr__(self):
        return f"BloomFilterValue(m={self.m_bits}, set={int(self.bits.sum())})"


def optimal_m_bits(max_entries: int, fpp: float = 0.01) -> int:
    m = -max_entries * np.log(fpp) / (np.log(2) ** 2)
    return max(64, int(np.ceil(m)))


@dataclass(frozen=True)
class BloomDimFilter(DimFilter):
    """Rows whose dim value is (probably) in the provided filter."""
    dimension: str
    bloom_b64: str
    m_bits: int

    def required_columns(self):
        return {self.dimension}

    def value_predicate(self):
        blm = BloomFilterValue.deserialize(self.bloom_b64, self.m_bits)
        return blm.test

    def optimize(self):
        return self

    def to_json(self):
        return {"type": "bloom", "dimension": self.dimension,
                "bloomKFilter": self.bloom_b64, "mBits": self.m_bits}


@dataclass(frozen=True)
class BloomFilterAggregator(AggregatorSpec):
    name: str
    field: str
    max_num_entries: int = 1500

    @property
    def m_bits(self) -> int:
        return optimal_m_bits(self.max_num_entries)

    def combining(self):
        return BloomFilterAggregator(self.name, self.name,
                                     self.max_num_entries)

    def to_json(self):
        return {"type": "bloom", "name": self.name, "fieldName": self.field,
                "maxNumEntries": self.max_num_entries}


class BloomKernel(AggKernel):
    reduce_kind = "max"   # bit OR

    def __init__(self, spec: BloomFilterAggregator, segment: Segment):
        super().__init__(spec)
        self.field = spec.field
        self.m = spec.m_bits
        col = segment.dims.get(self.field)
        if col is None:
            raise ValueError(
                f"bloom aggregator needs a string dimension, got {self.field!r}")
        self._pos_tbl = segment.aux_cached(
            ("bloom_pos", self.field, self.m),
            lambda: np.stack([_bit_positions(v, self.m)
                              for v in col.dictionary.values]).astype(np.int32))

    def signature(self):
        return f"bloom({self.field},{self.m})"

    def aux_arrays(self):
        return [self._pos_tbl]

    def update(self, cols, mask, keys, num, aux):
        import jax.numpy as jnp
        ids = cols[self.field]
        pos = next(aux)[ids]                       # [n, k] bit positions
        flat = (keys[:, None] * self.m + pos).reshape(-1)
        ones = jnp.broadcast_to(mask[:, None],
                                pos.shape).reshape(-1).astype(jnp.int32)
        bits = _seg_max(ones, flat, num * self.m)
        return bits.reshape(num, self.m)

    def host_post(self, state, segment):
        return np.asarray(state, dtype=np.uint8)

    def host_from_device(self, state):
        return np.asarray(state, dtype=np.uint8)

    def device_combine(self, a, b):
        import jax.numpy as jnp
        return jnp.maximum(a, b)

    def combine(self, a, b):
        return np.maximum(a, b)

    def empty_state(self, n):
        return np.zeros((n, self.m), dtype=np.uint8)

    def finalize_array(self, state):
        arr = np.asarray(state, dtype=np.uint8)
        out = np.empty(arr.shape[0], dtype=object)
        for i in range(arr.shape[0]):
            out[i] = BloomFilterValue(arr[i])
        return out


register_aggregator(
    "bloom",
    lambda j: BloomFilterAggregator(j["name"], j["fieldName"],
                                    j.get("maxNumEntries", 1500)))
register_kernel(BloomFilterAggregator, BloomKernel)
register_filter(
    "bloom",
    lambda j: BloomDimFilter(j["dimension"], j["bloomKFilter"], j["mBits"]))
