"""Extensions: sketches, histogram, stats, bloom filter.

Reference analog: extensions-core/ (datasketches, histogram, stats,
druid-bloom-filter) loaded via the DruidModule SPI
(server/.../initialization/Initialization.java:132). Here each module
registers its aggregators / post-aggregators / filters / kernels into the
core registries at import; importing druid_tpu.ext activates everything.
"""
from druid_tpu.ext.stats import (StandardDeviationPostAgg, VarianceAggregator)
from druid_tpu.ext.sketches import (QuantilePostAgg, QuantilesPostAgg,
                                    QuantilesSketchAggregator,
                                    ThetaSketchAggregator,
                                    ThetaSketchEstimatePostAgg,
                                    ThetaSketchSetOpPostAgg, ThetaSketchValue)
from druid_tpu.ext.histogram import (ApproximateHistogramAggregator,
                                     HistogramQuantilePostAgg, HistogramValue)
from druid_tpu.ext.bloom import (BloomFilterAggregator, BloomFilterValue,
                                 BloomDimFilter)
from druid_tpu.ext.hllsketch import (HLLSketchBuildAggregator,
                                     HLLSketchMergeAggregator,
                                     HLLSketchToEstimatePostAgg)
from druid_tpu.ext.protobuf_parser import ProtobufInputRowParser
from druid_tpu.ext.time_minmax import (TimeMaxAggregator, TimeMinAggregator)
from druid_tpu.ext.namespace_lookup import load_uri_namespace
from druid_tpu.ext.distinctcount import DistinctCountAggregator

__all__ = [
    "HLLSketchBuildAggregator", "HLLSketchMergeAggregator",
    "HLLSketchToEstimatePostAgg",
    "VarianceAggregator", "StandardDeviationPostAgg",
    "ThetaSketchAggregator", "ThetaSketchValue", "ThetaSketchEstimatePostAgg",
    "ThetaSketchSetOpPostAgg", "QuantilesSketchAggregator", "QuantilePostAgg",
    "QuantilesPostAgg", "ApproximateHistogramAggregator", "HistogramValue",
    "HistogramQuantilePostAgg", "BloomFilterAggregator", "BloomFilterValue",
    "ProtobufInputRowParser", "TimeMinAggregator", "TimeMaxAggregator",
    "load_uri_namespace", "DistinctCountAggregator",
    "BloomDimFilter",
]
