"""URI-backed namespace lookups (reference: extensions-core/
lookups-cached-global — UriExtractionNamespace + its namespaceParseSpec
family: the cluster-managed lookup whose key→value map is periodically
re-read from a file/object-store URI instead of being inlined in the
spec).

Registers the "uri" extractionNamespace loader with the cluster lookup
sync. Spec shape mirrors the reference:

    {"type": "uri", "uri": "file:///path/map.json",
     "namespaceParseSpec": {"format": "json"},          # {"k": "v", ...}
     "pollPeriod": 60}

Formats: "json" (flat object), "customJson" (list of objects with
keyFieldName/valueFieldName), "csv"/"tsv" (keyColumn/valueColumn over a
header row). Gzip transparently by .gz suffix.
"""
from __future__ import annotations

import csv
import gzip
import io
import json
import os
from typing import Dict
from urllib.parse import urlparse

from druid_tpu.cluster.lookups import register_namespace_loader


def _read_uri(uri: str) -> bytes:
    parsed = urlparse(uri)
    if parsed.scheme in ("", "file"):
        path = parsed.path if parsed.scheme else uri
        with open(path, "rb") as f:
            data = f.read()
        if path.endswith(".gz"):
            data = gzip.decompress(data)
        return data
    raise ValueError(f"unsupported namespace URI scheme {parsed.scheme!r} "
                     "(deep-storage schemes plug in via their own loader)")


def load_uri_namespace(ns: dict) -> Dict[str, str]:
    data = _read_uri(ns["uri"]).decode("utf-8")
    ps = ns.get("namespaceParseSpec", {"format": "json"})
    fmt = ps.get("format", "json")
    if fmt == "json":
        obj = json.loads(data)
        if not isinstance(obj, dict):
            raise ValueError("json namespace must be a flat object")
        return {str(k): str(v) for k, v in obj.items()}
    if fmt == "customJson":
        kf, vf = ps["keyFieldName"], ps["valueFieldName"]
        recs = json.loads(data)
        if not isinstance(recs, list):
            # a flat object would string-iterate into a silent {} — that
            # must be a load FAILURE (keeping the last good mapping)
            raise ValueError("customJson namespace must be a list of objects")
        out: Dict[str, str] = {}
        for rec in recs:
            if isinstance(rec, dict) and kf in rec and vf in rec:
                out[str(rec[kf])] = str(rec[vf])
        return out
    if fmt in ("csv", "tsv"):
        delim = "," if fmt == "csv" else "\t"
        rows = list(csv.reader(io.StringIO(data), delimiter=delim))
        if not rows:
            return {}
        header = rows[0]
        kc = ps.get("keyColumn", header[0])
        vc = ps.get("valueColumn", header[-1])
        ki, vi = header.index(kc), header.index(vc)
        return {r[ki]: r[vi] for r in rows[1:] if len(r) > max(ki, vi)}
    raise ValueError(f"unknown namespaceParseSpec format {fmt!r}")


register_namespace_loader("uri", load_uri_namespace)
