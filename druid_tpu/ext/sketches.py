"""Datasketches: theta (set cardinality + set ops) and quantiles.

Reference analog: extensions-core/datasketches — theta SketchAggregatorFactory
(+ SketchEstimatePostAggregator, SketchSetPostAggregator union/intersect/not)
and DoublesSketchAggregatorFactory (+ quantile/quantiles post-aggs).

TPU-first reformulations (branch-free segmented ops, mergeable states):

  Theta → one-permutation min-hash: B buckets; per bucket keep the MIN of
  normalized 64-bit hashes landing there (segment_min; merge = elementwise
  min = exact union of sketches). Estimate: each bucket min of k uniforms
  has mean 1/(k+1) → n̂ = B²/Σmin − B. Intersections use the min-hash
  Jaccard estimate (fraction of agreeing buckets) × union estimate — the
  classic MinHash identity, where the reference uses theta intersection.

  Quantiles → DDSketch-style log-bucketed counts: bucket(x) =
  round(log|x|/log γ) clamped, sign-mirrored, zero bucket; γ = 1.05 gives
  ~2.4% relative error. State = int32 count vector (segment_sum; merge =
  add = psum). Quantile lookup walks the CDF host-side. The reference's
  KLL/DoublesSketch gives rank error; this gives relative value error —
  both mergeable sketches with tunable accuracy.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data.segment import Segment, ValueType
from druid_tpu.engine import hll as hll_mod
from druid_tpu.engine.kernels import (AggKernel, _seg_min, _seg_sum,
                                      register_kernel)
from druid_tpu.query.aggregators import AggregatorSpec, register_aggregator
from druid_tpu.query.postaggs import (FieldAccessPostAgg, PostAggregator,
                                      postagg_from_json, register_postagg)

# ---------------------------------------------------------------------------
# Theta
# ---------------------------------------------------------------------------

DEFAULT_THETA_SIZE = 4096


class ThetaSketchValue:
    """Mergeable min-hash sketch value (bucket minima in [0, 1]; 1.0 =
    empty bucket)."""

    __slots__ = ("mins",)

    def __init__(self, mins: np.ndarray):
        self.mins = np.asarray(mins, dtype=np.float64)

    @property
    def estimate(self) -> float:
        """Censored-exponential MLE. Per bucket, the min of k uniforms is
        ≈ Exp(k) truncated at 1 (empty buckets read 1.0), so with λ = n/B,
        E[m] = (1 − e^−λ)/λ. Invert Σm/B = (1 − e^−λ)/λ for λ by bisection;
        n̂ = λB. Handles low occupancy (many empty buckets) where the naive
        B²/Σm − B estimator biases low, and converges to B²/Σm for n ≫ B."""
        b = float(len(self.mins))
        r = float(self.mins.sum()) / b
        if r >= 1.0 - 1e-12:
            return 0.0
        lo, hi = 1e-9, 1e9
        for _ in range(100):
            mid = (lo + hi) / 2 if hi < 1e8 else min(lo * 2, hi)
            val = (1.0 - math.exp(-mid)) / mid
            if val > r:
                lo = mid
            else:
                hi = mid
            if hi - lo < 1e-9 * max(1.0, lo):
                break
        return lo * b

    def union(self, other: "ThetaSketchValue") -> "ThetaSketchValue":
        return ThetaSketchValue(np.minimum(self.mins, other.mins))

    def jaccard(self, other: "ThetaSketchValue") -> float:
        both = (self.mins < 1.0) | (other.mins < 1.0)
        if not both.any():
            return 0.0
        agree = (self.mins == other.mins) & both
        return float(agree.sum()) / float(both.sum())

    def intersect_estimate(self, other: "ThetaSketchValue") -> float:
        u = self.union(other)
        return self.jaccard(other) * u.estimate

    def __repr__(self):
        return f"ThetaSketchValue(estimate≈{self.estimate:.1f})"

    def __float__(self):
        return self.estimate


@dataclass(frozen=True)
class ThetaSketchAggregator(AggregatorSpec):
    name: str
    field: str
    size: int = DEFAULT_THETA_SIZE
    should_finalize: bool = True   # True → estimate; False → sketch value

    def combining(self):
        return ThetaSketchAggregator(self.name, self.name, self.size,
                                     self.should_finalize)

    def to_json(self):
        return {"type": "thetaSketch", "name": self.name,
                "fieldName": self.field, "size": self.size,
                "shouldFinalize": self.should_finalize}


class ThetaKernel(AggKernel):
    reduce_kind = "min"

    def __init__(self, spec: ThetaSketchAggregator, segment: Segment):
        super().__init__(spec)
        self.field = spec.field
        self.size = spec.size
        col = segment.dims.get(self.field)
        self._numeric = col is None
        if col is not None:
            h = segment.aux_cached(("hll_hash", self.field),
                                   lambda: hll_mod.dim_hash_table(col.dictionary))
            # bucket = top bits; fraction = remaining bits normalized (0,1]
            self._bucket_tbl = (h % np.uint64(self.size)).astype(np.int32)
            frac = (h >> np.uint64(32)).astype(np.float64) / float(2 ** 32)
            self._frac_tbl = np.maximum(frac, 1e-12)

    def signature(self):
        return f"theta({self.field},{self.size},{self._numeric})"

    def aux_arrays(self):
        if self._numeric:
            return []
        return [self._bucket_tbl, self._frac_tbl]

    def update(self, cols, mask, keys, num, aux):
        import jax.numpy as jnp
        if self._numeric:
            v = cols[self.field] if self.field != "__time" \
                else cols["__time_offset"]
            # floats hash by BIT PATTERN (distinct fractions stay distinct);
            # integers widen then reinterpret
            h = hll_mod.splitmix64_device(
                v.astype(jnp.float64).view(jnp.uint64)
                if jnp.issubdtype(v.dtype, jnp.floating)
                else v.astype(jnp.int64).astype(jnp.uint64))
            bucket = (h % jnp.uint64(self.size)).astype(jnp.int32)
            frac = jnp.maximum(
                (h >> jnp.uint64(32)).astype(jnp.float64) / float(2 ** 32),
                1e-12)
        else:
            ids = cols[self.field]
            bucket_tbl = next(aux)
            frac_tbl = next(aux)
            bucket = bucket_tbl[ids]
            frac = frac_tbl[ids]
        flat = keys * self.size + bucket
        vals = jnp.where(mask, frac, 1.0)
        mins = _seg_min(vals, flat, num * self.size)
        # empty buckets carry segment_min's +inf identity → clamp to the
        # "empty" sentinel 1.0 or the estimator divides by infinity
        return jnp.minimum(mins, 1.0).reshape(num, self.size)

    def host_post(self, state, segment):
        return np.asarray(state, dtype=np.float64)

    def device_combine(self, a, b):
        import jax.numpy as jnp
        return jnp.minimum(a, b)

    def combine(self, a, b):
        return np.minimum(a, b)

    def empty_state(self, n):
        return np.ones((n, self.size), dtype=np.float64)

    def finalize_array(self, state):
        arr = np.asarray(state, dtype=np.float64)
        out = np.empty(arr.shape[0], dtype=object)
        for i in range(arr.shape[0]):
            sk = ThetaSketchValue(arr[i])
            out[i] = round(sk.estimate) if self.spec.should_finalize else sk
        return out


@dataclass(frozen=True)
class ThetaSketchEstimatePostAgg(PostAggregator):
    name: str
    field: PostAggregator = None

    def compute(self, row):
        v = self.field.compute(row)
        if isinstance(v, np.ndarray):
            return np.asarray([float(x) if x is not None else 0.0
                               for x in v])
        return float(v) if v is not None else None

    def to_json(self):
        return {"type": "thetaSketchEstimate", "name": self.name,
                "field": self.field.to_json()}


@dataclass(frozen=True)
class ThetaSketchSetOpPostAgg(PostAggregator):
    """union | intersect | not over sketch-valued fields; yields an
    ESTIMATE (the reference yields a sketch; wrap in thetaSketchEstimate
    there — here set ops finalize directly)."""
    name: str
    func: str                     # UNION | INTERSECT | NOT
    fields: Tuple[PostAggregator, ...] = ()

    def _sketches(self, row, vals):
        out = []
        for v in vals:
            if not isinstance(v, ThetaSketchValue):
                raise TypeError(
                    "thetaSketchSetOp needs sketch inputs — set "
                    "shouldFinalize=false on the theta aggregator")
            out.append(v)
        return out

    def compute(self, row):
        vals = [f.compute(row) for f in self.fields]
        if any(isinstance(v, np.ndarray) for v in vals):
            n = len(vals[0])
            return np.asarray([self._one([v[i] for v in vals])
                               for i in range(n)])
        return self._one(vals)

    def _one(self, vals):
        sks = self._sketches(None, vals)
        if self.func == "UNION":
            out = sks[0]
            for s in sks[1:]:
                out = out.union(s)
            return out.estimate
        if self.func == "INTERSECT":
            est = None
            base = sks[0]
            for s in sks[1:]:
                est = base.intersect_estimate(s) if est is None else min(
                    est, base.intersect_estimate(s))
            return est if est is not None else base.estimate
        if self.func == "NOT":
            # union the subtrahends first so overlapping Bi inside A aren't
            # double-subtracted (reference chains ((A\B1)\B2))
            base = sks[0]
            if len(sks) == 1:
                return base.estimate
            sub = sks[1]
            for s in sks[2:]:
                sub = sub.union(s)
            return max(base.estimate - base.intersect_estimate(sub), 0.0)
        raise ValueError(f"unknown set op {self.func!r}")

    def to_json(self):
        return {"type": "thetaSketchSetOp", "name": self.name,
                "func": self.func,
                "fields": [f.to_json() for f in self.fields]}


# ---------------------------------------------------------------------------
# Quantiles
# ---------------------------------------------------------------------------

# γ = 1.05 → ~2.4% relative value error; exponents ±E cover e^±25 ≈ 7e±10.
# Bucket layout (ascending): [neg mirrored | zero | pos], P buckets per sign.
GAMMA = 1.05
LOG_GAMMA = math.log(GAMMA)
E = 512
P = 2 * E + 1                     # buckets per sign (exponents −E..E)
NUM_BUCKETS = 2 * P + 1
ZERO_BUCKET = P


def _bucket_values() -> np.ndarray:
    """Representative value per bucket."""
    exps = np.exp(np.arange(-E, E + 1) * LOG_GAMMA)    # γ^idx, idx −E..E
    out = np.zeros(NUM_BUCKETS)
    out[P + 1:] = exps                                  # positive ascending
    out[:P] = -exps[::-1]                               # negative ascending
    return out


_BUCKET_VALUES = _bucket_values()


class QuantilesSketchValue:
    __slots__ = ("counts",)

    def __init__(self, counts: np.ndarray):
        self.counts = np.asarray(counts, dtype=np.int64)

    @property
    def count(self) -> int:
        return int(self.counts.sum())

    def quantile(self, q: float) -> float:
        total = self.counts.sum()
        if total == 0:
            return float("nan")
        target = q * (total - 1)
        cdf = np.cumsum(self.counts)
        i = int(np.searchsorted(cdf, target, side="right"))
        i = min(i, NUM_BUCKETS - 1)
        return float(_BUCKET_VALUES[i])

    def quantiles(self, qs: Sequence[float]) -> list:
        return [self.quantile(q) for q in qs]

    def merge(self, other: "QuantilesSketchValue") -> "QuantilesSketchValue":
        return QuantilesSketchValue(self.counts + other.counts)

    def __repr__(self):
        return f"QuantilesSketchValue(n={self.count})"


@dataclass(frozen=True)
class QuantilesSketchAggregator(AggregatorSpec):
    name: str
    field: str

    def combining(self):
        return QuantilesSketchAggregator(self.name, self.name)

    def to_json(self):
        return {"type": "quantilesDoublesSketch", "name": self.name,
                "fieldName": self.field}


class QuantilesKernel(AggKernel):
    reduce_kind = "sum"

    def __init__(self, spec: QuantilesSketchAggregator, segment: Segment):
        super().__init__(spec)
        self.field = spec.field

    def signature(self):
        return f"quantiles({self.field})"

    def update(self, cols, mask, keys, num, aux):
        import jax.numpy as jnp
        v = cols[self.field] if self.field != "__time" \
            else cols["__time_offset"]
        x = v.astype(jnp.float64)
        ax = jnp.abs(x)
        idx = jnp.clip(jnp.round(jnp.log(jnp.maximum(ax, 1e-300)) / LOG_GAMMA),
                       -E, E).astype(jnp.int32)
        pos = P + 1 + (idx + E)            # [P+1, 2P]
        neg = P - 1 - (idx + E)            # [0, P-1], ascending with value
        bucket = jnp.where(x > 0, pos, jnp.where(x < 0, neg, ZERO_BUCKET)) \
            .astype(jnp.int32)
        flat = keys * NUM_BUCKETS + bucket
        ones = mask.astype(jnp.int32)
        return _seg_sum(ones, flat, num * NUM_BUCKETS) \
            .reshape(num, NUM_BUCKETS)

    def host_post(self, state, segment):
        return np.asarray(state, dtype=np.int64)

    def device_combine(self, a, b):
        return a + b

    def combine(self, a, b):
        return a + b

    def empty_state(self, n):
        return np.zeros((n, NUM_BUCKETS), dtype=np.int64)

    def finalize_array(self, state):
        arr = np.asarray(state, dtype=np.int64)
        out = np.empty(arr.shape[0], dtype=object)
        for i in range(arr.shape[0]):
            out[i] = QuantilesSketchValue(arr[i])
        return out


@dataclass(frozen=True)
class QuantilePostAgg(PostAggregator):
    """reference: DoublesSketchToQuantilePostAggregator."""
    name: str
    field: PostAggregator = None
    fraction: float = 0.5

    def compute(self, row):
        v = self.field.compute(row)
        if isinstance(v, np.ndarray):
            return np.asarray([x.quantile(self.fraction) for x in v])
        return v.quantile(self.fraction)

    def to_json(self):
        return {"type": "quantilesDoublesSketchToQuantile", "name": self.name,
                "field": self.field.to_json(), "fraction": self.fraction}


@dataclass(frozen=True)
class QuantilesPostAgg(PostAggregator):
    """reference: DoublesSketchToQuantilesPostAggregator."""
    name: str
    field: PostAggregator = None
    fractions: Tuple[float, ...] = ()

    def compute(self, row):
        v = self.field.compute(row)
        if isinstance(v, np.ndarray):
            return np.asarray([x.quantiles(self.fractions) for x in v],
                              dtype=object)
        return v.quantiles(self.fractions)

    def to_json(self):
        return {"type": "quantilesDoublesSketchToQuantiles",
                "name": self.name, "field": self.field.to_json(),
                "fractions": list(self.fractions)}


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------

register_aggregator(
    "thetaSketch",
    lambda j: ThetaSketchAggregator(j["name"], j["fieldName"],
                                    j.get("size", DEFAULT_THETA_SIZE),
                                    j.get("shouldFinalize", True)))
register_kernel(ThetaSketchAggregator, ThetaKernel)
register_postagg(
    "thetaSketchEstimate",
    lambda j: ThetaSketchEstimatePostAgg(j["name"],
                                         postagg_from_json(j["field"])))
register_postagg(
    "thetaSketchSetOp",
    lambda j: ThetaSketchSetOpPostAgg(
        j["name"], j["func"],
        tuple(postagg_from_json(f) for f in j["fields"])))
register_aggregator(
    "quantilesDoublesSketch",
    lambda j: QuantilesSketchAggregator(j["name"], j["fieldName"]))
register_kernel(QuantilesSketchAggregator, QuantilesKernel)
register_postagg(
    "quantilesDoublesSketchToQuantile",
    lambda j: QuantilePostAgg(j["name"], postagg_from_json(j["field"]),
                              j["fraction"]))
register_postagg(
    "quantilesDoublesSketchToQuantiles",
    lambda j: QuantilesPostAgg(j["name"], postagg_from_json(j["field"]),
                               tuple(j["fractions"])))
