"""Approximate histogram (reference: extensions-core/histogram —
ApproximateHistogramAggregatorFactory + quantile/min/max/histogram
post-aggregators).

TPU-first: instead of the reference's centroid-merging per-row algorithm,
an equal-width bucket grid over [lower_limit, upper_limit) plus exact
min/max — counts via one scatter-add segment_sum, merge = add (psum).
Quantiles interpolate the bucket CDF.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence, Tuple

import numpy as np

from druid_tpu.engine.kernels import (AggKernel, _seg_max, _seg_min, _seg_sum,
                                      register_kernel)
from druid_tpu.query.aggregators import AggregatorSpec, register_aggregator
from druid_tpu.query.postaggs import (PostAggregator, postagg_from_json,
                                      register_postagg)


class HistogramValue:
    __slots__ = ("counts", "min", "max", "lower", "upper")

    def __init__(self, counts: np.ndarray, vmin: float, vmax: float,
                 lower: float, upper: float):
        self.counts = np.asarray(counts, dtype=np.int64)
        self.min = float(vmin)
        self.max = float(vmax)
        self.lower = lower
        self.upper = upper

    @property
    def count(self) -> int:
        return int(self.counts.sum())

    def quantile(self, q: float) -> float:
        total = self.counts.sum()
        if total == 0:
            return float("nan")
        b = len(self.counts)
        width = (self.upper - self.lower) / b
        target = q * total
        cdf = np.concatenate([[0], np.cumsum(self.counts)])
        i = int(np.searchsorted(cdf, target, side="left"))
        i = max(1, min(i, b))
        # linear interpolation within bucket i-1
        prev, cur = cdf[i - 1], cdf[i]
        frac = 0.0 if cur == prev else (target - prev) / (cur - prev)
        v = self.lower + (i - 1 + frac) * width
        return float(np.clip(v, self.min, self.max))

    def to_json(self) -> dict:
        b = len(self.counts)
        width = (self.upper - self.lower) / b
        breaks = [self.lower + i * width for i in range(b + 1)]
        return {"breaks": breaks, "counts": self.counts.tolist(),
                "min": self.min, "max": self.max}

    def __repr__(self):
        return f"HistogramValue(n={self.count}, [{self.min}, {self.max}])"


@dataclass(frozen=True)
class ApproximateHistogramAggregator(AggregatorSpec):
    name: str
    field: str
    num_buckets: int = 64
    lower_limit: float = 0.0
    upper_limit: float = 1.0

    def combining(self):
        return ApproximateHistogramAggregator(
            self.name, self.name, self.num_buckets, self.lower_limit,
            self.upper_limit)

    def to_json(self):
        return {"type": "approxHistogram", "name": self.name,
                "fieldName": self.field, "numBuckets": self.num_buckets,
                "lowerLimit": self.lower_limit, "upperLimit": self.upper_limit}


class HistogramKernel(AggKernel):
    reduce_kind = "fold"

    def __init__(self, spec: ApproximateHistogramAggregator, segment):
        super().__init__(spec)
        self.field = spec.field
        self.b = spec.num_buckets
        self.lower = spec.lower_limit
        self.upper = spec.upper_limit

    def signature(self):
        return f"hist({self.field},{self.b},{self.lower},{self.upper})"

    def update(self, cols, mask, keys, num, aux):
        import jax.numpy as jnp
        v = cols[self.field] if self.field != "__time" \
            else cols["__time_offset"]
        x = v.astype(jnp.float64)
        width = (self.upper - self.lower) / self.b
        bucket = jnp.clip(((x - self.lower) / width).astype(jnp.int32),
                          0, self.b - 1)
        flat = keys * self.b + bucket
        counts = _seg_sum(mask.astype(jnp.int32), flat, num * self.b) \
            .reshape(num, self.b)
        big = jnp.float64(np.finfo(np.float64).max)
        mn = _seg_min(jnp.where(mask, x, big), keys, num)
        mx = _seg_max(jnp.where(mask, x, -big), keys, num)
        return {"counts": counts, "min": mn, "max": mx}

    def host_post(self, state, segment):
        return {k: np.asarray(v) for k, v in state.items()}

    def host_from_device(self, state):
        return {k: np.asarray(v) for k, v in state.items()}

    def device_combine(self, a, b):
        import jax.numpy as jnp
        return {"counts": a["counts"] + b["counts"],
                "min": jnp.minimum(a["min"], b["min"]),
                "max": jnp.maximum(a["max"], b["max"])}

    def combine(self, a, b):
        return {"counts": a["counts"] + b["counts"],
                "min": np.minimum(a["min"], b["min"]),
                "max": np.maximum(a["max"], b["max"])}

    def empty_state(self, n):
        big = np.finfo(np.float64).max
        return {"counts": np.zeros((n, self.b), dtype=np.int64),
                "min": np.full(n, big), "max": np.full(n, -big)}

    def finalize_array(self, state):
        counts = np.asarray(state["counts"], dtype=np.int64)
        out = np.empty(counts.shape[0], dtype=object)
        for i in range(counts.shape[0]):
            out[i] = HistogramValue(counts[i], state["min"][i],
                                    state["max"][i], self.lower, self.upper)
        return out


@dataclass(frozen=True)
class HistogramQuantilePostAgg(PostAggregator):
    """reference: histogram ext QuantilePostAggregator."""
    name: str
    field: PostAggregator = None
    probability: float = 0.5

    def compute(self, row):
        v = self.field.compute(row)
        if isinstance(v, np.ndarray):
            return np.asarray([x.quantile(self.probability) for x in v])
        return v.quantile(self.probability)

    def to_json(self):
        return {"type": "quantile", "name": self.name,
                "field": self.field.to_json(),
                "probability": self.probability}


register_aggregator(
    "approxHistogram",
    lambda j: ApproximateHistogramAggregator(
        j["name"], j["fieldName"], j.get("numBuckets", 64),
        j.get("lowerLimit", 0.0), j.get("upperLimit", 1.0)))
register_kernel(ApproximateHistogramAggregator, HistogramKernel)
register_postagg(
    "quantile",
    lambda j: HistogramQuantilePostAgg(j["name"],
                                       postagg_from_json(j["field"]),
                                       j["probability"]))
