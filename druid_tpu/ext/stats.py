"""Variance / standard deviation (reference: extensions-core/stats —
VarianceAggregatorFactory with Welford-style combinable state, and the
variance/stddev SQL bindings).

TPU-first: the state is {count, sum, sumsq} in float64 — three segment_sums
in one pass; combine is elementwise add (psum over ICI). Finalization
computes population or sample variance host-side.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from druid_tpu.engine.kernels import AggKernel, _seg_sum, register_kernel
from druid_tpu.query.aggregators import AggregatorSpec, register_aggregator
from druid_tpu.query.postaggs import (PostAggregator, register_postagg)


@dataclass(frozen=True)
class VarianceAggregator(AggregatorSpec):
    name: str
    field: str
    estimator: str = "population"   # population | sample

    def required_columns(self):
        return {self.field}

    def combining(self):
        return VarianceAggregator(self.name, self.name, self.estimator)

    def to_json(self):
        return {"type": "variance", "name": self.name,
                "fieldName": self.field, "estimator": self.estimator}


class VarianceKernel(AggKernel):
    reduce_kind = "sum"

    def __init__(self, spec: VarianceAggregator, segment):
        super().__init__(spec)
        self.field = spec.field
        self.sample = spec.estimator == "sample"
        if self.field in segment.dims:
            raise ValueError(
                f"variance over string dimension {self.field!r} — it would "
                f"aggregate dictionary ids, not values")

    def signature(self):
        return f"variance({self.field},{self.sample})"

    def update(self, cols, mask, keys, num, aux):
        import jax.numpy as jnp
        v = cols[self.field] if self.field != "__time" \
            else cols["__time_offset"]
        v = v.astype(jnp.float64)
        vm = jnp.where(mask, v, 0.0)
        return {"n": _seg_sum(mask.astype(jnp.int64), keys, num),
                "sum": _seg_sum(vm, keys, num),
                "sumsq": _seg_sum(vm * vm, keys, num)}

    def host_post(self, state, segment):
        return {k: np.asarray(v) for k, v in state.items()}

    def host_from_device(self, state):
        return {k: np.asarray(v) for k, v in state.items()}

    def device_combine(self, a, b):
        return {k: a[k] + b[k] for k in a}

    def combine(self, a, b):
        return {k: a[k] + b[k] for k in a}

    def empty_state(self, n):
        return {"n": np.zeros(n, dtype=np.int64),
                "sum": np.zeros(n, dtype=np.float64),
                "sumsq": np.zeros(n, dtype=np.float64)}

    def finalize_array(self, state):
        n = np.asarray(state["n"], dtype=np.float64)
        s = np.asarray(state["sum"])
        ss = np.asarray(state["sumsq"])
        denom = np.maximum(n - (1.0 if self.sample else 0.0), 1.0)
        var = np.maximum(ss - s * s / np.maximum(n, 1.0), 0.0) / denom
        return np.where(n > 0, var, 0.0)


@dataclass(frozen=True)
class StandardDeviationPostAgg(PostAggregator):
    """reference: stats ext StandardDeviationPostAggregator."""
    name: str
    field: str

    def compute(self, row):
        v = row.get(self.field)
        return np.sqrt(np.maximum(np.asarray(v, dtype=np.float64), 0.0)) \
            if v is not None else None

    def to_json(self):
        return {"type": "stddev", "name": self.name, "fieldName": self.field}


register_aggregator(
    "variance",
    lambda j: VarianceAggregator(j["name"], j["fieldName"],
                                 j.get("estimator", "population")))
register_kernel(VarianceAggregator, VarianceKernel)
register_postagg("stddev",
                 lambda j: StandardDeviationPostAgg(j["name"], j["fieldName"]))
