"""Protobuf input rows: decode serialized messages via a compiled
FileDescriptorSet.

Reference analog: extensions-core/protobuf-extensions
(ProtobufInputRowParser.java — loads a `.desc` descriptor file produced by
`protoc --descriptor_set_out`, resolves the message type, and converts each
binary record to a flat row through the proto3 JSON mapping).

Registers parser type "protobuf" with the core InputRowParser registry, so
task specs may say `"parser": {"type": "protobuf", "descriptor": ...,
"protoMessageType": ..., "parseSpec": {...}}` exactly like the reference.
"""
from __future__ import annotations

import base64
from typing import Optional

from druid_tpu.ingest.input import (DimensionsSpec, InputRowParser,
                                    TimestampSpec)


class ProtobufInputRowParser(InputRowParser):
    """Binary protobuf records → dict rows (proto3 JSON field mapping,
    original field names). Nested messages flatten into dotted keys so
    `a.b` addresses them as dimension/metric columns."""

    def __init__(self, descriptor_bytes: bytes, message_type: str,
                 timestamp_spec: TimestampSpec,
                 dimensions_spec: Optional[DimensionsSpec] = None,
                 flatten_delimiter: str = "."):
        super().__init__(timestamp_spec,
                         dimensions_spec or DimensionsSpec())
        from google.protobuf import descriptor_pb2, descriptor_pool
        from google.protobuf import message_factory
        self.descriptor_bytes = descriptor_bytes
        self.message_type = message_type
        self.flatten_delimiter = flatten_delimiter
        fds = descriptor_pb2.FileDescriptorSet.FromString(descriptor_bytes)
        pool = descriptor_pool.DescriptorPool()
        for f in fds.file:
            pool.Add(f)
        desc = pool.FindMessageTypeByName(message_type)
        self._msg_cls = message_factory.GetMessageClass(desc)

    def _decode(self, record) -> Optional[dict]:
        from google.protobuf import json_format
        if isinstance(record, dict):
            return record        # already decoded (e.g. replayed rows)
        msg = self._msg_cls()
        msg.ParseFromString(record)
        # default-valued proto3 fields must still become row values (a
        # clicks=0 metric is data, not absence) — kwarg renamed in
        # protobuf 5
        try:
            d = json_format.MessageToDict(
                msg, preserving_proto_field_name=True,
                always_print_fields_with_no_presence=True)
        except TypeError:
            d = json_format.MessageToDict(
                msg, preserving_proto_field_name=True,
                including_default_value_fields=True)
        return self._flatten(d)

    def _flatten(self, d: dict, prefix: str = "") -> dict:
        out = {}
        for k, v in d.items():
            key = f"{prefix}{k}"
            if isinstance(v, dict):
                out.update(self._flatten(
                    v, prefix=f"{key}{self.flatten_delimiter}"))
            else:
                out[key] = v
        return out

    @staticmethod
    def from_json_spec(j: dict) -> "ProtobufInputRowParser":
        ps = j.get("parseSpec", {})
        desc = j.get("descriptor", "")
        if isinstance(desc, str):
            desc_bytes = base64.b64decode(desc)
        else:
            desc_bytes = bytes(desc)
        return ProtobufInputRowParser(
            desc_bytes, j["protoMessageType"],
            TimestampSpec.from_json(ps.get("timestampSpec")),
            DimensionsSpec.from_json(ps.get("dimensionsSpec")))

    def to_json(self) -> dict:
        return {"type": "protobuf",
                "descriptor":
                    base64.b64encode(self.descriptor_bytes).decode("ascii"),
                "protoMessageType": self.message_type,
                "parseSpec": {
                    "timestampSpec": self.timestamp_spec.to_json(),
                    "dimensionsSpec": self.dimensions_spec.to_json()}}


InputRowParser.register_type("protobuf",
                             ProtobufInputRowParser.from_json_spec)
