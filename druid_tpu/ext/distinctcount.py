"""Exact distinct count over a string dimension (reference:
extensions-contrib/distinctcount — DistinctCountAggregatorFactory counts
distinct dictionary ids per group with a per-segment bitmap).

Same accuracy contract as the contrib extension: EXACT within one
segment; across segments the per-segment distinct counts ADD, so the
global number is exact only when the data is partitioned such that each
dimension value lives in one segment (hashed/single-dim shard specs on
that dimension — the contrib docs state the identical requirement).
Use thetaSketch/HLL for segment-agnostic distincts.

TPU-first: the per-row bitmap OR of the reference becomes one scatter
into a [groups, cardinality] presence matrix and a row-sum — two fused
device ops instead of a per-row hot loop.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from druid_tpu.engine.kernels import AggKernel, register_kernel
from druid_tpu.query.aggregators import AggregatorSpec, register_aggregator

#: presence-matrix cell budget — groups × cardinality beyond this would
#: dominate HBM for a niche aggregator (the contrib ext has the analogous
#: practical bound through its per-group bitmap memory)
MAX_CELLS = 1 << 24


@dataclass(frozen=True)
class DistinctCountAggregator(AggregatorSpec):
    name: str
    field: str

    def combining(self):
        from druid_tpu.query.aggregators import LongSumAggregator
        # merge side adds per-segment counts (the contrib contract)
        return LongSumAggregator(self.name, self.name)

    def to_json(self):
        return {"type": "distinctCount", "name": self.name,
                "fieldName": self.field}


class DistinctCountKernel(AggKernel):
    reduce_kind = "sum"

    def __init__(self, spec: DistinctCountAggregator, segment):
        super().__init__(spec)
        self.field = spec.field
        if spec.field in getattr(segment, "metrics", {}):
            raise ValueError(
                f"distinctCount requires a string dimension; "
                f"[{spec.field}] is a metric (use thetaSketch)")
        dim = getattr(segment, "dims", {}).get(spec.field)
        # absent from THIS segment (schema evolution): contribute zero,
        # like every other kernel — never fail the whole query
        self.cardinality = dim.dictionary.cardinality if dim is not None \
            else 0

    def signature(self):
        return f"distinct({self.field},{self.cardinality})"

    def update(self, cols, mask, keys, num, aux):
        import jax.numpy as jnp
        if self.field not in cols or self.cardinality == 0:
            return jnp.zeros((num,), dtype=jnp.int64)
        if num * self.cardinality > MAX_CELLS:
            raise ValueError(
                f"distinctCount presence matrix {num}x{self.cardinality} "
                f"exceeds the cell budget ({MAX_CELLS}); use thetaSketch "
                "or hyperUnique at this scale")
        ids = cols[self.field].astype(jnp.int32)
        presence = jnp.zeros((num, self.cardinality), dtype=bool)
        safe_keys = jnp.where(mask, keys, 0)
        safe_ids = jnp.where(mask, ids, 0)
        presence = presence.at[safe_keys, safe_ids].set(True)
        # row (group) 0 / id 0 may carry masked-out garbage: recompute its
        # cell exactly
        real00 = jnp.any(mask & (keys == 0) & (ids == 0))
        presence = presence.at[0, 0].set(real00)
        return presence.sum(axis=1).astype(jnp.int64)

    def combine(self, a, b):
        return a + b              # per-segment counts add (contrib contract)

    def empty_state(self, n):
        return np.zeros(n, dtype=np.int64)


register_aggregator(
    "distinctCount",
    lambda j: DistinctCountAggregator(j["name"], j["fieldName"]))
register_kernel(DistinctCountAggregator, DistinctCountKernel)
