"""Broker: cluster-wide scatter-gather query execution.

Reference analog: CachingClusteredClient (client/CachingClusteredClient.java:93
— the broker's QuerySegmentWalker): timeline lookup (computeSegmentsToQuery
:290) → shard pruning → cache probe (pruneSegmentsWithCachedResults :397) →
group by server → per-server fan-out (addSequencesFromServer :536) → merge;
plus RetryQueryRunner (query/RetryQueryRunner.java:71 — re-fans-out segments
reported missing) and ResultLevelCachingQueryRunner.

TPU-first difference from the reference: data nodes return *partial
aggregation states* (AggregatePartials — dense per-key arrays), and the
broker merge is the same vectorized sparse-merge used across segments
(druid_tpu/engine/merge.py) — HLL and sketch merges stay exact because
states, not finalized estimates, cross the node boundary. Within one host
the same states would merge on-device via collectives (druid_tpu/parallel/).
"""
from __future__ import annotations

import logging
import random
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import wait as wait_futures
from dataclasses import replace
from typing import Dict, List, Optional, Sequence, Set, Tuple

from druid_tpu.cluster.cache import (CacheConfig, LruCache, query_cache_key,
                                     result_level_key)
from druid_tpu.cluster.metadata import SegmentDescriptor
from druid_tpu.cluster.resilience import (BrokerResilience, PartialResult,
                                          ResiliencePolicy, allows_partial,
                                          hedging_enabled)
from druid_tpu.cluster.view import InventoryView, _is_aggregate
from druid_tpu.engine import engines
from druid_tpu.engine.engines import AggregatePartials
from druid_tpu.obs import trace as qtrace
from druid_tpu.query import filters as F
from druid_tpu.query.model import (DataSourceMetadataQuery, GroupByQuery,
                                   Query, ScanQuery, SearchQuery,
                                   SegmentMetadataQuery, SelectQuery,
                                   TimeBoundaryQuery, TimeseriesQuery,
                                   TopNQuery, query_from_json)
from druid_tpu.server.querymanager import (Deadline, QueryCapacityError,
                                           QueryInterruptedError,
                                           QueryManager, QueryTimeoutError,
                                           QueryToken, context_timeout_ms)
from druid_tpu.utils.intervals import Interval, condense


class MissingSegmentsError(RuntimeError):
    def __init__(self, segment_ids: Sequence[str]):
        super().__init__(f"segments not served after retries: "
                         f"{sorted(segment_ids)}")
        self.segment_ids = sorted(segment_ids)


def _slice_scan_batches(batches, skip: int, remaining):
    """Apply a global offset/limit across scan batches. Returns
    (sliced batches, skip left, remaining left) so streaming callers can
    carry the counters across waves; `remaining` None means unlimited."""
    out = []
    for b in batches:
        ev = b["events"]
        if skip:
            if skip >= len(ev):
                skip -= len(ev)
                continue
            ev = ev[skip:]
            skip = 0
        if remaining is not None:
            ev = ev[:remaining]
            remaining -= len(ev)
        if ev:
            out.append({**b, "events": ev})
        if remaining is not None and remaining <= 0:
            break
    return out, skip, remaining


def _filter_domain(flt) -> Dict[str, List[Optional[str]]]:
    """Extract dim → candidate-values constraints for shard pruning
    (the broker's hash-pruning of secondary partitions)."""
    if getattr(flt, "extraction_fn", None) is not None:
        # the raw dictionary values behind fn(v) == target are unknowable
        # here — no pruning constraint may be derived
        return {}
    if isinstance(flt, F.SelectorFilter):
        return {flt.dimension: [flt.value]}
    if isinstance(flt, F.InFilter):
        return {flt.dimension: list(flt.values)}
    if isinstance(flt, F.AndFilter):
        out: Dict[str, List[Optional[str]]] = {}
        for f in flt.fields:
            for d, vals in _filter_domain(f).items():
                if d in out:
                    out[d] = [v for v in out[d] if v in set(vals)]
                else:
                    out[d] = vals
        return out
    return {}


class _ScatterCall:
    """One in-flight scatter call (primary or hedge) within a wave."""

    __slots__ = ("server", "sids", "is_hedge", "started", "cancel_sent")

    def __init__(self, server: str, sids: Sequence[str], is_hedge: bool):
        self.server = server
        self.sids = list(sids)
        self.is_hedge = is_hedge
        self.started = time.monotonic()
        self.cancel_sent = False


class Broker:
    """QuerySegmentWalker over the cluster. Also provides the QueryExecutor
    surface (run / run_json / datasources / segments_of) so SqlExecutor can
    plan and execute cluster-wide."""

    #: ceiling on one wave's park between completions: a query with no
    #: timeout context must still re-check liveness each quantum instead
    #: of parking a request thread on the pool indefinitely
    MAX_WAVE_POLL_S = 60.0

    def __init__(self, view: InventoryView,
                 cache: Optional[LruCache] = None,
                 cache_config: Optional[CacheConfig] = None,
                 max_retries: int = 2, seed: int = 0,
                 max_threads: int = 8,
                 query_manager: Optional[QueryManager] = None,
                 selector_strategy=None,
                 resilience_policy: Optional[ResiliencePolicy] = None):
        """selector_strategy: view.ServerSelectorStrategy for replica
        choice (default: random within the replica set).
        resilience_policy: every data-plane fault-tolerance knob —
        circuit breakers, hedged requests, partial-result degradation
        (cluster/resilience.py; default policy when None)."""
        self.view = view
        self.cache = cache
        self.cache_config = cache_config or CacheConfig()
        self.max_retries = max_retries
        self.rng = random.Random(seed)
        self.max_threads = max_threads
        self.query_manager = query_manager or QueryManager()
        self.selector_strategy = selector_strategy
        self.resilience = BrokerResilience(resilience_policy, seed=seed)
        # ONE broker-owned scatter pool (created on first scatter, shut
        # down in stop()) — retry rounds and hedges stop paying per-round
        # pool spin-up, and leakguard's shutdown-surface rules cover it
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_lock = threading.Lock()

    def _ensure_pool(self) -> ThreadPoolExecutor:
        """The broker-owned scatter pool. Unlike the old per-round pool
        (one per retry round per query), this one is shared by EVERY
        concurrent query's waves — so it is sized at a multiple of
        max_threads plus hedge headroom: one query's hung stragglers
        must not starve another query's primaries or hedges of workers
        (workers spawn lazily, so the headroom costs nothing while
        idle; deadline-abandoned calls are remote-cancelled, which
        frees their workers on nodes that honor the cancel)."""
        with self._pool_lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=4 * (self.max_threads
                                     + self.resilience.policy
                                     .hedge_max_per_query),
                    thread_name_prefix="broker-scatter")
            return self._pool

    def stop(self) -> None:
        """Release the scatter pool (idempotent). The broker stays
        usable — the next scatter recreates the pool."""
        with self._pool_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

    # ---- QueryExecutor-compatible surface ------------------------------
    @property
    def datasources(self) -> List[str]:
        return self.view.datasources()

    def segments_of(self, datasource: str):
        """Segment objects for schema discovery. In-process convenience —
        a multi-host deployment answers this with segmentMetadata queries
        (DruidSchema does exactly that)."""
        out, seen = [], set()
        for node in self.view.nodes():
            for s in node.segments():
                if s.id.datasource == datasource and str(s.id) not in seen:
                    seen.add(str(s.id))
                    out.append(s)
        return out

    def run_json(self, j: dict):
        return self.run(query_from_json(j))

    # ---- the signature path (§3.1) -------------------------------------
    def run(self, query: Query):
        # the trace root for a query entering at the broker (trace id =
        # queryId); when the lifecycle already opened the root this is a
        # plain child span, and {"trace": false} makes it (and every span
        # below it) a no-op
        with qtrace.root_span("broker/query", query,
                              service="druid/broker"):
            return self._run(query)

    def _run(self, query: Query):
        from druid_tpu.engine.executor import apply_interval_chunking
        query = apply_interval_chunking(query)
        if query.inner_query is not None:
            # subquery: inner runs cluster-wide; the outer re-groups the
            # materialized inner rows broker-locally (as the reference's
            # broker does for nested groupBys)
            from druid_tpu.engine.executor import (QueryExecutor,
                                                   subquery_segment)
            inner_rows = self.run(query.inner_query)
            seg = subquery_segment(query.inner_query, inner_rows)
            return QueryExecutor().run(query, segments=[seg])
        with qtrace.span("broker/plan"):
            segments = self._segments_to_query(query)
        if not segments:
            return []
        if _is_aggregate(query):
            if query.context_map.get("bySegment"):
                # per-segment unmerged results: the row path concatenates
                # what every node's BySegmentQueryRunner produced
                return self._run_rows(query, segments)
            return self._run_aggregate(query, segments)
        return self._run_rows(query, segments)

    def run_streaming(self, query: Query):
        """Streaming scan through the scatter path: segments are queried
        in per-segment waves (time-ordered when the scan is ordered) and
        each wave's batches yield before the next segment is touched, so
        a satisfied limit stops the scatter early and rows reach the
        caller incrementally. Non-scan queries are merge-shaped: they
        fall back to the materialized run()."""
        if not isinstance(query, ScanQuery) or query.inner_query is not None:
            yield from self.run(query)
            return
        from druid_tpu.engine.executor import apply_interval_chunking
        query = apply_interval_chunking(query)
        segments = self._segments_to_query(query)
        if not segments:
            return
        if query.order != "none":
            segments.sort(key=lambda d: d.interval.start,
                          reverse=(query.order == "descending"))
        skip = query.offset
        remaining = query.limit
        for d in segments:
            if remaining is not None and remaining <= 0:
                return
            # per-node offsets don't compose globally: request raw rows
            # (bounded by what could still be needed) and slice here
            want = None if remaining is None else remaining + skip
            sub = replace(query, limit=want, offset=0)
            wave, missing = self._scatter(sub, [d], rows_mode=True)
            if missing:
                # a streamed scan cannot attach a missing-segments report
                # to rows already on the wire — surface the typed error
                # instead of silently skipping the segment
                raise MissingSegmentsError(list(missing))
            batches = self._merge_rows(
                replace(sub, limit=None, offset=0), wave, [d])
            sliced, skip, remaining = _slice_scan_batches(
                batches, skip, remaining)
            yield from sliced

    def _segments_to_query(self, query: Query) -> List[SegmentDescriptor]:
        """Timeline lookup + shard pruning (computeSegmentsToQuery)."""
        datasources = query.union_datasources or (query.datasource,)
        out, seen = [], set()
        for ds in datasources:
            tl = self.view.timeline(ds)
            if tl is not None:
                self._collect(tl, query, out, seen)
        return out

    def _collect(self, tl, query: Query, out, seen) -> None:
        domain = _filter_domain(query.filter) if query.filter is not None else {}
        for iv in condense(query.intervals):
            for holder in tl.lookup(iv):
                for chunk in holder.partitions:
                    rs = chunk.obj
                    d = rs.descriptor
                    if d.id in seen:
                        continue
                    seen.add(d.id)
                    if domain and d.shard_spec is not None \
                            and not d.shard_spec.possible_in_domain(domain):
                        continue
                    out.append(d)

    # ---- aggregate path: partials + broker-side finish -----------------
    def _run_aggregate(self, query: Query,
                       segments: List[SegmentDescriptor]):
        use_rcache = (self.cache is not None
                      and self.cache_config.cacheable(query)
                      and self.cache_config.use_result_cache
                      and self._all_replicatable(segments))
        rkey = None
        if use_rcache:
            rkey = result_level_key(
                query, [f"{d.id}" for d in segments])
            hit = self.cache.get("result", rkey)
            if hit is not None:
                return hit

        # bound intervals by the queried segments' extents so every node
        # (and the broker finish) shares one finite bucket index space;
        # granularity "all" has a single bucket stamped with the query
        # interval start — leave it unbounded so the timestamp matches
        # single-process execution
        q2 = query
        if not query.granularity.is_all:
            lo = min(d.interval.start for d in segments)
            hi = max(d.interval.end for d in segments)
            bounded = []
            for iv in condense(query.intervals):
                x = iv.intersect(Interval(lo, hi))
                if x is not None and x.width > 0:
                    bounded.append(x)
            if not bounded:
                return []
            q2 = replace(query, intervals=tuple(bounded))

        parts, missing = self._scatter(q2, segments, rows_mode=False)
        if missing and not parts:
            # every replica exhausted with partials allowed: typed empty
            # partial — the caller learns exactly what is missing
            return PartialResult([], missing)
        ap = AggregatePartials.concat(parts)
        with qtrace.span("broker/merge", partials=len(ap.partials)):
            if isinstance(query, TimeseriesQuery):
                rows = engines.finish_timeseries(q2, ap)
            elif isinstance(query, TopNQuery):
                rows = engines.finish_topn(q2, ap)
            elif isinstance(query, GroupByQuery):
                rows = engines.finish_groupby(q2, ap)
            else:  # pragma: no cover
                raise TypeError(type(query).__name__)
        if missing:
            # a partial must never populate the result cache: the next
            # identical query would be served the hole forever
            return PartialResult(rows, missing)
        if use_rcache and self.cache_config.populate_result_cache:
            self.cache.put("result", rkey, rows)
        return rows

    def etag(self, query: Query):
        """Result-set identity for this query over the CURRENT timeline:
        hashed query key + exact segment-id set (the reference's
        X-Druid-ETag from CachingClusteredClient's etag computation).
        None when any replica is realtime (rows mutate under a stable
        segment id) or for nested/non-aggregate queries."""
        from druid_tpu.engine.executor import apply_interval_chunking
        import hashlib
        if query.inner_query is not None or not _is_aggregate(query):
            return None
        try:
            q = apply_interval_chunking(query)
            segments = self._segments_to_query(q)
            if not segments or not self._all_replicatable(segments):
                return None
            raw = result_level_key(q, [f"{d.id}" for d in segments])
            # result-SHAPING context must distinguish etags (bySegment
            # returns unmerged per-segment rows under the same cache key);
            # volatile per-request keys must not
            ctx = {k: v for k, v in query.context_map.items()
                   if k not in ("queryId", "timeout", "priority", "lane")}
            if ctx:
                import json as _json
                raw += "|ctx:" + _json.dumps(ctx, sort_keys=True)
            return hashlib.sha1(raw.encode()).hexdigest()
        except Exception:
            # etag is an optimization, never a failure
            logging.getLogger(__name__).debug(
                "etag computation failed; serving without one",
                exc_info=True)
            return None

    def _all_replicatable(self, segments: List[SegmentDescriptor]) -> bool:
        """True when no queried segment is served by a realtime server.
        A sink's rows grow between queries under a STABLE segment id, so a
        result cached while any replica is realtime would be served stale
        forever (the reference's CachingClusteredClient caches only
        segment-replicatable servers)."""
        for d in segments:
            rs = self.view.replica_set(d.id)
            if rs is None:
                continue
            for server in rs.servers:
                node = self.view.node(server)
                if node is not None and \
                        not getattr(node, "segment_replicatable", True):
                    return False
        return True

    # ---- row path -------------------------------------------------------
    def _run_rows(self, query: Query, segments: List[SegmentDescriptor]):
        q2 = query
        if isinstance(query, ScanQuery) and (query.limit is not None
                                             or query.offset):
            # nodes can't apply the global offset; ask for offset+limit rows
            # (unlimited when limit is None) and apply offset at the broker
            lim = None if query.limit is None else query.limit + query.offset
            q2 = replace(query, limit=lim, offset=0)
        results, missing = self._scatter(q2, segments, rows_mode=True)
        rows = self._merge_rows(query, results, segments)
        return PartialResult(rows, missing) if missing else rows

    # ---- scatter + retry + hedging (RetryQueryRunner) ------------------
    def _scatter(self, query: Query, segments: List[SegmentDescriptor],
                 rows_mode: bool):
        """Returns (gathered results, missing segment ids). The missing
        set is non-empty ONLY when the query allows partial results —
        otherwise exhausted replicas raise exactly as before."""
        with qtrace.span("broker/scatter",
                         segments=len(segments)) as scatter_span:
            return self._scatter_rounds(query, segments, rows_mode,
                                        scatter_span)

    def _scatter_rounds(self, query: Query,
                        segments: List[SegmentDescriptor],
                        rows_mode: bool, scatter_span):
        # cancel token + deadline ride the whole scatter (QueryContexts
        # timeout; DELETE /druid/v2/{id} trips the token)
        qid = query.context_map.get("queryId")
        token = self.query_manager.token(qid)
        deadline = Deadline.for_query(query)
        total_ms = context_timeout_ms(query)
        res = self.resilience
        allow_partial = allows_partial(query)
        circuits = res.circuits if res.policy.circuit_enabled else None
        pending: Dict[str, SegmentDescriptor] = {d.id: d for d in segments}
        tried: Dict[str, Set[str]] = {d.id: set() for d in segments}
        seg_errors: Dict[str, BaseException] = {}
        # 429 sheds per segment: ONE other replica gets a chance to absorb
        # a shed segment set before the capacity error surfaces
        capacity_attempts: Dict[str, int] = {}
        gathered = []
        hedges_left = res.policy.hedge_max_per_query \
            if hedging_enabled(res.policy, query) else 0
        for _ in range(self.max_retries + 1):
            if not pending:
                break
            if token is not None:
                token.check()
            if deadline.expired() \
                    or res.deadline_nearly_spent(deadline, total_ms):
                if allow_partial:
                    # another round cannot finish inside the remaining
                    # budget: degrade to a typed partial now instead of
                    # burning the rest of the deadline into a 504
                    break
                deadline.check()
            # each round carries only the REMAINING time budget, so retries
            # cannot stretch the query past its context timeout
            remaining = deadline.remaining_ms()
            q_round = query if remaining is None else replace(
                query, context=tuple(sorted(
                    {**query.context_map, "timeout": remaining}.items())))
            # group by chosen server (selection skips open circuits while
            # any closed replica remains; all-open falls back as a probe)
            by_server: Dict[str, List[str]] = {}
            for sid in pending:
                rs = self.view.replica_set(sid)
                server = rs.pick(self.rng, exclude=tried[sid],
                                 strategy=self.selector_strategy,
                                 view=self.view,
                                 circuits=circuits) if rs else None
                if server is not None:
                    by_server.setdefault(server, []).append(sid)
            if not by_server:
                break
            hedges_left = self._run_wave(
                q_round, by_server, rows_mode, scatter_span, token, qid,
                deadline, allow_partial, hedges_left, pending, tried,
                seg_errors, capacity_attempts, gathered)
            saturated = [sid for sid, shed in capacity_attempts.items()
                         if sid in pending and shed > 1]
            if saturated:
                # the one-other-replica retry was shed too: the tier is
                # saturated — surface the 429 now (one saturated node is
                # not a saturated tier, but two are — don't hammer the
                # rest), or degrade when the query allows partials
                if allow_partial:
                    break
                raise seg_errors[saturated[-1]]
        if pending:
            if allow_partial:
                # typed degradation: the caller wraps the merged rows in
                # a PartialResult carrying this exact missing set
                res.stats.note_partial(len(pending))
                return gathered, set(pending)
            # a spent deadline is a timeout, not a replica problem — the
            # wave abandons in-flight stragglers when it expires, so the
            # strict contract surfaces the 504 here
            deadline.check()
            errs = [seg_errors[sid] for sid in pending if sid in seg_errors]
            if errs:
                raise errs[-1]
            raise MissingSegmentsError(list(pending))
        return gathered, set()

    def _run_wave(self, q_round: Query, by_server: Dict[str, List[str]],
                  rows_mode: bool, scatter_span, token, qid,
                  deadline: Deadline, allow_partial: bool,
                  hedges_left: int, pending, tried, seg_errors,
                  capacity_attempts, gathered) -> int:
        """One scatter wave with tail hedging. Primaries fan out on the
        broker pool; when a straggler exceeds its EWMA-derived hedge
        delay, its still-pending segment set is re-issued on one other
        replica. Responses CLAIM the segments they served under a
        first-complete-wins rule: a response whose served set intersects
        segments already claimed by its rival is dropped WHOLE (a fused
        AggregatePartials cannot be split per segment), which makes
        double-merging a hedge-won segment structurally impossible. A
        call that can no longer win anything is remote-cancelled through
        the same node.cancel hook the query token registers. Returns the
        remaining per-query hedge budget."""
        res = self.resilience
        pool = self._ensure_pool()
        claimed: Set[str] = set()
        futures: Dict[object, _ScatterCall] = {}
        for server, sids in by_server.items():
            call = _ScatterCall(server, sids, is_hedge=False)
            futures[pool.submit(self._call_node, call, q_round, rows_mode,
                                scatter_span, token, qid)] = call
            for sid in sids:
                tried[sid].add(server)
        live = set(futures)
        hedged: Set[str] = set()

        def collect(f):
            # collect() only ever receives futures from wait_futures'
            # `done` set — result() returns immediately, it cannot park
            call, result, served, exc = f.result()  # druidlint: disable=unbounded-blocking-call
            if exc is None:
                if result is not None and not (served & claimed):
                    claimed.update(served)
                    gathered.append(result)
                    for sid in served:
                        pending.pop(sid, None)
                    if call.is_hedge and served:
                        res.stats.note_hedge_won()
                    return
                # a response racing a rival that already claimed any of
                # its segments is dropped WHOLE — never double-merged.
                # The server answered fine though: segments of its that
                # nobody claimed must stay retryable THERE, or a
                # partially-overlapping hedge win would strand them with
                # no untried replica (found by the dead+hedge chaos
                # scenario)
                for sid in served - claimed:
                    if sid in pending:
                        tried[sid].discard(call.server)
                return
            unclaimed = [sid for sid in call.sids if sid not in claimed]
            if isinstance(exc, QueryInterruptedError):
                if token is not None and token.cancelled():
                    raise exc     # genuine DELETE: abort the scatter
                if not unclaimed:
                    # our own loser-cancel answered with the interrupt —
                    # nothing to record, its segments are all claimed
                    return
                if not allow_partial:
                    # segments still live means this was NOT our loser
                    # cancel: someone interrupted the query node-side —
                    # surface the true error (the old abort contract),
                    # don't let it degrade into MissingSegmentsError
                    raise exc
                res.circuits.on_failure(call.server)
                for sid in unclaimed:
                    seg_errors[sid] = exc
                return
            if isinstance(exc, QueryTimeoutError) and not allow_partial:
                raise exc         # deadline: abort (the strict contract)
            # everything below is a per-server failure the circuit
            # breaker counts: sheds, timeouts (partial mode), dead and
            # sick nodes alike
            res.circuits.on_failure(call.server)
            if isinstance(exc, QueryCapacityError):
                # the node shed the query (and the client's one
                # Retry-After retry was shed again): ONE other replica
                # of the segment set gets a lane-aware try — the query
                # context (lane, priority) is resent unchanged
                self.view.note_capacity_shed(call.server)
                for sid in unclaimed:
                    seg_errors[sid] = exc
                    capacity_attempts[sid] = \
                        capacity_attempts.get(sid, 0) + 1
                return
            if isinstance(exc, ConnectionError):
                # unreachable server: plain failover; exhausting
                # replicas is a MissingSegmentsError
                return
            # a sick node (HTTP 500, crash mid-query) is retried on
            # another replica exactly like a missing segment (reference:
            # query/RetryQueryRunner.java:71-80); the error is kept PER
            # SEGMENT so exhausting replicas reports the real failure
            # for a segment that actually failed — not a recovered one's
            # stale error
            for sid in unclaimed:
                seg_errors[sid] = exc

        while live:
            if all(set(futures[f].sids) <= claimed for f in live):
                # nothing left to win: end the wave now instead of
                # paying the slowest straggler's full response time
                break
            timeout = self._wave_timeout(live, futures, hedged, deadline,
                                         hedges_left)
            done, live = wait_futures(live, timeout=timeout,
                                      return_when=FIRST_COMPLETED)
            for f in done:
                collect(f)
            self._cancel_stale_calls(live, futures, claimed, qid)
            if deadline.expired():
                # the bounded wait IS the no-hang guarantee: abandon
                # what is still in flight (best-effort cancel) and let
                # the terminal classification decide 504 vs partial
                self._abandon_calls(live, futures, qid)
                break
            if hedges_left > 0:
                hedges_left = self._issue_hedges(
                    live, futures, hedged, claimed, pending, tried,
                    hedges_left, pool, q_round, rows_mode, scatter_span,
                    token, qid)
        return hedges_left

    def _call_node(self, call: "_ScatterCall", q_round: Query,
                   rows_mode: bool, scatter_span, token, qid):
        """One server call on the broker pool. Never raises: the outcome
        (call, result, served, error) is classified by the wave collector,
        which knows whether the call's segments were already claimed by a
        hedge rival."""
        server, sids = call.server, call.sids
        node = self.view.node(server)
        if node is None:
            return call, None, set(), None
        # propagate a cancel to remote nodes with work in flight
        # (deduped per server across retry rounds)
        if token is not None and qid and hasattr(node, "cancel"):
            token.add_remote_cancel(lambda n=node: n.cancel(qid),
                                    key=server)
        # the pool worker re-activates the scatter span, times this
        # node's response as broker/node, and stamps the span as the
        # remote parent into the context it POSTs — the data node
        # re-roots its spans under it (qtrace wire propagation)
        with qtrace.attach(scatter_span), \
                qtrace.span("broker/node", server=server,
                            segments=len(sids),
                            hedge=call.is_hedge) as nsp:
            q_call = q_round if nsp is None \
                else qtrace.with_traceparent(q_round, nsp)
            self.view.connection_started(server)
            t0 = time.monotonic()
            try:
                if rows_mode:
                    result, served = node.run_rows(q_call, sids)
                else:
                    result, served = node.run_partials(q_call, sids)
                # feed the response time back into the view's per-server
                # EWMA — the NEXT wave's hedge delay derives from it
                self.view.note_latency(
                    server, (time.monotonic() - t0) * 1e3,
                    alpha=self.resilience.policy.latency_alpha)
                self.resilience.circuits.on_success(server)
                return call, result, set(served), None
            except BaseException as e:
                return call, None, set(), e
            finally:
                self.view.connection_finished(server)

    def _wave_timeout(self, live, futures, hedged: Set[str],
                      deadline: Deadline,
                      hedges_left: int) -> float:
        """How long the wave may block before something needs attention:
        the earliest un-hedged straggler's hedge deadline, bounded by the
        query deadline — and ALWAYS by MAX_WAVE_POLL_S: with no timeout
        context and hedging exhausted the wave re-arms each quantum
        instead of parking on the pool until the last straggler answers
        (every in-flight call carries its own connect/read timeout, so
        the re-armed wait is a liveness re-check, not a busy loop)."""
        cands = [self.MAX_WAVE_POLL_S]
        rem = deadline.remaining()
        if rem is not None:
            cands.append(rem)
        if hedges_left > 0:
            now = time.monotonic()
            for f in live:
                c = futures[f]
                if not c.is_hedge and c.server not in hedged:
                    delay = self.resilience.hedge_delay_s(self.view,
                                                          c.server)
                    cands.append(c.started + delay - now)
        return max(0.005, min(cands))

    def _issue_hedges(self, live, futures, hedged: Set[str],
                      claimed: Set[str], pending, tried, hedges_left: int,
                      pool, q_round: Query, rows_mode: bool, scatter_span,
                      token, qid) -> int:
        """Speculatively re-issue each overdue straggler's still-pending
        segment set on one other replica (one hedge per straggler call,
        bounded by the per-query hedge budget)."""
        res = self.resilience
        circuits = res.circuits if res.policy.circuit_enabled else None
        now = time.monotonic()
        for f in list(live):
            call = futures[f]
            if call.is_hedge or call.server in hedged:
                continue
            if now - call.started < res.hedge_delay_s(self.view,
                                                      call.server):
                continue
            hedged.add(call.server)
            h_by_server: Dict[str, List[str]] = {}
            for sid in call.sids:
                if sid not in pending or sid in claimed:
                    continue
                rs = self.view.replica_set(sid)
                srv = rs.pick(self.rng, exclude=tried[sid],
                              strategy=self.selector_strategy,
                              view=self.view,
                              circuits=circuits) if rs else None
                if srv is not None:
                    h_by_server.setdefault(srv, []).append(sid)
            for srv, sids in h_by_server.items():
                if hedges_left <= 0:
                    break
                hedges_left -= 1
                res.stats.note_hedge_issued()
                hcall = _ScatterCall(srv, sids, is_hedge=True)
                fut = pool.submit(self._call_node, hcall, q_round,
                                  rows_mode, scatter_span, token, qid)
                futures[fut] = hcall
                live.add(fut)
                for sid in sids:
                    tried[sid].add(srv)
        return hedges_left

    def _cancel_stale_calls(self, live, futures, claimed: Set[str],
                            qid) -> None:
        """Remote-cancel in-flight calls that can no longer win anything
        (every segment they carry is claimed by a rival response) —
        unless the same server still runs another live call for this
        query, because the cancel is qid-wide on the node. Fired through
        the same node.cancel hook the query token's remote-cancel
        propagation uses (QueryToken._fire: off-thread, best-effort)."""
        if not qid:
            return
        for f in list(live):
            call = futures[f]
            if call.cancel_sent or not call.sids \
                    or not set(call.sids) <= claimed:
                continue
            if any(g is not f and futures[g].server == call.server
                   and not set(futures[g].sids) <= claimed
                   for g in live):
                continue
            call.cancel_sent = True
            node = self.view.node(call.server)
            if node is None or not hasattr(node, "cancel"):
                continue
            self.resilience.stats.note_hedge_cancelled()
            QueryToken._fire([lambda n=node: n.cancel(qid)])

    def _abandon_calls(self, live, futures, qid) -> None:
        """Deadline-abandoned calls: best-effort cancel per server so a
        hung node stops holding broker pool workers past the query."""
        if not qid:
            return
        seen: Set[str] = set()
        for f in live:
            call = futures[f]
            if call.cancel_sent or call.server in seen:
                continue
            seen.add(call.server)
            call.cancel_sent = True
            node = self.view.node(call.server)
            if node is None or not hasattr(node, "cancel"):
                continue
            QueryToken._fire([lambda n=node: n.cancel(qid)])

    # ---- row merges (QueryToolChest.mergeResults analogs) --------------
    def _merge_rows(self, query: Query, results: List[List[dict]],
                    segments: List[SegmentDescriptor]):
        if _is_aggregate(query) and query.context_map.get("bySegment"):
            merged = [r for rows in results for r in rows]
            merged.sort(key=lambda r: r["result"]["segment"])
            return merged
        if isinstance(query, ScanQuery):
            batches = [b for rows in results for b in rows]
            if query.order != "none":
                iv_of = {d.id: d.interval.start for d in segments}
                batches.sort(key=lambda b: iv_of.get(b["segmentId"], 0),
                             reverse=(query.order == "descending"))
            if query.limit is not None or query.offset:
                batches, _, _ = _slice_scan_batches(
                    batches, query.offset, query.limit)
            return batches
        if isinstance(query, TimeBoundaryQuery):
            mn, mx = None, None
            for rows in results:
                for r in rows:
                    res = r["result"]
                    if "minTime" in res:
                        mn = res["minTime"] if mn is None \
                            else min(mn, res["minTime"])
                    if "maxTime" in res:
                        mx = res["maxTime"] if mx is None \
                            else max(mx, res["maxTime"])
            if mn is None and mx is None:
                return []
            result = {}
            if query.bound in (None, "minTime"):
                result["minTime"] = mn
            if query.bound in (None, "maxTime"):
                result["maxTime"] = mx
            ts = mn if query.bound != "maxTime" else mx
            return [{"timestamp": ts, "result": result}]
        if isinstance(query, SearchQuery):
            hits: Dict[Tuple[str, str], int] = {}
            ts = None
            for rows in results:
                for r in rows:
                    ts = r["timestamp"] if ts is None \
                        else min(ts, r["timestamp"])
                    for e in r["result"]:
                        key = (e["dimension"], e["value"])
                        hits[key] = hits.get(key, 0) + e["count"]
            if not hits:
                return []
            entries = [{"dimension": d, "value": v, "count": c}
                       for (d, v), c in hits.items()]
            if query.sort == "strlen":
                entries.sort(key=lambda e: (len(e["value"]), e["value"],
                                            e["dimension"]))
            else:
                entries.sort(key=lambda e: (e["value"], e["dimension"]))
            return [{"timestamp": ts, "result": entries[: query.limit]}]
        if isinstance(query, (SegmentMetadataQuery, SelectQuery)):
            merged: List[dict] = []
            for rows in results:
                merged += rows
            return merged
        if isinstance(query, DataSourceMetadataQuery):
            best = None
            for rows in results:
                for r in rows:
                    t = r["result"].get("maxIngestedEventTime")
                    if best is None or (t is not None and t > best):
                        best = t
            return [] if best is None else \
                [{"timestamp": best,
                  "result": {"maxIngestedEventTime": best}}]
        raise TypeError(f"cannot merge {type(query).__name__}")
