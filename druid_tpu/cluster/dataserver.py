"""The network data plane: data-node HTTP server + the broker's per-server
HTTP query client.

Reference analogs:
  server/QueryResource.java:153 — the historical/realtime query endpoint the
    broker hits per server (here split into /partials for aggregate queries,
    which return binary partial-state bundles, and /rows for row queries)
  server/QueryResource.java:126 — DELETE /druid/v2/{id} cancel
  client/DirectDruidClient.java:98 — the broker-side per-server client
    (async Netty there; blocking-in-threadpool here — the broker already
    fans out across servers on a ThreadPoolExecutor)
  java-util/.../http/client/NettyHttpClient.java — transport

Wire formats: queries travel as Druid-native JSON; aggregate partials come
back as the tensor-bundle binary (cluster/wire.py); row results as JSON.
Server-side the node enforces the query's context timeout and honors
cancellation between per-segment computations (when segments fuse into one
sharded device program, that program is uninterruptible once launched — the
check runs before and after it).
"""
from __future__ import annotations

import json
import random
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Sequence, Set, Tuple

from druid_tpu.cluster import wire
from druid_tpu.cluster.view import DataNode
from druid_tpu.obs import trace as qtrace
from druid_tpu.obs.prometheus import CONTENT_TYPE as PROM_CONTENT_TYPE
from druid_tpu.obs.prometheus import MetricRegistry, compose_sink
from druid_tpu.query.model import Query, query_from_json
from druid_tpu.server.http import _json_value
from druid_tpu.server.querymanager import (DEFAULT_TIMEOUT_MS, Deadline,
                                           QueryCapacityError,
                                           QueryInterruptedError,
                                           QueryManager, QueryTimeoutError,
                                           cancel_path_id)
from druid_tpu.server.scheduler import (DataNodeScheduler,
                                        SchedulerConfig,
                                        SchedulerMetricsMonitor)
from druid_tpu.utils.emitter import (QueryCountStatsMonitor,
                                     ServiceEmitter)


class RemoteQueryError(RuntimeError):
    """A data node answered with a query error (HTTP 4xx/5xx). Distinct from
    ConnectionError on purpose: the broker retries unreachable servers on
    other replicas, but a deterministic query error must propagate with the
    node's actual message, not degrade into MissingSegmentsError."""

    def __init__(self, server: str, code: int, detail: str):
        super().__init__(f"server [{server}] HTTP {code}: {detail}")
        self.server = server
        self.code = code
        self.detail = detail


class DataNodeServer:
    """Serves one DataNode's query surface over HTTP.

    Observability/pool plumbing: `emitter` (a ServiceEmitter) wires the
    device-pool and batched-execution monitors — segment/devicePool/hitRate,
    segment/devicePool/evictedBytes, query/batch/segments,
    query/batch/fillRatio — on a MonitorScheduler owned by this server
    (start()/stop() manage it; metrics_tick() drives it manually in tests).
    `device_pool_bytes` sets the process-wide HBM budget staged segment
    blocks LRU-evict against (the data node is where segments live, so its
    server is where the budget is configured — the analog of the
    historical's druid.server.maxSize)."""

    def __init__(self, node: DataNode, host: str = "127.0.0.1",
                 port: int = 0, emitter=None,
                 device_pool_bytes: Optional[int] = None,
                 monitor_period_seconds: float = 60.0,
                 trace_store: Optional[qtrace.TraceStore] = None,
                 scheduler_config: Optional[SchedulerConfig] = None):
        """`trace_store` (default: the process singleton) receives this
        node's qtrace spans and backs GET /druid/v2/trace/<queryId>; a
        MetricRegistry always backs GET /metrics — the given `emitter`'s
        sink is composed with it, or a registry-only ServiceEmitter is
        created so every data node is scrapeable out of the box.

        `scheduler_config` turns on the admission-controlled cross-query
        scheduler (server/scheduler.py): aggregate /partials requests are
        held for the batching window and fused across queries; saturation
        answers HTTP 429 + Retry-After instead of queueing unboundedly."""
        self.node = node
        self.query_manager = QueryManager()
        self.scheduler: Optional[DataNodeScheduler] = None
        self._scheduler_config = scheduler_config
        self.trace_store = trace_store if trace_store is not None \
            else qtrace.trace_store()
        self.registry = MetricRegistry()
        self._query_counts = QueryCountStatsMonitor()
        if device_pool_bytes is not None:
            from druid_tpu.data.devicepool import device_pool
            device_pool().configure(device_pool_bytes)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, code: int, ctype: str, data: bytes,
                      headers=None):
                # the client may have hung up already (its own timeout
                # fired) — a late reply to a dead socket is not an error
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(data)))
                    for k, v in (headers or {}).items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(data)
                except (BrokenPipeError, ConnectionResetError):
                    self.close_connection = True

            def _reply_json(self, code: int, body, headers=None):
                self._send(code, "application/json",
                           json.dumps(body, default=_json_value).encode(),
                           headers=headers)

            def _reply_bytes(self, data: bytes):
                self._send(200, wire.CONTENT_TYPE, data)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_GET(self):
                if self.path == "/status":
                    descs = [d.to_json()
                             for d in outer.node.served_descriptors()]
                    self._reply_json(200, {
                        "version": "druid-tpu-0.2",
                        "server": outer.node.name,
                        "tier": outer.node.tier,
                        "segments": sorted(outer.node.served_segment_ids()),
                        # full descriptors so a broker's inventory sync can
                        # announce without being hand-fed
                        # (HttpServerInventoryView's segment listing)
                        "segmentDescriptors": descs})
                elif self.path.rstrip("/") == "/metrics":
                    self._send(200, PROM_CONTENT_TYPE,
                               outer.registry.exposition().encode())
                elif self.path.startswith("/druid/v2/trace/"):
                    qid = urllib.parse.unquote(
                        self.path[len("/druid/v2/trace/"):].rstrip("/"))
                    got = outer.trace_store.get(qid)
                    if got is None:
                        self._reply_json(404, {"error": "unknown trace",
                                               "queryId": qid})
                    else:
                        self._reply_json(200, got)
                else:
                    self._reply_json(404, {"error": "unknown path"})

            def do_POST(self):
                path = self.path.rstrip("/")
                try:
                    payload = self._body()
                    if path == "/druid/v2/partials":
                        self._partials(payload)
                    elif path == "/druid/v2/rows":
                        self._rows(payload)
                    else:
                        self._reply_json(404, {"error": "unknown path"})
                except QueryInterruptedError as e:
                    self._reply_json(500, {"error": "Query cancelled",
                                           "errorMessage": str(e)})
                except QueryTimeoutError as e:
                    self._reply_json(504, {"error": "Query timed out",
                                           "errorMessage": str(e)})
                except QueryCapacityError as e:
                    # the scheduler shed at admission: the 429 contract —
                    # not a hang, not a 500 — with the drain estimate as
                    # Retry-After so a well-behaved client backs off
                    self._reply_json(
                        429, {"error": "Query capacity exceeded",
                              "errorMessage": str(e)},
                        headers={"Retry-After": e.retry_after_header()})
                except (ValueError, KeyError) as e:
                    self._reply_json(400,
                                     {"error": f"{type(e).__name__}: {e}"})
                except Exception as e:
                    self._reply_json(500,
                                     {"error": f"{type(e).__name__}: {e}"})

            def _run(self, payload, rows_mode: bool):
                """Returns ((result, served), spans): the request's finished
                qtrace spans ride back in the response so the broker can
                assemble one end-to-end trace."""
                query = query_from_json(payload["query"])
                sids = payload.get("segments") or []
                qid = query.context_map.get("queryId")
                token = outer.query_manager.register(qid) if qid else None
                deadline = Deadline.for_query(query)

                def check():
                    if token is not None:
                        token.check()
                    deadline.check()

                t0 = time.monotonic()
                ok = False
                try:
                    # re-root this node's spans under the broker's remote
                    # parent (context traceparent); collect=True captures
                    # the request's spans for the response payload
                    with qtrace.root_span("datanode/query", query,
                                          service=outer.node.name,
                                          store=outer.trace_store,
                                          collect=True) as root:
                        check()
                        if rows_mode:
                            out = outer.node.run_rows(query, sids)
                        elif outer.scheduler is not None \
                                and outer.node.fusable(query):
                            # admission-controlled cross-query path: the
                            # hold opens a queue/wait span under THIS
                            # request's root; saturation raises
                            # QueryCapacityError (429 above). Work the
                            # node cannot fuse (mesh/per-segment-metrics)
                            # skips the queue — it would only serialize on
                            # the dispatcher thread. Segment-cache queries
                            # DO queue: hits resolve inline in the flush,
                            # misses join the fused wave
                            out = outer.scheduler.submit(query, sids,
                                                         check=check)
                        else:
                            out = outer.node.run_partials(query, sids,
                                                          check=check)
                        check()
                    ok = True
                    return out, (root.collected()
                                 if root is not None else [])
                finally:
                    if qid:
                        outer.query_manager.unregister(qid)
                    outer._query_counts.on_query(ok)
                    outer.emitter.metric(
                        "query/time", (time.monotonic() - t0) * 1e3,
                        dataSource=query.datasource, type=query.query_type,
                        id=qid or "", success=str(ok).lower())

            def _partials(self, payload):
                (ap, served), spans = self._run(payload, rows_mode=False)
                # the explicit wire half of the partial-result contract:
                # requested-but-unserved ids (the broker degrades on them
                # when the query allows partials)
                missing = [s for s in (payload.get("segments") or [])
                           if str(s) not in served]
                # compressed payload mode: requester advertised support
                # AND the query context did not opt out
                ctx = (payload.get("query") or {}).get("context") or {}
                compress = bool(payload.get("wireCompress")) \
                    and ctx.get("wireCompress", True) is not False
                self._reply_bytes(wire.dumps_partials(ap, served,
                                                      trace=spans,
                                                      missing=missing,
                                                      compress=compress))

            def _rows(self, payload):
                (rows, served), spans = self._run(payload, rows_mode=True)
                self._reply_json(200, {"rows": rows,
                                       "served": sorted(served),
                                       "trace": spans})

            def do_DELETE(self):
                qid = cancel_path_id(self.path)
                if qid is not None:
                    outer.query_manager.cancel(qid)
                    self._reply_json(202, {"queryId": qid})
                else:
                    self._reply_json(404, {"error": "unknown path"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        # every node is scrapeable: the registry joins the given emitter's
        # sink chain (undone on stop(), so an emitter reused across server
        # generations doesn't feed dead registries), or becomes the sink
        # of a fresh ServiceEmitter
        self._restore_sink = lambda: None
        if emitter is None:
            emitter = ServiceEmitter("druid/historical",
                                     f"{self.host}:{self.port}",
                                     self.registry)
        else:
            self._restore_sink = compose_sink(emitter, self.registry)
        self.emitter = emitter
        from druid_tpu.data.cascade import CodeDomainMonitor
        from druid_tpu.data.devicepool import DevicePoolMonitor
        from druid_tpu.engine.batching import BatchMetricsMonitor
        from druid_tpu.engine.filters import FilterBitmapMonitor
        from druid_tpu.engine.megakernel import MegakernelMonitor
        from druid_tpu.obs.dispatch import DispatchMonitor
        from druid_tpu.parallel.distributed import ShardedMonitor
        from druid_tpu.utils.emitter import MonitorScheduler
        from druid_tpu.storage.format_v2 import SegmentLoadMonitor
        monitors = [DevicePoolMonitor(), BatchMetricsMonitor(),
                    FilterBitmapMonitor(), MegakernelMonitor(),
                    CodeDomainMonitor(), DispatchMonitor(),
                    ShardedMonitor(), wire.WireStatsMonitor(),
                    SegmentLoadMonitor(), self._query_counts]
        if self._scheduler_config is not None:
            self.scheduler = DataNodeScheduler(
                node, self._scheduler_config, emitter=emitter)
            monitors.append(SchedulerMetricsMonitor(self.scheduler))
        self._monitors = MonitorScheduler(
            emitter, monitors, period_seconds=monitor_period_seconds)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def metrics_tick(self) -> None:
        """Drive the pool/batch monitors once (tests; the scheduler drives
        them periodically after start())."""
        if self._monitors is not None:
            self._monitors.tick()

    def start(self) -> "DataNodeServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        if self.scheduler is not None:
            self.scheduler.start()
        if self._monitors is not None:
            self._monitors.start()
        return self

    def stop(self) -> None:
        if self._monitors is not None:
            self._monitors.stop()
        self._restore_sink()
        self._httpd.shutdown()
        self._httpd.server_close()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        if self.scheduler is not None:
            # after the listener: no new submits can arrive; queued
            # waiters fail fast instead of hanging on a dead dispatcher
            self.scheduler.stop()


class RemoteDataNodeClient:
    """The broker's per-server query client (DirectDruidClient analog).

    Exposes the same (run_partials / run_rows) surface as an in-process
    DataNode so the broker's scatter path is transport-agnostic; registered
    into the InventoryView exactly like a local node. Socket timeouts follow
    the query's context timeout; cancel() propagates the DELETE."""

    def __init__(self, name: str, base_url: str,
                 connect_timeout: float = 5.0,
                 jitter_seed: Optional[int] = None):
        """jitter_seed: seeds the Retry-After jitter rng (deterministic
        tests); None draws from entropy, which is what production wants —
        identical seeds across a client fleet would defeat the point."""
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.connect_timeout = connect_timeout
        self.tier = "_default_tier"
        self.alive = True
        self._retry_rng = random.Random(jitter_seed)

    # ---- InventoryView/DataNode surface the broker touches -------------
    def segments(self) -> List:
        return []            # schema discovery uses segmentMetadata queries

    def served_segment_ids(self) -> Set[str]:
        try:
            st = self._status()
            return set(st.get("segments", []))
        except ConnectionError:
            return set()

    def served_descriptors(self) -> List:
        """Full segment descriptors from the node's /status — the sync
        loop's announcement source. PROPAGATES ConnectionError: a blip must
        abort the sync round for this server (liveness handles real
        deaths), not read as 'serves nothing' and mass-unannounce."""
        st = self._status()
        from druid_tpu.cluster.metadata import SegmentDescriptor
        return [SegmentDescriptor.from_json(j)
                for j in st.get("segmentDescriptors", [])]

    def ping(self) -> bool:
        """Liveness probe: a /status round-trip within connect_timeout,
        retried once — one dropped packet must not read as a dead server
        (the view additionally supports multi-cycle grace via
        check_liveness(failures_required=...))."""
        for attempt in (0, 1):
            try:
                self._status()
                return True
            except ConnectionError:
                if attempt:
                    return False
                time.sleep(0.05)
        return False

    def _status(self) -> dict:
        try:
            with urllib.request.urlopen(self.base_url + "/status",
                                        timeout=self.connect_timeout) as r:
                return json.loads(r.read())
        except (urllib.error.URLError, OSError) as e:
            raise ConnectionError(f"server [{self.name}] unreachable: {e}")

    def _timeout_for(self, query: Query) -> float:
        t = query.context_map.get("timeout")
        try:
            t = float(t) if t is not None else 0.0
        except (TypeError, ValueError):
            t = 0.0
        # socket timeout covers connect + full response read; the broker
        # rewrites the context timeout to the REMAINING deadline each
        # scatter round, so this never exceeds the original budget
        return (t / 1000.0) if t > 0 else DEFAULT_TIMEOUT_MS / 1000.0

    #: never sleep longer than this on a Retry-After before the one 429
    #: retry — a long drain estimate should fail fast at the broker, not
    #: camp on a scatter thread
    MAX_RETRY_AFTER_SLEEP = 2.0

    def _post(self, path: str, query: Query, segment_ids: Sequence[str]):
        # wireCompress advertises this client reads compressed tensor
        # entries (wire VERSION_COMPRESSED) — the server only emits them
        # when asked, so old clients keep receiving version-1 bytes
        body = json.dumps({"query": query.to_json(),
                           "segments": [str(s) for s in segment_ids],
                           "wireCompress": True},
                          default=_json_value).encode()
        # ONE total budget across the shed retry: the context timeout is
        # the query's, not per-attempt
        deadline = Deadline.after_s(self._timeout_for(query))
        for attempt in (0, 1):
            req = urllib.request.Request(
                self.base_url + path, data=body,
                headers={"Content-Type": "application/json"},
                method="POST")
            try:
                with urllib.request.urlopen(
                        req, timeout=max(0.1, deadline.remaining())) as r:
                    return r.headers.get_content_type(), r.read()
            except urllib.error.HTTPError as e:
                detail = e.read().decode(errors="replace")
                if e.code == 429:
                    # admission shed: distinguishable from query errors.
                    # Retry ONCE after Retry-After (within the remaining
                    # budget); a second shed propagates as a clear
                    # capacity error, not an opaque RemoteQueryError
                    try:
                        retry_after = float(
                            e.headers.get("Retry-After") or 1.0)
                    except (TypeError, ValueError):
                        retry_after = 1.0
                    # a drain estimate past the cap means the retry is
                    # near-certain to shed again — fail fast instead of
                    # sleeping the cap and reissuing a doomed request.
                    # The actual sleep is decorrelated-jittered ABOVE the
                    # server's estimate: under a 429 storm every client
                    # hears the same Retry-After, and sleeping it exactly
                    # re-synchronizes the whole fleet onto one retry
                    # instant — the next shed wave
                    from druid_tpu.cluster.resilience import \
                        decorrelated_jitter
                    sleep_s = decorrelated_jitter(
                        self._retry_rng, retry_after, retry_after,
                        self.MAX_RETRY_AFTER_SLEEP)
                    if attempt == 0 \
                            and retry_after <= self.MAX_RETRY_AFTER_SLEEP \
                            and sleep_s < deadline.remaining():
                        time.sleep(sleep_s)
                        continue
                    raise QueryCapacityError(
                        f"server [{self.name}] shed the query: {detail}",
                        retry_after_s=retry_after, server=self.name)
                if e.code == 504:
                    raise QueryTimeoutError(detail)
                if e.code == 500 and "cancelled" in detail.lower():
                    raise QueryInterruptedError(detail)
                # a served HTTP error is a QUERY error — propagate the
                # node's message instead of retrying into
                # MissingSegmentsError
                raise RemoteQueryError(self.name, e.code, detail)
            except socket.timeout:
                raise QueryTimeoutError(
                    f"server [{self.name}] did not respond in time")
            except (urllib.error.URLError, OSError) as e:
                if isinstance(getattr(e, "reason", None), socket.timeout):
                    raise QueryTimeoutError(
                        f"server [{self.name}] did not respond in time")
                raise ConnectionError(
                    f"server [{self.name}] unreachable: {e}")

    def run_partials(self, query: Query, segment_ids: Sequence[str]
                     ) -> Tuple[object, Set[str]]:
        ctype, data = self._post("/druid/v2/partials", query, segment_ids)
        if ctype != wire.CONTENT_TYPE:
            raise ConnectionError(
                f"server [{self.name}] returned {ctype}, expected partials")
        ap, served, spans = wire.loads_partials(data)
        self._ingest_trace(spans)
        return ap, served

    def run_rows(self, query: Query, segment_ids: Sequence[str]
                 ) -> Tuple[List[dict], Set[str]]:
        _, data = self._post("/druid/v2/rows", query, segment_ids)
        out = json.loads(data)
        self._ingest_trace(out.get("trace"))
        return out["rows"], set(out["served"])

    def _ingest_trace(self, spans) -> None:
        """Merge the node's returned span tree into this (broker) process's
        trace store — the gather half of qtrace propagation. Span-id dedupe
        in the store makes this idempotent when broker and node share one
        process (in-process tests)."""
        if spans:
            qtrace.trace_store().ingest(spans)

    def cancel(self, query_id: str) -> None:
        req = urllib.request.Request(
            f"{self.base_url}/druid/v2/{query_id}", method="DELETE")
        try:
            urllib.request.urlopen(req, timeout=self.connect_timeout).read()
        except (urllib.error.URLError, OSError):
            pass   # best-effort, server may already be gone
