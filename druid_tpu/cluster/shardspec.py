"""Shard specs: secondary partitioning within a time chunk.

Capability parity with the reference's shard specs
(common/.../timeline/partition/ — NoneShardSpec, LinearShardSpec,
NumberedShardSpec, HashBasedNumberedShardSpec, SingleDimensionShardSpec).
Shard specs drive (a) partition-set completeness in the timeline MVCC,
(b) broker-side pruning (hash/range), (c) ingest-time row routing.
"""
from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence


class ShardSpec:
    partition_num: int = 0

    def is_in_chunk(self, dim_values: Dict[str, Optional[str]]) -> bool:
        """Row routing at ingest (reference ShardSpec.isInChunk)."""
        return True

    def possible_in_domain(self, domain: Dict[str, List[Optional[str]]]) -> bool:
        """Broker pruning: can any row matching `domain` (dim -> candidate
        values; absent = unconstrained) live in this shard?"""
        return True

    def complete_set(self, specs: Sequence["ShardSpec"]) -> bool:
        """Is this collection of sibling specs a complete partition set?"""
        return True

    def to_json(self) -> dict:
        raise NotImplementedError


@dataclass(frozen=True)
class NoneShardSpec(ShardSpec):
    """Single unsharded chunk (reference NoneShardSpec)."""
    partition_num: int = 0

    def to_json(self):
        return {"type": "none"}


@dataclass(frozen=True)
class LinearShardSpec(ShardSpec):
    """Append-friendly: any subset is complete (reference LinearShardSpec)."""
    partition_num: int = 0

    def to_json(self):
        return {"type": "linear", "partitionNum": self.partition_num}


@dataclass(frozen=True)
class NumberedShardSpec(ShardSpec):
    """partNum of a fixed set of `partitions` core partitions; the set is
    visible only when all core partitions are present
    (reference NumberedShardSpec)."""
    partition_num: int = 0
    partitions: int = 0

    def complete_set(self, specs):
        if self.partitions == 0:
            return True  # open-ended (streaming appends)
        present = {s.partition_num for s in specs}
        return all(i in present for i in range(self.partitions))

    def to_json(self):
        return {"type": "numbered", "partitionNum": self.partition_num,
                "partitions": self.partitions}


def _hash_row(values: Sequence[Optional[str]]) -> int:
    payload = json.dumps([v if v is not None else "" for v in values])
    return int.from_bytes(
        hashlib.md5(payload.encode()).digest()[:4], "big", signed=False)


@dataclass(frozen=True)
class HashBasedNumberedShardSpec(NumberedShardSpec):
    """Rows hash-routed on partitionDimensions; the broker prunes shards
    when a filter pins every partition dimension
    (reference HashBasedNumberedShardSpec + DetermineHashedPartitionsJob)."""
    partition_num: int = 0
    partitions: int = 1
    partition_dimensions: tuple = ()

    def is_in_chunk(self, dim_values):
        if not self.partition_dimensions or self.partitions <= 1:
            return True
        vals = [dim_values.get(d) for d in self.partition_dimensions]
        return _hash_row(vals) % self.partitions == self.partition_num

    def possible_in_domain(self, domain):
        if not self.partition_dimensions or self.partitions <= 1:
            return True
        candidate_lists = []
        for d in self.partition_dimensions:
            if d not in domain:
                return True  # unconstrained dim: cannot prune
            candidate_lists.append(domain[d])
        # cartesian check (domains are small filter value sets)
        def rec(i, acc):
            if i == len(candidate_lists):
                return _hash_row(acc) % self.partitions == self.partition_num
            return any(rec(i + 1, acc + [v]) for v in candidate_lists[i])
        return rec(0, [])

    def to_json(self):
        return {"type": "hashed", "partitionNum": self.partition_num,
                "partitions": self.partitions,
                "partitionDimensions": list(self.partition_dimensions)}


@dataclass(frozen=True)
class SingleDimensionShardSpec(ShardSpec):
    """Contiguous [start, end) value range on one dimension
    (reference SingleDimensionShardSpec)."""
    dimension: str = ""
    start: Optional[str] = None  # None = unbounded below
    end: Optional[str] = None    # None = unbounded above
    partition_num: int = 0

    def _contains(self, v: Optional[str]) -> bool:
        v = "" if v is None else v
        if self.start is not None and v < self.start:
            return False
        if self.end is not None and v >= self.end:
            return False
        return True

    def is_in_chunk(self, dim_values):
        return self._contains(dim_values.get(self.dimension))

    def possible_in_domain(self, domain):
        if self.dimension not in domain:
            return True
        return any(self._contains(v) for v in domain[self.dimension])

    def complete_set(self, specs):
        # complete iff ranges tile (-inf, +inf) contiguously
        rs = sorted(specs, key=lambda s: ("" if s.start is None else s.start,))
        if not rs or rs[0].start is not None or rs[-1].end is not None:
            return False
        for a, b in zip(rs, rs[1:]):
            if a.end is None or b.start is None or a.end != b.start:
                return False
        return True

    def to_json(self):
        return {"type": "single", "dimension": self.dimension,
                "start": self.start, "end": self.end,
                "partitionNum": self.partition_num}


def shardspec_from_json(j: Optional[dict]) -> ShardSpec:
    if not j:
        return NoneShardSpec()
    t = j.get("type", "none")
    if t == "none":
        return NoneShardSpec()
    if t == "linear":
        return LinearShardSpec(j.get("partitionNum", 0))
    if t == "numbered":
        return NumberedShardSpec(j.get("partitionNum", 0),
                                 j.get("partitions", 0))
    if t == "hashed":
        return HashBasedNumberedShardSpec(
            j.get("partitionNum", 0), j.get("partitions", 1),
            tuple(j.get("partitionDimensions", [])))
    if t == "single":
        return SingleDimensionShardSpec(
            j.get("dimension", ""), j.get("start"), j.get("end"),
            j.get("partitionNum", 0))
    raise ValueError(f"unknown shardSpec type {t!r}")
