"""Deterministic fault-injection harness for the query DATA plane — the
peer of coordination/chaos.py (which faults the control plane's lease
store). Where that harness proves bounded leader failover, this one
proves the broker's fault-tolerance contract: under any injected data-node
fault, every query either returns EXACT results (bit-identical to the
fault-free oracle), a TYPED partial (allowPartialResults, with an accurate
missingSegments report), or a TYPED error — within its deadline, never a
hang, never a silently wrong answer.

Data-node clients wrap in seeded fault gates covering the canonical
data-plane failure modes:

  dead   — every call raises ConnectionError (process death / partition)
  slow   — fixed latency plus a seeded heavy tail (the straggler the
           hedging layer exists for)
  flap   — alternates reachable/unreachable every `flap_period` calls
           (a GC-thrashing or link-flapping server)
  error  — every call fails with a server error (the HTTP-500 storm)
  shed   — every call answers a capacity shed (the 429 storm)
  hang   — calls block until the query is CANCELLED on this node (the
           loser-cancellation path) or a hard cap elapses; the cap is
           what keeps the harness itself deterministic and hang-free

All randomness (heavy-tail draws) comes from per-node seeded rngs, so a
scenario replays identically. Reference analog: none 1:1 — the reference
leans on integration chaos (e.g. Druid's RetryQueryRunnerTest fakes
missing segments); this plays that role as a first-class harness.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from druid_tpu.cluster.broker import Broker, MissingSegmentsError
from druid_tpu.cluster.dataserver import RemoteQueryError
from druid_tpu.cluster.resilience import ResiliencePolicy
from druid_tpu.cluster.view import DataNode, InventoryView, descriptor_for
from druid_tpu.server.querymanager import (QueryCapacityError,
                                           QueryInterruptedError,
                                           QueryTimeoutError)

class ChaosError(RuntimeError):
    """The injected server-error fault (the HTTP-500 class)."""


#: the error types the contract counts as TYPED — anything else escaping
#: the broker under chaos is a harness failure
TYPED_ERRORS = (QueryCapacityError, QueryTimeoutError,
                QueryInterruptedError, MissingSegmentsError,
                RemoteQueryError, ConnectionError, ChaosError)


@dataclass(frozen=True)
class FaultSpec:
    """One node's injected fault."""
    mode: str                       # dead|slow|flap|error|shed|hang
    delay_ms: float = 100.0         # slow: fixed latency
    heavy_tail_ms: float = 0.0      # slow: extra tail latency...
    tail_prob: float = 0.1          # ...drawn with this probability
    flap_period: int = 2            # flap: calls per up/down half-cycle
    retry_after_s: float = 0.05     # shed: the 429's drain estimate
    max_hang_s: float = 5.0         # hang: hard cap (determinism bound)

    def __post_init__(self):
        if self.mode not in ("dead", "slow", "flap", "error", "shed",
                             "hang"):
            raise ValueError(f"unknown fault mode {self.mode!r}")


class ChaosDataNode:
    """A data-node client behind a seeded fault gate. Exposes the same
    surface the broker and InventoryView touch (run_partials / run_rows /
    cancel / ping / segments / load_segment ...), so it registers into
    the view exactly like the node it wraps."""

    segment_replicatable = True

    def __init__(self, inner: DataNode, seed: int = 0):
        self.inner = inner
        self._rng = random.Random(seed)
        self._spec: Optional[FaultSpec] = None
        self._calls = 0
        self._lock = threading.Lock()
        #: qid → event set by cancel(); how a hang releases
        self._hang_cancels: Dict[str, threading.Event] = {}
        #: every cancel(qid) observed — the loser-cancellation witness
        self.cancel_calls: List[str] = []

    # ---- proxied identity ----------------------------------------------
    @property
    def name(self) -> str:
        return self.inner.name

    @property
    def tier(self) -> str:
        return self.inner.tier

    @property
    def alive(self) -> bool:
        return self.inner.alive

    def ping(self) -> bool:
        with self._lock:
            spec = self._spec
        if spec is not None and spec.mode == "dead":
            return False
        return self.inner.ping()

    def segments(self):
        return self.inner.segments()

    def served_segment_ids(self):
        return self.inner.served_segment_ids()

    def served_descriptors(self):
        return self.inner.served_descriptors()

    def load_segment(self, segment, descriptor=None):
        return self.inner.load_segment(segment, descriptor)

    # ---- fault control ---------------------------------------------------
    def fault(self, spec: Optional[FaultSpec]) -> None:
        with self._lock:
            self._spec = spec
            self._calls = 0

    def heal(self) -> None:
        self.fault(None)

    # ---- the gate --------------------------------------------------------
    def _gate(self, query) -> None:
        """Applied before every inner call; raises or delays per spec.
        Deterministic: latency draws come from the node's seeded rng (the
        draw happens under the lock; the sleep does not)."""
        with self._lock:
            spec = self._spec
            n = self._calls
            self._calls += 1
            draw = self._rng.random() if spec is not None else 0.0
        if spec is None:
            return
        if spec.mode == "dead":
            raise ConnectionError(f"chaos: [{self.name}] is dead")
        if spec.mode == "flap" and (n // max(1, spec.flap_period)) % 2:
            raise ConnectionError(f"chaos: [{self.name}] is flapping")
        if spec.mode == "error":
            raise ChaosError(f"chaos: [{self.name}] error storm")
        if spec.mode == "shed":
            raise QueryCapacityError(
                f"chaos: [{self.name}] 429 storm",
                retry_after_s=spec.retry_after_s, server=self.name)
        if spec.mode == "slow":
            delay = spec.delay_ms
            if spec.heavy_tail_ms > 0 and draw < spec.tail_prob:
                delay += spec.heavy_tail_ms
            time.sleep(delay / 1000.0)
            return
        if spec.mode == "hang":
            qid = query.context_map.get("queryId") or ""
            with self._lock:
                ev = self._hang_cancels.setdefault(qid,
                                                   threading.Event())
            if ev.wait(spec.max_hang_s):
                # released by the broker's loser/abandon cancellation —
                # answer the way a cancelled node would
                raise QueryInterruptedError(
                    f"chaos: [{self.name}] hang cancelled")
            raise ConnectionError(
                f"chaos: [{self.name}] hang cap elapsed")

    # ---- query surface ---------------------------------------------------
    def run_partials(self, query, segment_ids, check=None):
        self._gate(query)
        if check is None:
            # remote clients (RemoteDataNodeClient) take no check kwarg —
            # forwarding None would TypeError the wrapped HTTP node
            return self.inner.run_partials(query, segment_ids)
        return self.inner.run_partials(query, segment_ids, check=check)

    def run_rows(self, query, segment_ids):
        self._gate(query)
        return self.inner.run_rows(query, segment_ids)

    def cancel(self, query_id: str) -> None:
        """The remote-cancel hook the broker fires at hedge losers and
        deadline-abandoned calls; releases a hanging gate and is recorded
        so tests can observe the cancellation."""
        with self._lock:
            self.cancel_calls.append(query_id)
            ev = self._hang_cancels.setdefault(query_id,
                                               threading.Event())
        ev.set()
        cancel = getattr(self.inner, "cancel", None)
        if cancel is not None:
            cancel(query_id)


@dataclass
class Outcome:
    """One classified query run: kind is 'exact' | 'partial' | 'error'."""
    kind: str
    rows: Optional[list]
    error: Optional[BaseException]
    elapsed_s: float
    missing: List[str] = field(default_factory=list)


class DataPlaneChaosHarness:
    """A broker over chaos-wrapped data nodes plus the fault-free oracle,
    with outcome classification — the scenario suite's one entry point.

    Segments spread round-robin at the given replication factor; every
    node wraps in a ChaosDataNode whose seed derives from the harness
    seed, so a scenario is replayable bit-for-bit."""

    def __init__(self, segments: Sequence, n_nodes: int = 3,
                 replication: int = 2, seed: int = 0,
                 policy: Optional[ResiliencePolicy] = None,
                 max_retries: int = 2):
        self.segments = list(segments)
        self.view = InventoryView()
        self.nodes: Dict[str, ChaosDataNode] = {}
        for i in range(n_nodes):
            node = ChaosDataNode(DataNode(f"chaos{i}"), seed=seed * 1000 + i)
            self.nodes[node.name] = node
            self.view.register(node)
        names = sorted(self.nodes)
        for i, s in enumerate(self.segments):
            for j in range(replication):
                node = self.nodes[names[(i + j) % n_nodes]]
                node.load_segment(s)
                self.view.announce(node.name, descriptor_for(s))
        self.broker = Broker(self.view, seed=seed, max_retries=max_retries,
                             resilience_policy=policy)
        self._by_id = {str(s.id): s for s in self.segments}

    # ---- fault control ---------------------------------------------------
    def fault(self, name: str, spec: FaultSpec) -> None:
        self.nodes[name].fault(spec)

    def heal(self, name: Optional[str] = None) -> None:
        for node in ([self.nodes[name]] if name else self.nodes.values()):
            node.heal()

    def stop(self) -> None:
        self.broker.stop()

    # ---- oracle + classification ----------------------------------------
    def oracle(self, query, exclude: Sequence[str] = ()) -> list:
        """Fault-free single-process execution over all segments (or all
        but `exclude` — the surviving set of a partial result)."""
        from druid_tpu.engine.executor import QueryExecutor
        keep = [s for sid, s in self._by_id.items() if sid not in
                set(str(x) for x in exclude)]
        return QueryExecutor(keep).run(query)

    def run_classified(self, query) -> Outcome:
        """Run through the broker and classify: exact rows, typed partial
        (with its report), or a typed error. Anything else propagates —
        an UNtyped escape is precisely what the suite must catch."""
        t0 = time.monotonic()
        try:
            rows = self.broker.run(query)
        except TYPED_ERRORS as e:
            return Outcome("error", None, e, time.monotonic() - t0)
        elapsed = time.monotonic() - t0
        missing = getattr(rows, "missing_segments", None)
        if missing is not None:
            return Outcome("partial", list(rows), None, elapsed,
                           missing=list(missing))
        return Outcome("exact", rows, None, elapsed)

    def verify(self, query, outcome: Outcome) -> None:
        """The bit-parity gate on every surviving path: exact results
        must equal the full oracle; a partial's rows must equal the
        oracle over exactly the segments its report says survived (an
        inaccurate missingSegments report fails here)."""
        if outcome.kind == "exact":
            assert outcome.rows == self.oracle(query), \
                "exact result diverged from the fault-free oracle"
        elif outcome.kind == "partial":
            assert outcome.missing, "partial without a missing report"
            assert set(outcome.missing) <= set(self._by_id), \
                f"report names unknown segments: {outcome.missing}"
            expect = self.oracle(query, exclude=outcome.missing) \
                if len(outcome.missing) < len(self._by_id) else []
            assert outcome.rows == expect, \
                "partial rows diverged from the oracle over the " \
                "surviving segment set — the report is inaccurate " \
                "or a partial was double-merged"
