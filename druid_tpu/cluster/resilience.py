"""Broker-side data-plane fault tolerance.

Three mechanisms, one policy surface:

  * Per-server CIRCUIT BREAKERS — consecutive failures (connection errors,
    capacity sheds, timeouts) OPEN the circuit; replica selection skips an
    open server, so a sick replica stops being rediscovered by paying its
    full timeout on every query. After a jittered cooldown the breaker
    goes HALF_OPEN and lets exactly ONE probe query through; success
    closes it, failure re-opens with a fresh cooldown. When EVERY replica
    of a segment is open, selection falls back to an open server anyway
    (tagged as a probe) — a guaranteed MissingSegmentsError is worse than
    one fail-fast attempt.
  * HEDGED REQUESTS — when a scatter wave's straggler exceeds a hedge
    delay derived from the view's per-server latency EWMA (the broker
    feeds its broker/node span times back into the view), the pending
    segment set is speculatively re-issued on one other replica. The
    first complete response wins; the loser's response is dropped whole
    (AggregatePartials over a fused segment set cannot be split, so
    claim-or-drop is what makes "a hedge-won segment is never
    double-merged" a structural invariant, not a hope) and its in-flight
    work is cancelled through the same remote-cancel hook the query
    token uses.
  * GRACEFUL DEGRADATION — context `allowPartialResults: true` lets a
    query whose replicas are exhausted (or whose deadline is nearly
    spent) return a typed PartialResult carrying a missingSegments
    report instead of a 500/504 — exactly once, never silently: the
    report rides the result object, the HTTP response context header,
    and the SQL surface.

Reference analogs: RetryQueryRunner + QueryContexts.allowPartialResults
(the reference reports unserved segments in the response context), and
the hedged-request/breaker vocabulary of The Tail at Scale. Every knob
lives in ResiliencePolicy so the chaos suite (cluster/chaos.py) can force
each mechanism deterministically.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from druid_tpu.utils.emitter import Monitor


@dataclass(frozen=True)
class ResiliencePolicy:
    """Every fault-tolerance knob of the broker's data plane."""

    # ---- circuit breakers ----------------------------------------------
    #: master switch for per-server breakers
    circuit_enabled: bool = True
    #: consecutive failures (errors/sheds/timeouts) that OPEN a circuit
    circuit_failure_threshold: int = 3
    #: base OPEN → HALF_OPEN cooldown; the actual cooldown is
    #: decorrelated-jittered in [base, cap] so a fleet of brokers does not
    #: re-probe a recovering server in lockstep
    circuit_cooldown_s: float = 5.0
    circuit_cooldown_cap_s: float = 30.0

    # ---- hedged requests -----------------------------------------------
    #: master switch (context {"hedge": false} opts a query out)
    hedge_enabled: bool = True
    #: hedge delay = max(min_delay, multiplier * per-server latency EWMA);
    #: with no EWMA yet (first contact) the min delay alone applies
    hedge_latency_multiplier: float = 3.0
    hedge_min_delay_ms: float = 50.0
    #: speculative re-issues allowed per query (not per wave) — hedging is
    #: a tail-latency tool, not a second scatter
    hedge_max_per_query: int = 4

    # ---- partial results -----------------------------------------------
    #: with allowPartialResults set, degrade to a partial instead of
    #: starting another retry round once the remaining deadline fraction
    #: drops below this (a round that cannot finish only converts a
    #: partial into a 504)
    partial_deadline_fraction: float = 0.1

    # ---- latency EWMA ---------------------------------------------------
    #: smoothing for the view's per-server latency estimate
    latency_alpha: float = 0.2


def decorrelated_jitter(rng: random.Random, base_s: float, prev_s: float,
                        cap_s: float) -> float:
    """Decorrelated jitter (the AWS backoff variant): next sleep is
    uniform in [base, prev * 3], capped. Feeding each sleep back as
    `prev` makes successive sleeps spread out instead of re-synchronizing
    every client onto the same retry instant — the failure mode of both a
    429 storm's Retry-After and a fleet's half-open probes."""
    base_s = max(0.0, min(base_s, cap_s))
    hi = max(base_s, min(cap_s, prev_s * 3.0))
    return base_s + rng.random() * (hi - base_s)


# ---------------------------------------------------------------------------
# Circuit breakers
# ---------------------------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """One server's breaker. Not thread-safe on its own — the registry's
    lock covers every transition."""

    def __init__(self, policy: ResiliencePolicy, rng: random.Random,
                 clock=time.monotonic):
        self.policy = policy
        self._rng = rng
        self._clock = clock
        self.state = CLOSED
        self.consecutive_failures = 0
        self._cooldown_until = 0.0
        self._prev_cooldown_s = policy.circuit_cooldown_s

    def cooled_down(self) -> bool:
        return self._clock() >= self._cooldown_until

    def trip(self) -> None:
        self.state = OPEN
        self._prev_cooldown_s = decorrelated_jitter(
            self._rng, self.policy.circuit_cooldown_s,
            self._prev_cooldown_s, self.policy.circuit_cooldown_cap_s)
        self._cooldown_until = self._clock() + self._prev_cooldown_s

    def on_success(self) -> None:
        self.state = CLOSED
        self.consecutive_failures = 0
        self._prev_cooldown_s = self.policy.circuit_cooldown_s

    def on_failure(self) -> bool:
        """Record one failure; True when this one tripped the circuit."""
        self.consecutive_failures += 1
        if self.state == HALF_OPEN:
            # the probe failed: straight back to OPEN, fresh cooldown
            self.trip()
            return True
        if self.state == CLOSED and self.consecutive_failures >= \
                self.policy.circuit_failure_threshold:
            self.trip()
            return True
        return False


class CircuitRegistry:
    """Per-server breakers + the selection/outcome surface the broker and
    ReplicaSet.pick talk to. All state transitions run under one lock;
    the seeded rng keeps cooldown jitter deterministic in tests."""

    def __init__(self, policy: Optional[ResiliencePolicy] = None,
                 seed: int = 0, clock=time.monotonic):
        self.policy = policy or ResiliencePolicy()
        self._rng = random.Random(seed)
        self._clock = clock
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()
        self.trips = 0
        self.probes = 0

    def _breaker(self, server: str) -> CircuitBreaker:
        b = self._breakers.get(server)
        if b is None:
            b = self._breakers[server] = CircuitBreaker(
                self.policy, self._rng, self._clock)
        return b

    # ---- selection surface (ReplicaSet.pick) ---------------------------
    def closed(self, server: str) -> bool:
        """Selection may route here freely (CLOSED, or never seen)."""
        if not self.policy.circuit_enabled:
            return True
        with self._lock:
            b = self._breakers.get(server)
            return b is None or b.state == CLOSED

    def probe_candidate(self, server: str) -> bool:
        """OPEN with its cooldown elapsed — the half-open transition is
        waiting for exactly one query to ride through."""
        with self._lock:
            b = self._breakers.get(server)
            return b is not None and b.state == OPEN and b.cooled_down()

    def begin_probe(self, server: str) -> None:
        """Selection chose an open server: mark the half-open probe (one
        in flight — further selections skip it until it resolves)."""
        with self._lock:
            b = self._breakers.get(server)
            if b is not None and b.state != CLOSED:
                b.state = HALF_OPEN
                self.probes += 1

    # ---- outcome surface (broker scatter) ------------------------------
    def on_success(self, server: str) -> None:
        with self._lock:
            b = self._breakers.get(server)
            if b is not None:
                b.on_success()

    def on_failure(self, server: str) -> None:
        with self._lock:
            if self._breaker(server).on_failure():
                self.trips += 1

    # ---- observation ----------------------------------------------------
    def state_of(self, server: str) -> str:
        with self._lock:
            b = self._breakers.get(server)
            return CLOSED if b is None else b.state

    def open_count(self) -> int:
        with self._lock:
            return sum(1 for b in self._breakers.values()
                       if b.state != CLOSED)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"open": sum(1 for b in self._breakers.values()
                                if b.state != CLOSED),
                    "trips": self.trips, "probes": self.probes}


# ---------------------------------------------------------------------------
# Typed partial results
# ---------------------------------------------------------------------------

class PartialResult(list):
    """Result rows that are knowingly incomplete: a list (every existing
    merge/serialization caller keeps working) that TYPES the degradation
    and carries the missing-segment report — a partial can never be
    mistaken for a full result by anyone who checks, and the HTTP/SQL
    surfaces stamp the report onto the response exactly once."""

    def __init__(self, rows: Sequence, missing_segments: Sequence[str]):
        super().__init__(rows)
        # deduped: UNION arms (and hedge retries) may report one segment
        # several times — the report counts holes, not sightings
        self.missing_segments: List[str] = sorted(
            {str(s) for s in missing_segments})

    def response_context(self) -> dict:
        """The X-Druid-Response-Context payload (the reference broker
        reports unserved segments the same way)."""
        return {"partial": True, "missingSegments": self.missing_segments}


def missing_segments_of(rows) -> Optional[List[str]]:
    """The missing-segment report of a (possibly partial) result — None
    for a complete result. Duck-typed so shaped SQL rows re-wrapped as
    PartialResult and broker-native rows answer identically."""
    return getattr(rows, "missing_segments", None)


def allows_partial(query) -> bool:
    """Context `allowPartialResults` — the degradation opt-in (never the
    default: silent partials are the one unforgivable failure mode)."""
    return bool(query.context_map.get("allowPartialResults"))


def hedging_enabled(policy: ResiliencePolicy, query) -> bool:
    """Hedging is policy-on by default; a query opts out with
    {"hedge": false} (e.g. side-effectful extensions)."""
    v = query.context_map.get("hedge")
    return policy.hedge_enabled and (v is None or bool(v))


# ---------------------------------------------------------------------------
# Stats + monitor
# ---------------------------------------------------------------------------

class ResilienceStats:
    """Broker-wide counters for the fault-tolerance layer (cumulative;
    the monitor emits per-period deltas for the countable events and the
    live open-circuit gauge)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.hedges_issued = 0
        self.hedges_won = 0
        self.hedges_cancelled = 0
        self.partial_queries = 0
        self.partial_missing_segments = 0

    def note_hedge_issued(self, n: int = 1) -> None:
        with self._lock:
            self.hedges_issued += n

    def note_hedge_won(self) -> None:
        with self._lock:
            self.hedges_won += 1

    def note_hedge_cancelled(self) -> None:
        with self._lock:
            self.hedges_cancelled += 1

    def note_partial(self, missing: int) -> None:
        with self._lock:
            self.partial_queries += 1
            self.partial_missing_segments += missing

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"hedges_issued": self.hedges_issued,
                    "hedges_won": self.hedges_won,
                    "hedges_cancelled": self.hedges_cancelled,
                    "partial_queries": self.partial_queries,
                    "partial_missing_segments":
                        self.partial_missing_segments}


class BrokerResilience:
    """The broker's fault-tolerance state bundle: one policy, one circuit
    registry, one stats block. Owned by the Broker; the view's replica
    selection reads the registry through it."""

    def __init__(self, policy: Optional[ResiliencePolicy] = None,
                 seed: int = 0):
        self.policy = policy or ResiliencePolicy()
        self.circuits = CircuitRegistry(self.policy, seed=seed)
        self.stats = ResilienceStats()

    def hedge_delay_s(self, view, server: str) -> float:
        """How long a wave waits on `server` before hedging its pending
        segments: the per-server latency EWMA (fed back from broker/node
        call times) scaled by the policy multiplier, floored at the
        policy minimum."""
        ewma = view.latency_ms(server)
        delay_ms = self.policy.hedge_min_delay_ms if ewma is None else max(
            self.policy.hedge_min_delay_ms,
            self.policy.hedge_latency_multiplier * ewma)
        return delay_ms / 1000.0

    def deadline_nearly_spent(self, deadline, total_ms: Optional[float]
                              ) -> bool:
        """True when another retry round is pointless: the remaining
        budget is below the policy fraction of the query's total."""
        remaining = deadline.remaining_ms()
        if remaining is None or total_ms is None:
            return False
        return remaining < total_ms * self.policy.partial_deadline_fraction


class ResilienceMetricsMonitor(Monitor):
    """broker/circuit/* + query/hedge/* + query/partial/* per tick."""

    def __init__(self, resilience: BrokerResilience):
        self.resilience = resilience
        self._last: Dict[str, int] = {}

    def _delta(self, key: str, value: int) -> int:
        d = value - self._last.get(key, 0)
        self._last[key] = value
        return d

    def do_monitor(self, emitter):
        circuits = self.resilience.circuits.snapshot()
        stats = self.resilience.stats.snapshot()
        emitter.metric("broker/circuit/open", circuits["open"])
        emitter.metric("broker/circuit/trips",
                       self._delta("trips", circuits["trips"]))
        emitter.metric("broker/circuit/probes",
                       self._delta("probes", circuits["probes"]))
        emitter.metric("query/hedge/issued",
                       self._delta("issued", stats["hedges_issued"]))
        emitter.metric("query/hedge/won",
                       self._delta("won", stats["hedges_won"]))
        emitter.metric("query/hedge/cancelled",
                       self._delta("cancelled", stats["hedges_cancelled"]))
        emitter.metric("query/partial/missingSegments",
                       self._delta("missing",
                                   stats["partial_missing_segments"]))
