"""Per-server asynchronous segment load/drop queues.

Reference analog: server/src/main/java/org/apache/druid/server/coordinator/
LoadQueuePeon.java (+ HttpLoadQueuePeon): the coordinator never blocks on a
segment download — it enqueues load/drop requests per server, a worker
drains them (pull from deep storage, load, announce), callbacks fire on
completion, and the per-server queue depth bounds how much one cycle can
pile onto a node (maxSegmentsInNodeLoadingQueue).
"""
from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Dict, List, Optional, Set

from druid_tpu.cluster.metadata import SegmentDescriptor


class LoadQueuePeon:
    """One server's load/drop queue + worker thread."""

    def __init__(self, node, view, segment_source: Callable,
                 max_queue_size: Optional[int] = None):
        """segment_source: descriptor -> Segment (deep-storage pull)."""
        self.node = node
        self.view = view
        self.segment_source = segment_source
        self.max_queue_size = max_queue_size
        self._q: "queue.Queue[tuple]" = queue.Queue()
        self._pending: Set[str] = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()
        self.loads_done = 0
        self.drops_done = 0
        self.failures: List[str] = []

    # ---- enqueue (coordinator side) ------------------------------------
    def load(self, descriptor: SegmentDescriptor,
             callback: Optional[Callable[[bool], None]] = None) -> bool:
        """Enqueue a load; False when the queue is full or already pending
        (the coordinator retries next cycle — exactly the reference's
        bounded-queue behavior)."""
        with self._lock:
            if descriptor.id in self._pending:
                return False
            if self.max_queue_size is not None \
                    and len(self._pending) >= self.max_queue_size:
                return False
            self._pending.add(descriptor.id)
        self._idle.clear()
        self._q.put(("load", descriptor, callback))
        return True

    def drop(self, descriptor: SegmentDescriptor,
             callback: Optional[Callable[[bool], None]] = None) -> bool:
        with self._lock:
            if descriptor.id in self._pending:
                return False
            self._pending.add(descriptor.id)
        self._idle.clear()
        self._q.put(("drop", descriptor, callback))
        return True

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def is_pending(self, segment_id: str) -> bool:
        with self._lock:
            return str(segment_id) in self._pending

    def pending_ids(self) -> Set[str]:
        """Snapshot of queued/in-flight segment ids (one lock hold — the
        coordinator's rules loop must not take this lock per segment)."""
        with self._lock:
            return set(self._pending)

    # ---- worker ---------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                op, d, callback = self._q.get(timeout=0.05)
            except queue.Empty:
                # set idle ONLY while provably drained: a load() racing
                # this branch must not let wait_idle() pass early
                with self._lock:
                    if not self._pending:
                        self._idle.set()
                continue
            ok = False
            try:
                if op == "load":
                    segment = self.segment_source(d)
                    ok = segment is not None \
                        and self.node.load_segment(segment, d)
                    if ok:
                        if self.view.node(self.node.name) is not None:
                            self.view.announce(self.node.name, d)
                            self.loads_done += 1
                        else:
                            # the server died while this sat queued: do
                            # not ghost-announce for an unregistered node
                            self.node.drop_segment(d.id)
                            ok = False
                else:
                    ok = self.node.drop_segment(d.id)
                    if ok:
                        self.view.unannounce(self.node.name, d.id)
                        self.drops_done += 1
                    else:
                        self.failures.append(f"drop {d.id}: not loaded")
            except Exception as e:   # a bad segment must not kill the peon
                self.failures.append(f"{op} {d.id}: {e}")
            finally:
                # callback BEFORE the idle signal: wait_idle() returning
                # means every completion effect (e.g. a balancer move's
                # drop-source) has been applied, not merely scheduled
                if callback is not None:
                    try:
                        callback(ok)
                    except Exception:
                        logging.getLogger(__name__).exception(
                            "completion callback for [%s %s] failed",
                            op, d.id)
                with self._lock:
                    self._pending.discard(d.id)
                    if not self._pending:
                        self._idle.set()

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until the queue drains (tests / graceful handover)."""
        return self._idle.wait(timeout)

    def stop(self, join: bool = True) -> None:
        self._stop.set()
        if join:
            self._worker.join(timeout=5.0)
