"""Binary wire format for the broker ↔ data-node data plane.

Reference analog: the serialized result stream a historical returns to
DirectDruidClient (client/DirectDruidClient.java:98 — JSON/smile rows over
Netty). TPU-first difference: what crosses the wire on the aggregate path is
*partial aggregation state* (AggregatePartials — dense per-key numpy arrays),
not finalized rows, so the broker's merge stays exact for HLL/sketch states.

Format ("tensor bundle", no pickle, nothing executable):

    MAGIC "DTPW" | u8 version | u32 header_len | header JSON | tensor bytes

The header describes the object tree; every numpy array is referenced by
index into a tensor table of (dtype, shape, offset) entries whose raw
little-endian bytes follow the header. Aggregator kernels travel as their
aggregator-spec JSON and are rebuilt against a null segment on the receiving
side — only their segment-independent merge behavior (combine / empty_state /
finalize) is exercised there.

Per-row device-staging arrays in GroupSpec (host_bucket_ids, host_keys) are
deliberately dropped from the wire: the broker merge needs only the compact
key space (host_unique), cardinalities, and bucket starts.
"""
from __future__ import annotations

import json
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = b"DTPW"
VERSION = 1
#: compressed tensor entries (enc: narrow/rle); emitted only when the
#: requester advertised support AND at least one tensor benefits, so a
#: version-1 peer never sees bytes it cannot parse
VERSION_COMPRESSED = 2

# HTTP content type for partials payloads (the data plane's "smile")
CONTENT_TYPE = "application/x-druid-tpu-partials"


class WireError(ValueError):
    pass


class WireStats:
    """Cumulative wire accounting: logical (raw little-endian) tensor bytes
    vs bytes actually emitted after per-tensor compression."""

    def __init__(self):
        self._lock = threading.Lock()
        self.logical_bytes = 0
        self.wire_bytes = 0
        self.compressed_payloads = 0

    def record(self, logical: int, wire: int, compressed: bool) -> None:
        with self._lock:
            self.logical_bytes += int(logical)
            self.wire_bytes += int(wire)
            if compressed:
                self.compressed_payloads += 1

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"logicalBytes": self.logical_bytes,
                    "wireBytes": self.wire_bytes,
                    "compressedPayloads": self.compressed_payloads}


_WIRE_STATS = WireStats()


def wire_stats() -> WireStats:
    return _WIRE_STATS


class WireStatsMonitor:
    """Emits query/wire/{bytes,compressedBytes} per tick (deltas over the
    tick window). Duck-typed Monitor — utils.emitter only requires
    do_monitor."""

    def __init__(self, source: Optional[WireStats] = None):
        self.source = source or _WIRE_STATS
        self._last = self.source.snapshot()

    def do_monitor(self, emitter):
        s = self.source.snapshot()
        last, self._last = self._last, s
        emitter.metric("query/wire/bytes",
                       s["logicalBytes"] - last["logicalBytes"])
        emitter.metric("query/wire/compressedBytes",
                       s["wireBytes"] - last["wireBytes"])


# ---------------------------------------------------------------------------
# Tensor table
# ---------------------------------------------------------------------------

class _TensorTable:
    def __init__(self):
        self.arrays: List[np.ndarray] = []

    def add(self, a: np.ndarray) -> int:
        self.arrays.append(np.ascontiguousarray(a))
        return len(self.arrays) - 1

    def add_opt(self, a: Optional[np.ndarray]) -> Optional[int]:
        return None if a is None else self.add(np.asarray(a))

    def manifest_and_payload(self, compress: bool = False
                             ) -> Tuple[List[dict], bytes, int]:
        """(manifest, payload, logical_bytes). With compress=True each
        tensor additionally tries the bit-exact wire encodings (_wire_enc)
        and ships the smallest form; entries then carry an "enc" key and
        the payload needs a VERSION_COMPRESSED reader."""
        manifest, chunks, off, logical = [], [], 0, 0
        for a in self.arrays:
            if a.dtype == object:
                raise WireError("object arrays are not wire-serializable")
            data = a.tobytes()
            logical += len(data)
            entry = {"dtype": a.dtype.str, "shape": list(a.shape)}
            if compress:
                enc = _wire_enc(a, len(data))
                if enc is not None:
                    entry.update(enc[0])
                    data = enc[1]
            entry["off"], entry["len"] = off, len(data)
            off += len(data)
            chunks.append(data)
            manifest.append(entry)
        return manifest, b"".join(chunks), logical


def _int_view_dtype(dt: np.dtype) -> Optional[np.dtype]:
    """Same-width integer view dtype for run comparison: floats compare as
    bit patterns so -0.0 vs 0.0 and NaN payloads survive the round trip
    EXACTLY (value comparison would merge/kill them)."""
    if dt.kind in ("i", "u"):
        return dt
    if dt.kind == "f" and dt.itemsize in (4, 8):
        return np.dtype(f"<i{dt.itemsize}")
    if dt.kind == "b":
        return np.dtype(np.uint8)
    return None


def _wire_enc(a: np.ndarray, raw_len: int
              ) -> Optional[Tuple[dict, bytes]]:
    """Best bit-exact wire encoding of `a`, or None to ship raw.

    "rle":    1-D run tables (values + int32 lengths) over the integer bit
              view — the dominant win for broker partials, whose per-key
              state arrays are mostly constant runs on RLE-friendly data.
    "narrow": integers recast to the smallest signed dtype holding
              min/max (counts and dictionary ids rarely need 8 bytes).
    """
    if a.size < 16:
        return None
    best: Optional[Tuple[dict, bytes]] = None

    vdt = _int_view_dtype(a.dtype)
    if vdt is not None and a.ndim == 1:
        v = a.view(vdt)
        changes = np.flatnonzero(v[1:] != v[:-1])
        n_runs = int(changes.shape[0]) + 1
        rle_len = n_runs * (vdt.itemsize + 4)
        if rle_len < raw_len:
            starts = np.concatenate([[0], changes + 1])
            values = v[starts]
            lengths = np.diff(np.concatenate(
                [starts, [v.shape[0]]])).astype(np.int32)
            best = ({"enc": "rle", "runs": n_runs, "vdtype": vdt.str},
                    values.tobytes() + lengths.tobytes())

    if a.dtype.kind in ("i", "u"):
        lo = int(a.min())
        hi = int(a.max())
        for sdt in (np.int8, np.int16, np.int32):
            d = np.dtype(sdt)
            if d.itemsize >= a.dtype.itemsize:
                break
            if np.iinfo(d).min <= lo and hi <= np.iinfo(d).max:
                nlen = a.size * d.itemsize
                if nlen < raw_len and (best is None
                                       or nlen < len(best[1])):
                    best = ({"enc": "narrow", "sdtype": d.str},
                            a.astype(d).tobytes())
                break
    return best


def _read_tensors(manifest: Sequence[dict], payload: memoryview
                  ) -> List[np.ndarray]:
    out = []
    for m in manifest:
        dt = np.dtype(m["dtype"])
        if dt == object or dt.hasobject:
            raise WireError("object dtype in wire payload")
        buf = payload[m["off"]: m["off"] + m["len"]]
        enc = m.get("enc")
        if enc == "rle":
            vdt = np.dtype(m["vdtype"])
            if vdt.hasobject:
                raise WireError("object dtype in wire payload")
            n_runs = int(m["runs"])
            split = n_runs * vdt.itemsize
            values = np.frombuffer(buf[:split], dtype=vdt)
            lengths = np.frombuffer(buf[split:], dtype=np.int32)
            if lengths.shape[0] != n_runs or int(lengths.sum()) < 0:
                raise WireError("malformed rle tensor entry")
            a = np.repeat(values, lengths).view(dt).reshape(m["shape"])
            out.append(a.copy())
        elif enc == "narrow":
            sdt = np.dtype(m["sdtype"])
            if sdt.hasobject:
                raise WireError("object dtype in wire payload")
            a = np.frombuffer(buf, dtype=sdt).astype(dt)
            out.append(a.reshape(m["shape"]))
        elif enc is None:
            out.append(np.frombuffer(buf, dtype=dt)
                       .reshape(m["shape"]).copy())
        else:
            raise WireError(f"unknown tensor encoding {enc!r}")
    return out


# ---------------------------------------------------------------------------
# State pytrees (numpy arrays or string-keyed dicts of arrays)
# ---------------------------------------------------------------------------

def _enc_state(x, tt: _TensorTable):
    if isinstance(x, np.ndarray):
        return {"a": tt.add(x)}
    if isinstance(x, dict):
        return {"d": {k: _enc_state(v, tt) for k, v in x.items()}}
    if isinstance(x, np.generic):
        return {"a": tt.add(np.asarray(x))}
    raise WireError(f"state leaf not serializable: {type(x).__name__}")


def _dec_state(x, tensors: List[np.ndarray]):
    if "a" in x:
        return tensors[x["a"]]
    return {k: _dec_state(v, tensors) for k, v in x["d"].items()}


# ---------------------------------------------------------------------------
# GroupSpec / kernels
# ---------------------------------------------------------------------------

def _enc_spec(spec, tt: _TensorTable) -> dict:
    return {
        "bucket_starts": tt.add(np.asarray(spec.bucket_starts)),
        "bucket_mode": spec.bucket_mode,
        "uniform_period": int(spec.uniform_period),
        "uniform_first_offset": int(spec.uniform_first_offset),
        "key_mode": spec.key_mode,
        "dims": [{"column": d.column, "cardinality": int(d.cardinality),
                  "remap": tt.add_opt(d.remap)} for d in spec.dims],
        "host_unique": tt.add_opt(spec.host_unique),
        "num_total": int(spec.num_total),
    }


def _dec_spec(j: dict, tensors: List[np.ndarray]):
    from druid_tpu.engine.grouping import GroupSpec, KeyDim
    t = lambda i: None if i is None else tensors[i]
    return GroupSpec(
        bucket_starts=t(j["bucket_starts"]),
        bucket_mode=j["bucket_mode"],
        uniform_period=j["uniform_period"],
        uniform_first_offset=j["uniform_first_offset"],
        host_bucket_ids=None,
        key_mode=j["key_mode"],
        dims=tuple(KeyDim(d["column"], d["cardinality"], t(d["remap"]))
                   for d in j["dims"]),
        host_keys=None,
        host_unique=t(j["host_unique"]),
        num_total=j["num_total"],
    )


class _NullSegment:
    """Segment stand-in for rebuilding kernels whose merge-side behavior
    (combine / empty_state / finalize_array) is segment-independent."""
    dims: Dict = {}
    metrics: Dict = {}

    def staged_dtype(self, name):
        return np.int64

    def aux_cached(self, key, fn):
        return fn()


_NULL_SEGMENT = _NullSegment()


def rebuild_kernels(agg_jsons: Sequence[dict]):
    """Kernels for the merge/finish side, from aggregator-spec JSON."""
    from druid_tpu.query import aggregators as A
    from druid_tpu.engine.filters import ConstNode
    from druid_tpu.engine.kernels import FilteredKernel, make_kernel

    def one(spec):
        if isinstance(spec, A.FilteredAggregator):
            # the filter only gates update(); merge-side it is inert
            return FilteredKernel(spec, one(spec.delegate), ConstNode(True))
        return make_kernel(spec, _NULL_SEGMENT)

    return [one(A.agg_from_json(j)) for j in agg_jsons]


# ---------------------------------------------------------------------------
# AggregatePartials
# ---------------------------------------------------------------------------

def dumps_partials(ap, served: Sequence[str] = (),
                   trace: Sequence[dict] = (),
                   missing: Sequence[str] = (),
                   compress: bool = False) -> bytes:
    """Serialize AggregatePartials (+ the served-segment-id set the node is
    acknowledging, and the node's finished trace spans — plain JSON dicts —
    so the broker can assemble one end-to-end trace per query; both ride in
    the same payload). `missing` makes the partial-result contract explicit
    on the wire: segment ids the node was ASKED for but could not serve —
    the broker's degradation report composes from these, and a
    broker-of-brokers tier can propagate them without re-deriving the
    requested set.

    compress=True enables the bit-exact per-tensor wire encodings; emit
    it only for peers that advertised support ("wireCompress") — the
    payload then carries wire version 2 when any tensor benefits."""
    tt = _TensorTable()
    partials = []
    for p in ap.partials:
        partials.append({
            "spec": _enc_spec(p.spec, tt),
            "counts": tt.add(np.asarray(p.counts)),
            "states": {k: _enc_state(v, tt) for k, v in p.states.items()},
            "aggs": [k.spec.to_json() for k in p.kernels],
        })
    header = {
        "partials": partials,
        "dim_values": ap.dim_values,
        "spans": [[int(a), int(b)] for a, b in ap.spans],
        "intervals": None if ap.intervals is None
        else [[iv.start, iv.end] for iv in ap.intervals],
        "served": sorted(served),
        "missing": sorted(str(s) for s in missing),
        "trace": list(trace),
    }
    manifest, payload, logical = tt.manifest_and_payload(compress=compress)
    header["tensors"] = manifest
    hj = json.dumps(header).encode()
    any_enc = any("enc" in m for m in manifest)
    version = VERSION_COMPRESSED if any_enc else VERSION
    body = MAGIC + struct.pack("<BI", version, len(hj)) + hj + payload
    _WIRE_STATS.record(logical, len(payload), any_enc)
    return body


class PartialsPayload(tuple):
    """The decoded partials bundle: unpacks as the 3-tuple
    (AggregatePartials, served ids, trace spans) every existing caller
    expects, with the explicit partial-result report as `.missing`
    (segment ids the node was asked for but could not serve; empty on a
    complete response or a pre-missing-field peer)."""

    def __new__(cls, ap, served, spans, missing=()):
        self = super().__new__(cls, (ap, served, spans))
        self.missing = sorted({str(s) for s in missing})
        return self


def loads_partials(data: bytes):
    """Returns a PartialsPayload — unpackable as
    (AggregatePartials, served_segment_ids, trace_spans)."""
    from druid_tpu.engine.engines import AggregatePartials
    from druid_tpu.engine.grouping import SegmentPartial
    from druid_tpu.utils.intervals import Interval

    mv = memoryview(data)
    if bytes(mv[:4]) != MAGIC:
        raise WireError("bad magic")
    version, hlen = struct.unpack("<BI", mv[4:9])
    if version not in (VERSION, VERSION_COMPRESSED):
        raise WireError(f"unsupported wire version {version}")
    header = json.loads(bytes(mv[9: 9 + hlen]))
    tensors = _read_tensors(header["tensors"], mv[9 + hlen:])

    partials = []
    for pj in header["partials"]:
        kernels = rebuild_kernels(pj["aggs"])
        partials.append(SegmentPartial(
            segment=None,
            spec=_dec_spec(pj["spec"], tensors),
            counts=tensors[pj["counts"]],
            states={k: _dec_state(v, tensors)
                    for k, v in pj["states"].items()},
            kernels=kernels))
    intervals = header["intervals"]
    ap = AggregatePartials(
        partials=partials,
        dim_values=header["dim_values"],
        spans=[tuple(s) for s in header["spans"]],
        intervals=None if intervals is None
        else tuple(Interval(a, b) for a, b in intervals))
    return PartialsPayload(ap, set(header["served"]),
                           list(header.get("trace") or ()),
                           missing=header.get("missing") or ())
