"""Data nodes and the broker's cluster view.

Reference analogs:
  DataNode       — historical process: ServerManager (server/coordination/
                   ServerManager.java:74 — per-segment query serving) +
                   SegmentLoadDropHandler (load/drop lifecycle) +
                   SegmentManager (local timeline of loaded segments).
  InventoryView  — BrokerServerView (client/BrokerServerView.java:57) +
                   HttpServerInventoryView: the broker's live map of which
                   server holds which segment, maintained via announcements
                   (here: direct callbacks standing in for ZK/HTTP sync),
                   building per-datasource VersionedIntervalTimeline whose
                   payloads are replica sets (ServerSelector analog).

The node boundary (run_partials / run_rows) is in-process here; a real
multi-host deployment serializes AggregatePartials' numpy states over the
wire — shapes and dtypes are all plain host arrays by construction.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from druid_tpu.cluster.cache import CacheConfig, LruCache, query_cache_key
from druid_tpu.cluster.metadata import SegmentDescriptor
from druid_tpu.cluster.shardspec import NoneShardSpec
from druid_tpu.cluster.timeline import (PartitionChunk,
                                        VersionedIntervalTimeline)
from druid_tpu.data.segment import Segment
from druid_tpu.engine import engines
from druid_tpu.engine.engines import AggregatePartials, make_aggregate_partials
from druid_tpu.query.model import (GroupByQuery, Query, TimeseriesQuery,
                                   TopNQuery)
from druid_tpu.utils.intervals import Interval

log = logging.getLogger(__name__)


def descriptor_for(segment: Segment,
                   shard_spec=None) -> SegmentDescriptor:
    """Pass the real shard spec for multi-partition sets (numbered/hashed) —
    the timeline's completeness check depends on it. The defaults (none for
    partition 0, linear otherwise) are always-complete append semantics."""
    from druid_tpu.cluster.shardspec import LinearShardSpec
    if shard_spec is None:
        shard_spec = NoneShardSpec(0) if segment.id.partition == 0 \
            else LinearShardSpec(segment.id.partition)
    return SegmentDescriptor(
        segment.id.datasource, segment.id.interval, segment.id.version,
        segment.id.partition, shard_spec, num_rows=segment.n_rows)


def _is_aggregate(query: Query) -> bool:
    return isinstance(query, (TimeseriesQuery, TopNQuery, GroupByQuery))


class DataNode:
    """One data server: loaded segments + the per-node query engine."""

    #: results from this server may be cached and the coordinator may manage
    #: its segments (False on realtime servers whose sinks mutate in place)
    segment_replicatable = True

    def __init__(self, name: str, tier: str = "_default_tier",
                 max_segments: Optional[int] = None,
                 cache: Optional[LruCache] = None,
                 cache_config: Optional[CacheConfig] = None,
                 mesh=None, emitter=None, per_segment_metrics: bool = False):
        """emitter: optional ServiceEmitter — per-segment query metrics
        (query/segment/time, query/segmentAndCache/time, query/cpu/time)
        emit here, the MetricsEmittingQueryRunner layer of the reference.
        per_segment_metrics=True additionally runs the uncached path
        segment-by-segment so each gets its own timing — an observability/
        throughput trade (the fused multi-segment program is faster); off,
        fused executions emit ONE aggregate timing."""
        self.name = name
        self.tier = tier
        self.max_segments = max_segments
        self.cache = cache
        self.cache_config = cache_config or CacheConfig()
        self.mesh = mesh
        self.emitter = emitter
        self.per_segment_metrics = per_segment_metrics
        self._segments: Dict[str, Segment] = {}
        self._descriptors: Dict[str, SegmentDescriptor] = {}
        self._lock = threading.RLock()
        self.alive = True

    def _emit_segment(self, query, segment_id: str, wall_ms: float,
                      cpu_ms: float, cached: bool) -> None:
        if self.emitter is None:
            return
        qid = query.context_map.get("queryId", "")
        dims = dict(dataSource=query.datasource, type=query.query_type,
                    id=qid, segment=str(segment_id), server=self.name)
        if not cached:
            self.emitter.metric("query/segment/time", wall_ms, **dims)
            self.emitter.metric("query/cpu/time", cpu_ms, **dims)
        self.emitter.metric("query/segmentAndCache/time", wall_ms, **dims)

    # ---- load/drop (SegmentLoadDropHandler analog) ---------------------
    def load_segment(self, segment: Segment,
                     descriptor: Optional[SegmentDescriptor] = None) -> bool:
        """`descriptor` (when the loader has it) preserves the REAL shard
        spec for /status inventory listings — descriptor_for can only
        reconstruct default specs, and the timeline completeness check
        depends on the real one."""
        with self._lock:
            if self.max_segments is not None \
                    and len(self._segments) >= self.max_segments \
                    and str(segment.id) not in self._segments:
                return False
            self._segments[str(segment.id)] = segment
            if descriptor is not None:
                self._descriptors[str(segment.id)] = descriptor
            return True

    def drop_segment(self, segment_id: str) -> bool:
        with self._lock:
            self._descriptors.pop(str(segment_id), None)
            return self._segments.pop(str(segment_id), None) is not None

    def served_descriptors(self) -> List[SegmentDescriptor]:
        """Descriptors for every served segment — stored ones (real shard
        specs) where known, reconstructed defaults otherwise."""
        with self._lock:
            return [self._descriptors.get(sid) or descriptor_for(s)
                    for sid, s in self._segments.items()]

    def served_segment_ids(self) -> Set[str]:
        with self._lock:
            return set(self._segments)

    def ping(self) -> bool:
        """Liveness probe (the heartbeat a ZK ephemeral node implies)."""
        return self.alive

    def segment_count(self) -> int:
        with self._lock:
            return len(self._segments)

    def segments(self) -> List[Segment]:
        with self._lock:
            return list(self._segments.values())

    # ---- query serving (ServerManager analog) --------------------------
    def _select(self, segment_ids: Sequence[str]) -> Tuple[List[Segment], Set[str]]:
        with self._lock:
            found, served = [], set()
            for sid in segment_ids:
                s = self._segments.get(str(sid))
                if s is not None:
                    found.append(s)
                    served.add(str(sid))
            return found, served

    def run_partials(self, query: Query, segment_ids: Sequence[str],
                     check: Optional[Callable[[], None]] = None
                     ) -> Tuple[AggregatePartials, Set[str]]:
        """Aggregate path: produce partial states for the requested segments
        (clamp=False — the broker pre-bounds intervals so bucket index
        spaces align across nodes). Per-segment partials are cached when the
        segment cache is enabled (CachingQueryRunner analog).

        `check` (cancel/timeout probe) runs at every dispatch boundary —
        between per-segment programs, between batched shape-bucket
        dispatches, before the single sharded program (the engine threads
        it through make_aggregate_partials); an individual device program
        is uninterruptible once launched."""
        if not self.alive:
            raise ConnectionError(f"server [{self.name}] is down")
        segs, served = self._select(segment_ids)
        use_cache = self._segment_cache_active(query)
        if not use_cache:
            if not (self.emitter is not None and self.per_segment_metrics) \
                    or self.mesh is not None or len(segs) <= 1:
                t0, c0 = time.monotonic(), time.thread_time()
                ap = make_aggregate_partials(query, segs, clamp=False,
                                             check=check)
                if segs:
                    # fused/mesh/batched execution: one timing over the set
                    self._emit_segment(
                        query, f"{len(segs)}-segments",
                        (time.monotonic() - t0) * 1e3,
                        (time.thread_time() - c0) * 1e3, cached=False)
                if check is not None:
                    check()
            else:
                parts = []
                for s in segs:
                    if check is not None:
                        check()
                    t0, c0 = time.monotonic(), time.thread_time()
                    parts.append(
                        make_aggregate_partials(query, [s], clamp=False))
                    self._emit_segment(query, s.id,
                                       (time.monotonic() - t0) * 1e3,
                                       (time.thread_time() - c0) * 1e3,
                                       cached=False)
                ap = AggregatePartials.concat(parts)
            return ap, served
        qkey, parts, to_compute = self._cache_scan(query, segs)
        if to_compute and (self.mesh is not None
                           or (self.emitter is not None
                               and self.per_segment_metrics)):
            # mesh: the sharded program may FUSE the miss set into one
            # merged partial that cannot split back into per-segment cache
            # entries — keep the per-miss loop (as the uncached path does).
            # per_segment_metrics: observability trade, per-segment
            # timings require per-segment dispatches
            for s in to_compute:
                if check is not None:
                    check()
                t0, c0 = time.monotonic(), time.thread_time()
                ap = make_aggregate_partials(query, [s], clamp=False)
                self._emit_segment(query, s.id,
                                   (time.monotonic() - t0) * 1e3,
                                   (time.thread_time() - c0) * 1e3,
                                   cached=False)
                self._cache_put(qkey, [(s, ap)])
                parts.append(ap)
        elif to_compute:
            # the whole miss set in ONE wave: shape-compatible misses fuse
            # into batched dispatches (engine/batching.py) instead of one
            # device program per miss; the per-segment partials come back
            # split, so cache entries stay identical to the per-miss path
            from druid_tpu.engine.engines import make_partials_by_segment
            if check is not None:
                check()
            t0, c0 = time.monotonic(), time.thread_time()
            per_seg = make_partials_by_segment(query, to_compute,
                                               clamp=False, check=check)
            self._emit_segment(query, f"{len(to_compute)}-segment-misses",
                               (time.monotonic() - t0) * 1e3,
                               (time.thread_time() - c0) * 1e3,
                               cached=False)
            self._cache_put(qkey, zip(to_compute, per_seg))
            parts.extend(per_seg)
        return AggregatePartials.concat(parts), served

    def _cache_scan(self, query: Query, segs: Sequence[Segment]
                    ) -> Tuple[str, List[AggregatePartials], List[Segment]]:
        """(qkey, hit partials, miss segments): the timed per-segment cache
        scan — THE one hit/miss discipline; run_partials (request thread)
        and run_partials_group (scheduler flush) both use it, so cache
        semantics cannot diverge between the two execution paths."""
        qkey = query_cache_key(query)
        hit_parts: List[AggregatePartials] = []
        to_compute: List[Segment] = []
        for s in segs:
            t0 = time.monotonic()
            hit = self.cache.get("segment", f"{s.id}|{qkey}")
            if hit is not None:
                hit_parts.append(hit)
                self._emit_segment(query, s.id,
                                   (time.monotonic() - t0) * 1e3, 0.0,
                                   cached=True)
            else:
                to_compute.append(s)
        return qkey, hit_parts, to_compute

    def _cache_put(self, qkey: str, pairs) -> None:
        """Populate per-segment cache entries (gated on the config), the
        counterpart of _cache_scan shared by both serving paths."""
        if not self.cache_config.populate_segment_cache:
            return
        for s, ap in pairs:
            self.cache.put("segment", f"{s.id}|{qkey}", ap)

    def _segment_cache_active(self, query: Query) -> bool:
        """Whether the per-segment results cache takes this query — the
        ONE eligibility condition run_partials and run_partials_group must
        agree on (a fused request must never bypass cache population the
        serial path would have done)."""
        return (self.cache is not None
                and self.cache_config.cacheable(query)
                and self.cache_config.use_segment_cache)

    def fusable(self, query: Query) -> bool:
        """Whether run_partials_group would FUSE this query with its
        flush-mates. Work this node cannot fuse — mesh execution,
        per-segment metrics, non-aggregate queries, batching opted out
        (process switch or {"batchSegments": false}) — gains nothing from
        the scheduler hold and would serialize on the single dispatcher
        thread; DataNodeServer routes it straight to run_partials on the
        request thread instead.

        Segment-cache-active queries DO fuse (PR 7 follow-on closed):
        run_partials_group resolves cache hits inline during the flush and
        sends only the MISS set into the fused wave, splitting the results
        back into per-segment cache entries — a hot datasource's cached
        queries no longer serialize per-query inside a flush."""
        from druid_tpu.engine import batching
        return (_is_aggregate(query) and self.mesh is None
                and batching.query_enabled(query.context_map)
                and not (self.emitter is not None
                         and self.per_segment_metrics))

    def run_partials_group(self, requests, on_batch=None) -> List[object]:
        """Cross-query serving: one call for a whole scheduler flush.
        `requests` is a sequence of (query, segment_ids, check) triples;
        returns one entry per request — (AggregatePartials, served) or the
        Exception that request failed with (one query's cancel/timeout
        must not fail its flush-mates).

        Plan-compatible segment work FUSES across the requests into shared
        device dispatches (engines.make_aggregate_partials_multi). Requests
        this node cannot fuse (see `fusable`) normally never reach the
        scheduler — DataNodeServer runs them on the request thread — but
        any that slip through run via the normal run_partials path, so
        semantics (cache population, per-segment metrics) stay identical.
        `on_batch` observes each fused dispatch (query/crossBatch/*)."""
        if not self.alive:
            err = ConnectionError(f"server [{self.name}] is down")
            return [err for _ in requests]
        fused_idx: List[int] = []
        fused_items = []        # ((query, segs, check), (served, cache_meta))
        out: List[object] = [None] * len(requests)
        for i, (query, segment_ids, check) in enumerate(requests):
            if not self.fusable(query):
                # robustness backstop — DataNodeServer bypasses the
                # scheduler for non-fusable work, so this only fires when
                # eligibility changed between admission and flush
                try:
                    out[i] = self.run_partials(query, segment_ids,
                                               check=check)
                except Exception as e:
                    out[i] = e
                continue
            segs, served = self._select(segment_ids)
            if self._segment_cache_active(query):
                # cache hits resolve INSIDE the flush (no device work, no
                # per-query routing); only the miss set joins the fused
                # wave, and its results split back into per-segment cache
                # entries identical to the serial path's (the scan/put
                # discipline is _cache_scan/_cache_put — shared with
                # run_partials, so the two paths cannot drift)
                qkey, hit_parts, to_compute = self._cache_scan(query, segs)
                if not to_compute:
                    # the hot-datasource shape: a fully-cached query costs
                    # the flush nothing at all
                    out[i] = (AggregatePartials.concat(hit_parts), served)
                    continue
                fused_idx.append(i)
                fused_items.append(((query, to_compute, check),
                                    (served, (hit_parts, to_compute, qkey))))
            else:
                fused_idx.append(i)
                fused_items.append(((query, segs, check), (served, None)))
        if fused_items:
            t0, c0 = time.monotonic(), time.thread_time()
            results = engines.make_aggregate_partials_multi(
                [item for item, _ in fused_items], on_batch=on_batch)
            wall_ms = (time.monotonic() - t0) * 1e3
            cpu_ms = (time.thread_time() - c0) * 1e3
            for i, got, ((query, segs, _), (served, cache_meta)) \
                    in zip(fused_idx, results, fused_items):
                if isinstance(got, BaseException):
                    out[i] = got
                    continue
                if cache_meta is None:
                    if segs:
                        # one fused timing per request, as run_partials
                        # emits for a batched set — the flush is shared,
                        # so the wall/cpu cost is the whole group's, not
                        # this query's alone
                        self._emit_segment(query, f"{len(segs)}-segments",
                                           wall_ms, cpu_ms, cached=False)
                    out[i] = (got, served)
                    continue
                hit_parts, to_compute, qkey = cache_meta
                per_seg = engines.split_partials_by_segment(got, to_compute)
                self._cache_put(qkey, zip(to_compute, per_seg))
                self._emit_segment(query,
                                   f"{len(to_compute)}-segment-misses",
                                   wall_ms, cpu_ms, cached=False)
                # hit parts first, computed parts after — the same order
                # run_partials' cached path concatenates in
                out[i] = (AggregatePartials.concat(hit_parts + per_seg),
                          served)
        return out

    def run_rows(self, query: Query, segment_ids: Sequence[str]
                 ) -> Tuple[List[dict], Set[str]]:
        """Row path (scan/select/search/timeBoundary/metadata queries):
        run the local engine to finished rows; the broker row-merges."""
        if not self.alive:
            raise ConnectionError(f"server [{self.name}] is down")
        segs, served = self._select(segment_ids)
        from druid_tpu.engine.executor import QueryExecutor
        ex = QueryExecutor(mesh=self.mesh)
        rows = ex.run(query, segments=segs)
        return rows, served


class ServerSelectorStrategy:
    """Replica-choice SPI (client/selector/ServerSelectorStrategy.java +
    TierSelectorStrategy): given candidate server names, pick one."""

    def pick(self, candidates: List[str], view: Optional["InventoryView"],
             rng: random.Random) -> str:
        raise NotImplementedError


class RandomServerSelectorStrategy(ServerSelectorStrategy):
    def pick(self, candidates, view, rng):
        return candidates[rng.randrange(len(candidates))]


class ConnectionCountServerSelectorStrategy(ServerSelectorStrategy):
    """Least-loaded replica by open query count
    (client/selector/ConnectionCountServerSelectorStrategy.java); the view
    tracks in-flight queries per server. Ties break RANDOMLY — on an idle
    cluster every replica shows zero connections and a deterministic
    tie-break would route everything to one server."""

    def pick(self, candidates, view, rng):
        if view is None:
            return candidates[rng.randrange(len(candidates))]
        loads = [(view.open_connections(s), s) for s in candidates]
        lo = min(l for l, _ in loads)
        pool = [s for l, s in loads if l == lo]
        return pool[rng.randrange(len(pool))]


class TierPreferenceStrategy(ServerSelectorStrategy):
    """Prefer replicas on the listed tiers in order (Highest/Lowest
    PriorityTierSelectorStrategy capability), falling back to `delegate`
    within the chosen tier."""

    def __init__(self, preferred_tiers: Sequence[str],
                 delegate: Optional[ServerSelectorStrategy] = None):
        self.preferred_tiers = list(preferred_tiers)
        self.delegate = delegate or RandomServerSelectorStrategy()

    def pick(self, candidates, view, rng):
        if view is not None:
            by_tier: Dict[str, List[str]] = {}
            for s in candidates:
                node = view.node(s)
                by_tier.setdefault(
                    getattr(node, "tier", "_default_tier"), []).append(s)
            for tier in self.preferred_tiers:
                if by_tier.get(tier):
                    return self.delegate.pick(by_tier[tier], view, rng)
        return self.delegate.pick(candidates, view, rng)


class ReplicaSet:
    """Which servers hold one segment chunk (ServerSelector analog);
    pick() delegates to the configured ServerSelectorStrategy
    (client/selector/TierSelectorStrategy.java)."""

    def __init__(self, descriptor: SegmentDescriptor):
        self.descriptor = descriptor
        self.servers: Set[str] = set()
        #: per-server announce sequence (sync_server stale-round guard)
        self.server_seq: Dict[str, int] = {}

    def pick(self, rng: random.Random,
             exclude: Optional[Set[str]] = None,
             strategy: Optional[ServerSelectorStrategy] = None,
             view: Optional["InventoryView"] = None,
             circuits=None) -> Optional[str]:
        """`circuits` (resilience.CircuitRegistry): selection NEVER
        returns an excluded server, and skips open-circuit servers that
        are still cooling down. A cooled-down open server rejoins the
        pool as the half-open PROBE candidate (picking it routes exactly
        one query through and tags it via begin_probe — without this, a
        sick server could never recover while a healthy replica keeps
        absorbing the traffic). Only when EVERY candidate is open-and-
        uncooled does selection fall back to an open server anyway,
        tagged as a probe: a guaranteed no-replica failure is worse than
        one fail-fast attempt on a sick server."""
        pool = sorted(self.servers - (exclude or set()))
        if not pool:
            return None
        probe_set: Set[str] = set()
        if circuits is not None:
            closed = [s for s in pool if circuits.closed(s)]
            cooled = [s for s in pool if circuits.probe_candidate(s)]
            if closed or cooled:
                pool = sorted(closed + cooled)
                probe_set = set(cooled)
            else:
                probe_set = set(pool)      # all-open last resort
        if strategy is None:
            chosen = pool[rng.randrange(len(pool))]
        else:
            chosen = strategy.pick(pool, view, rng)
        if chosen in probe_set:
            circuits.begin_probe(chosen)
        return chosen


class InventoryView:
    """The live cluster map: node registry + per-datasource timelines whose
    payloads are ReplicaSets. Announcements are direct method calls (the
    in-process stand-in for ZK ephemeral nodes / HTTP sync)."""

    def __init__(self):
        self._nodes: Dict[str, DataNode] = {}
        self._timelines: Dict[str, VersionedIntervalTimeline] = {}
        self._replicas: Dict[str, ReplicaSet] = {}   # segment id → replicas
        self._probe_failures: Dict[str, int] = {}    # consecutive ping fails
        self._connections: Dict[str, int] = {}       # in-flight per server
        self._capacity_sheds: Dict[str, int] = {}    # cumulative 429s seen
        self._latency_ewma: Dict[str, float] = {}    # per-server ms EWMA
        self._announce_seq = 0                       # monotonic, under lock
        self._lock = threading.RLock()
        self._listeners: List[Callable[[str, str, str], None]] = []

    # ---- capacity-shed accounting (broker lane-aware retry) ------------
    def note_capacity_shed(self, server: str) -> None:
        """A data node answered 429 for a query wave. The broker records it
        here before retrying the segment set on ONE other replica, so
        operators can see per-server shed pressure alongside connection
        counts."""
        with self._lock:
            self._capacity_sheds[server] = \
                self._capacity_sheds.get(server, 0) + 1

    def capacity_sheds(self, server: str) -> int:
        with self._lock:
            return self._capacity_sheds.get(server, 0)

    # ---- latency accounting (hedged-request delay input) ---------------
    def note_latency(self, server: str, wall_ms: float,
                     alpha: float = 0.2) -> None:
        """Feed one broker/node response time into the server's latency
        EWMA — the broker reports every successful scatter call here, and
        the hedge delay derives from the estimate (resilience.
        BrokerResilience.hedge_delay_s)."""
        with self._lock:
            prev = self._latency_ewma.get(server)
            self._latency_ewma[server] = wall_ms if prev is None \
                else alpha * wall_ms + (1.0 - alpha) * prev

    def latency_ms(self, server: str) -> Optional[float]:
        with self._lock:
            return self._latency_ewma.get(server)

    # ---- in-flight accounting (ConnectionCount strategy input) ---------
    def connection_started(self, server: str) -> None:
        with self._lock:
            self._connections[server] = self._connections.get(server, 0) + 1

    def connection_finished(self, server: str) -> None:
        with self._lock:
            n = self._connections.get(server, 0) - 1
            if n <= 0:
                self._connections.pop(server, None)
            else:
                self._connections[server] = n

    def open_connections(self, server: str) -> int:
        with self._lock:
            return self._connections.get(server, 0)

    # ---- node lifecycle ------------------------------------------------
    def register(self, node: DataNode) -> None:
        with self._lock:
            self._nodes[node.name] = node

    def remove_node(self, name: str) -> None:
        """Server death: drop it from every replica set instantly; segments
        it was the last holder of leave the timeline (the broker's reaction
        to a ZK ephemeral node vanishing)."""
        with self._lock:
            node = self._nodes.pop(name, None)
            if node is None:
                return
            orphaned = []
            for sid, rs in self._replicas.items():
                rs.servers.discard(name)
                if not rs.servers:
                    orphaned.append(sid)
            for sid in orphaned:
                d = self._replicas.pop(sid).descriptor
                tl = self._timelines.get(d.datasource)
                if tl is not None:
                    tl.remove(d.interval, d.version,
                              d.shard_spec.partition_num if d.shard_spec
                              else d.partition)

    def node(self, name: str) -> Optional[DataNode]:
        with self._lock:
            return self._nodes.get(name)

    def nodes(self) -> List[DataNode]:
        with self._lock:
            return list(self._nodes.values())

    def sync_server(self, node) -> Tuple[int, int]:
        """One inventory-sync round for a node exposing
        served_descriptors() (RemoteDataNodeClient): announce segments the
        node now serves, unannounce ones it no longer does — the poll loop
        of HttpServerInventoryView, replacing hand-registration. Returns
        (announced, unannounced)."""
        with self._lock:
            fetch_seq = self._announce_seq
        descs = node.served_descriptors() \
            if hasattr(node, "served_descriptors") else \
            [descriptor_for(s) for s in node.segments()]
        current = {d.id: d for d in descs}
        added = removed = 0
        with self._lock:
            known = {sid: rs for sid, rs in self._replicas.items()
                     if node.name in rs.servers}
            for sid, d in current.items():
                if sid not in known:
                    self.announce(node.name, d)
                    added += 1
            for sid, rs in known.items():
                if sid in current:
                    continue
                # an announce NEWER than our /status fetch (e.g. a load
                # peon finishing mid-sync) must not be reverted by this
                # round's stale snapshot
                if rs.server_seq.get(node.name, 0) > fetch_seq:
                    continue
                self.unannounce(node.name, sid)
                removed += 1
        return added, removed

    def sync_all(self) -> Tuple[int, int]:
        """Sync every registered node (the periodic inventory refresh)."""
        a = r = 0
        for node in self.nodes():
            try:
                da, dr = self.sync_server(node)
                a += da
                r += dr
            except Exception:
                # liveness handles dead nodes; keep syncing the rest
                log.debug("inventory sync for [%s] failed", node.name,
                          exc_info=True)
                continue
        return a, r

    def check_liveness(self, failures_required: int = 1) -> List[str]:
        """Probe every node (concurrently — a dead remote must not stall
        the cycle by its timeout) and drop the dead ones from the view: the
        stand-in for ZK ephemeral-node expiry (curator/announcement/
        Announcer.java). Removal retracts all of the server's announcements,
        so brokers stop routing to it and the coordinator's rule run sees
        the replica deficit and re-replicates.

        failures_required > 1 adds a grace period: a node is removed only
        after that many CONSECUTIVE failed cycles (ZK's session timeout is
        likewise multiple missed heartbeats, not one). Transient-blip
        tolerance also lives in RemoteDataNodeClient.ping (one in-call
        retry). A recovered node re-registers + re-announces to rejoin."""
        from concurrent.futures import ThreadPoolExecutor
        nodes = self.nodes()
        if not nodes:
            return []

        def probe(node) -> bool:
            try:
                ping = getattr(node, "ping", None)
                return bool(ping()) if callable(ping) \
                    else bool(getattr(node, "alive", True))
            except Exception:
                log.debug("liveness probe for [%s] raised", node.name,
                          exc_info=True)
                return False

        with ThreadPoolExecutor(max_workers=min(len(nodes), 16)) as pool:
            results = list(pool.map(probe, nodes))
        dead = []
        with self._lock:
            for node, ok in zip(nodes, results):
                if ok:
                    self._probe_failures.pop(node.name, None)
                    continue
                n = self._probe_failures.get(node.name, 0) + 1
                self._probe_failures[node.name] = n
                if n >= failures_required:
                    dead.append(node.name)
                    del self._probe_failures[node.name]
        for name in dead:
            self.remove_node(name)
        return dead

    # ---- announcements -------------------------------------------------
    def announce(self, server: str, descriptor: SegmentDescriptor) -> None:
        with self._lock:
            sid = descriptor.id
            rs = self._replicas.get(sid)
            if rs is None:
                rs = self._replicas[sid] = ReplicaSet(descriptor)
                tl = self._timelines.setdefault(
                    descriptor.datasource, VersionedIntervalTimeline())
                spec = descriptor.shard_spec or NoneShardSpec(descriptor.partition)
                tl.add(descriptor.interval, descriptor.version,
                       PartitionChunk(spec, rs))
            rs.servers.add(server)
            self._announce_seq += 1
            rs.server_seq[server] = self._announce_seq
        for fn in list(self._listeners):
            fn("announce", server, sid)

    def unannounce(self, server: str, segment_id: str) -> None:
        with self._lock:
            rs = self._replicas.get(segment_id)
            if rs is None:
                return
            rs.servers.discard(server)
            rs.server_seq.pop(server, None)
            if not rs.servers:
                d = rs.descriptor
                tl = self._timelines.get(d.datasource)
                if tl is not None:
                    tl.remove(d.interval, d.version,
                              d.shard_spec.partition_num if d.shard_spec
                              else d.partition)
                del self._replicas[segment_id]
        for fn in list(self._listeners):
            fn("unannounce", server, segment_id)

    def add_listener(self, fn: Callable[[str, str, str], None]) -> None:
        self._listeners.append(fn)

    # ---- lookup ---------------------------------------------------------
    def timeline(self, datasource: str) -> Optional[VersionedIntervalTimeline]:
        with self._lock:
            return self._timelines.get(datasource)

    def datasources(self) -> List[str]:
        with self._lock:
            return sorted(ds for ds, tl in self._timelines.items()
                          if not tl.is_empty())

    def replica_set(self, segment_id: str) -> Optional[ReplicaSet]:
        with self._lock:
            return self._replicas.get(segment_id)

    def served_segments(self, server: str) -> List[SegmentDescriptor]:
        with self._lock:
            return [rs.descriptor for rs in self._replicas.values()
                    if server in rs.servers]
