"""SQL metadata store (sqlite) — the cluster's source of truth.

Reference analogs:
  segments table + transactional publish — server/src/main/java/org/apache/
    druid/metadata/IndexerSQLMetadataStorageCoordinator.java (announceHistorical
    Segments with dataSource-metadata compare-and-swap = exactly-once streaming
    publish), MetadataSegmentManager.java (used-segment polling)
  rules table — metadata/MetadataRuleManager.java
  audit — server/audit/SQLAuditManager.java

Segments are stored as JSON descriptors (DataSegment analog); payload columns
keep (datasource, start, end, version, partition, used) queryable. The
datasource metadata CAS is the exactly-once hook used by streaming ingestion
(§3.4: offsets and segments commit in one transaction).
"""
from __future__ import annotations

import json
import sqlite3
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from druid_tpu.cluster.shardspec import NoneShardSpec, ShardSpec, shardspec_from_json
from druid_tpu.utils.intervals import Interval, ts_to_iso


class SegmentAllocationError(RuntimeError):
    """Allocation refused: the bucket conflicts with differently-aligned
    committed segments (SegmentAllocateAction returns null there)."""


@dataclass(frozen=True)
class SegmentDescriptor:
    """DataSegment analog (api/.../timeline/DataSegment.java): identity +
    shard spec + size/location metadata, without the column data."""
    datasource: str
    interval: Interval
    version: str
    partition: int = 0
    shard_spec: Optional[ShardSpec] = None
    size_bytes: int = 0
    num_rows: int = 0
    load_spec: Optional[dict] = None   # where the segment file lives

    @property
    def id(self) -> str:
        return (f"{self.datasource}_{self.interval}_{self.version}"
                f"_{self.partition}")

    def to_json(self) -> dict:
        return {"dataSource": self.datasource, "interval": str(self.interval),
                "version": self.version,
                "shardSpec": (self.shard_spec.to_json() if self.shard_spec
                              else {"type": "numbered",
                                    "partitionNum": self.partition,
                                    "partitions": 0}),
                "size": self.size_bytes, "numRows": self.num_rows,
                "loadSpec": self.load_spec}

    @staticmethod
    def from_json(j: dict) -> "SegmentDescriptor":
        spec = shardspec_from_json(j.get("shardSpec"))
        return SegmentDescriptor(
            j["dataSource"], Interval.parse(j["interval"]), j["version"],
            getattr(spec, "partition_num", 0), spec,
            j.get("size", 0), j.get("numRows", 0), j.get("loadSpec"))


class MetadataStore:
    """sqlite-backed metadata store; ':memory:' for tests, a file path for
    durability. Thread-safe via one connection + lock (sqlite serializes
    writers anyway; the reference uses JDBI connection pools)."""

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        self._create_tables()

    def _create_tables(self):
        with self._lock, self._conn as c:
            c.executescript("""
            CREATE TABLE IF NOT EXISTS segments (
              id TEXT PRIMARY KEY, datasource TEXT NOT NULL,
              start INTEGER NOT NULL, end INTEGER NOT NULL,
              version TEXT NOT NULL, partition_num INTEGER NOT NULL,
              used INTEGER NOT NULL DEFAULT 1,
              created_ms INTEGER NOT NULL, payload TEXT NOT NULL);
            CREATE INDEX IF NOT EXISTS idx_segments_ds
              ON segments(datasource, used);
            CREATE TABLE IF NOT EXISTS datasource_metadata (
              datasource TEXT PRIMARY KEY, commit_metadata TEXT NOT NULL);
            CREATE TABLE IF NOT EXISTS rules (
              datasource TEXT PRIMARY KEY, payload TEXT NOT NULL,
              updated_ms INTEGER NOT NULL);
            CREATE TABLE IF NOT EXISTS config (
              name TEXT PRIMARY KEY, payload TEXT NOT NULL);
            CREATE TABLE IF NOT EXISTS audit (
              id INTEGER PRIMARY KEY AUTOINCREMENT, audit_key TEXT,
              type TEXT, author TEXT, comment_txt TEXT, created_ms INTEGER,
              payload TEXT);
            CREATE TABLE IF NOT EXISTS tasks (
              id TEXT PRIMARY KEY, datasource TEXT, status TEXT,
              created_ms INTEGER, payload TEXT);
            CREATE TABLE IF NOT EXISTS supervisors (
              id TEXT PRIMARY KEY, payload TEXT NOT NULL);
            CREATE TABLE IF NOT EXISTS pending_segments (
              id TEXT PRIMARY KEY, datasource TEXT NOT NULL,
              start INTEGER NOT NULL, end INTEGER NOT NULL,
              version TEXT NOT NULL, partition_num INTEGER NOT NULL,
              created_ms INTEGER NOT NULL);
            """)

    # ---- segments ------------------------------------------------------
    def publish_segments(self, descriptors: Sequence[SegmentDescriptor],
                         datasource_meta_update: Optional[Tuple[str, Optional[dict], dict]] = None
                         ) -> bool:
        """Transactionally insert segments; optionally CAS the datasource
        commit metadata (start_metadata → end_metadata) in the SAME
        transaction — the exactly-once publish of
        IndexerSQLMetadataStorageCoordinator.announceHistoricalSegments.
        Returns False (and commits nothing) if the CAS comparison fails."""
        now = int(time.time() * 1000)
        with self._lock:
            try:
                self._conn.execute("BEGIN IMMEDIATE")
                if datasource_meta_update is not None:
                    ds, expected, new = datasource_meta_update
                    cur = self._conn.execute(
                        "SELECT commit_metadata FROM datasource_metadata "
                        "WHERE datasource = ?", (ds,))
                    row = cur.fetchone()
                    current = json.loads(row[0]) if row else None
                    if current != expected:
                        self._conn.execute("ROLLBACK")
                        return False
                    self._conn.execute(
                        "INSERT INTO datasource_metadata(datasource, commit_metadata) "
                        "VALUES(?, ?) ON CONFLICT(datasource) DO UPDATE SET "
                        "commit_metadata = excluded.commit_metadata",
                        (ds, json.dumps(new, sort_keys=True)))
                for d in descriptors:
                    self._conn.execute(
                        "INSERT OR IGNORE INTO segments(id, datasource, start, "
                        "end, version, partition_num, used, created_ms, payload) "
                        "VALUES(?,?,?,?,?,?,1,?,?)",
                        (d.id, d.datasource, d.interval.start, d.interval.end,
                         d.version, d.partition, now,
                         json.dumps(d.to_json(), sort_keys=True)))
                    self._conn.execute(
                        "DELETE FROM pending_segments WHERE id = ?", (d.id,))
                self._conn.execute("COMMIT")
                return True
            except BaseException:
                try:
                    self._conn.execute("ROLLBACK")
                except sqlite3.OperationalError:
                    pass
                raise

    def used_segments(self, datasource: Optional[str] = None
                      ) -> List[SegmentDescriptor]:
        with self._lock:
            if datasource is None:
                cur = self._conn.execute(
                    "SELECT payload FROM segments WHERE used = 1")
            else:
                cur = self._conn.execute(
                    "SELECT payload FROM segments WHERE used = 1 AND "
                    "datasource = ?", (datasource,))
            return [SegmentDescriptor.from_json(json.loads(r[0]))
                    for r in cur.fetchall()]

    def mark_unused(self, segment_ids: Sequence[str]) -> int:
        with self._lock, self._conn as c:
            n = 0
            for sid in segment_ids:
                n += c.execute("UPDATE segments SET used = 0 WHERE id = ?",
                               (sid,)).rowcount
            return n

    def mark_used(self, segment_ids: Sequence[str]) -> int:
        with self._lock, self._conn as c:
            n = 0
            for sid in segment_ids:
                n += c.execute("UPDATE segments SET used = 1 WHERE id = ?",
                               (sid,)).rowcount
            return n

    def update_segment_payload(self, descriptor: SegmentDescriptor) -> bool:
        """Rewrite a segment's stored payload in place — the metadata step
        of archive/move/restore, which changes only the loadSpec."""
        with self._lock, self._conn as c:
            n = c.execute(
                "UPDATE segments SET payload = ? WHERE id = ?",
                (json.dumps(descriptor.to_json(), sort_keys=True),
                 descriptor.id)).rowcount
            return n > 0

    def delete_segments(self, segment_ids: Sequence[str]) -> int:
        """Permanent removal (the kill-task step after mark_unused)."""
        with self._lock, self._conn as c:
            n = 0
            for sid in segment_ids:
                n += c.execute("DELETE FROM segments WHERE id = ?",
                               (sid,)).rowcount
            return n

    def unused_segments(self, datasource: str,
                        interval: Optional[Interval] = None
                        ) -> List[SegmentDescriptor]:
        with self._lock:
            if interval is None:
                cur = self._conn.execute(
                    "SELECT payload FROM segments WHERE used = 0 AND "
                    "datasource = ?", (datasource,))
            else:
                cur = self._conn.execute(
                    "SELECT payload FROM segments WHERE used = 0 AND "
                    "datasource = ? AND start >= ? AND end <= ?",
                    (datasource, interval.start, interval.end))
            return [SegmentDescriptor.from_json(json.loads(r[0]))
                    for r in cur.fetchall()]

    def visible_segments(self, datasource: str,
                         interval: Optional[Interval] = None
                         ) -> List[SegmentDescriptor]:
        """Used segments VISIBLE under MVCC (overshadowed versions excluded)
        — what queries and compaction must operate on, vs. raw
        used_segments which may still contain not-yet-cleaned old versions."""
        from druid_tpu.cluster.shardspec import NoneShardSpec as _None
        from druid_tpu.cluster.timeline import (PartitionChunk,
                                                VersionedIntervalTimeline)
        tl: VersionedIntervalTimeline = VersionedIntervalTimeline()
        for d in self.used_segments(datasource):
            spec = d.shard_spec or _None(d.partition)
            tl.add(d.interval, d.version, PartitionChunk(spec, d))
        iv = interval if interval is not None else Interval.eternity()
        out, seen = [], set()
        for holder in tl.lookup(iv):
            for chunk in holder.partitions:
                if chunk.obj.id not in seen:
                    seen.add(chunk.obj.id)
                    out.append(chunk.obj)
        return out

    def datasources(self) -> List[str]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT DISTINCT datasource FROM segments WHERE used = 1")
            return sorted(r[0] for r in cur.fetchall())

    def max_version(self, datasource: str, interval: Interval) -> Optional[str]:
        """Highest version overlapping the interval (segment allocation)."""
        with self._lock:
            cur = self._conn.execute(
                "SELECT MAX(version) FROM segments WHERE datasource = ? AND "
                "used = 1 AND start < ? AND end > ?",
                (datasource, interval.end, interval.start))
            row = cur.fetchone()
            return row[0] if row else None

    def max_partition(self, datasource: str, interval: Interval,
                      version: str) -> int:
        with self._lock:
            cur = self._conn.execute(
                "SELECT MAX(partition_num) FROM segments WHERE datasource = ? "
                "AND version = ? AND start = ? AND end = ?",
                (datasource, version, interval.start, interval.end))
            row = cur.fetchone()
            return -1 if row is None or row[0] is None else int(row[0])

    def allocate_segment(self, datasource: str, interval: Interval,
                         version: Optional[str] = None
                         ) -> Tuple[str, int]:
        """Atomically allocate (version, partition) for a new segment in the
        given time bucket — the overlord's SegmentAllocateAction: all
        concurrent writers to one bucket get the SAME version (appends are
        siblings, not overshadowing) and unique ascending partitions, by
        transacting against used + pending segments together."""
        now = int(time.time() * 1000)
        with self._lock:
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                # refuse buckets that overlap differently-aligned committed
                # segments: minting a newer version there would partially
                # overshadow (hide) their data
                cur = self._conn.execute(
                    "SELECT (SELECT COUNT(*) FROM segments WHERE "
                    "datasource = ? AND used = 1 AND start < ? AND end > ? "
                    "AND NOT (start = ? AND end = ?)) + "
                    "(SELECT COUNT(*) FROM pending_segments WHERE "
                    "datasource = ? AND start < ? AND end > ? "
                    "AND NOT (start = ? AND end = ?))",
                    (datasource, interval.end, interval.start,
                     interval.start, interval.end) * 2)
                if cur.fetchone()[0]:
                    self._conn.execute("ROLLBACK")
                    raise SegmentAllocationError(
                        f"bucket {interval} overlaps existing segments of a "
                        f"different granularity in [{datasource}]")
                if version is None:
                    cur = self._conn.execute(
                        "SELECT version FROM pending_segments WHERE "
                        "datasource = ? AND start = ? AND end = ? "
                        "UNION SELECT version FROM segments WHERE "
                        "datasource = ? AND start = ? AND end = ? AND used = 1",
                        (datasource, interval.start, interval.end) * 2)
                    versions = sorted(r[0] for r in cur.fetchall())
                    version = versions[-1] if versions else ts_to_iso(now)
                cur = self._conn.execute(
                    "SELECT MAX(partition_num) FROM (SELECT partition_num "
                    "FROM pending_segments WHERE datasource = ? AND "
                    "start = ? AND end = ? AND version = ? UNION ALL "
                    "SELECT partition_num FROM segments WHERE datasource = ? "
                    "AND start = ? AND end = ? AND version = ?)",
                    (datasource, interval.start, interval.end, version) * 2)
                row = cur.fetchone()
                part = 0 if row is None or row[0] is None else int(row[0]) + 1
                sid = f"{datasource}_{interval}_{version}_{part}"
                self._conn.execute(
                    "INSERT INTO pending_segments(id, datasource, start, end, "
                    "version, partition_num, created_ms) VALUES(?,?,?,?,?,?,?)",
                    (sid, datasource, interval.start, interval.end, version,
                     part, now))
                self._conn.execute("COMMIT")
                return version, part
            except BaseException:
                try:
                    self._conn.execute("ROLLBACK")
                except sqlite3.OperationalError:
                    pass
                raise

    def kill_pending_segments(self, datasource: str,
                              created_before_ms: Optional[int] = None) -> int:
        """Drop allocation leftovers from failed/discarded tasks
        (overlord killPendingSegments)."""
        with self._lock, self._conn as c:
            if created_before_ms is None:
                return c.execute(
                    "DELETE FROM pending_segments WHERE datasource = ?",
                    (datasource,)).rowcount
            return c.execute(
                "DELETE FROM pending_segments WHERE datasource = ? AND "
                "created_ms < ?", (datasource, created_before_ms)).rowcount

    # ---- datasource commit metadata (streaming offsets) ----------------
    def datasource_metadata(self, datasource: str) -> Optional[dict]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT commit_metadata FROM datasource_metadata WHERE "
                "datasource = ?", (datasource,))
            row = cur.fetchone()
            return json.loads(row[0]) if row else None

    def reset_datasource_metadata(self, datasource: str) -> None:
        with self._lock, self._conn as c:
            c.execute("DELETE FROM datasource_metadata WHERE datasource = ?",
                      (datasource,))

    # ---- rules ---------------------------------------------------------
    def set_rules(self, datasource: str, rules: List[dict]) -> None:
        with self._lock, self._conn as c:
            c.execute(
                "INSERT INTO rules(datasource, payload, updated_ms) "
                "VALUES(?,?,?) ON CONFLICT(datasource) DO UPDATE SET "
                "payload = excluded.payload, updated_ms = excluded.updated_ms",
                (datasource, json.dumps(rules), int(time.time() * 1000)))

    def rules_for(self, datasource: str) -> List[dict]:
        """Datasource rules + default-datasource (_default) rules appended —
        the reference's rule resolution order."""
        with self._lock:
            out = []
            for ds in (datasource, "_default"):
                cur = self._conn.execute(
                    "SELECT payload FROM rules WHERE datasource = ?", (ds,))
                row = cur.fetchone()
                if row:
                    out += json.loads(row[0])
            return out

    # ---- config / audit ------------------------------------------------
    def set_config(self, name: str, payload: dict) -> None:
        with self._lock, self._conn as c:
            c.execute("INSERT INTO config(name, payload) VALUES(?,?) "
                      "ON CONFLICT(name) DO UPDATE SET payload = excluded.payload",
                      (name, json.dumps(payload)))

    def get_config(self, name: str, default: Optional[dict] = None
                   ) -> Optional[dict]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT payload FROM config WHERE name = ?", (name,))
            row = cur.fetchone()
            return json.loads(row[0]) if row else default

    def audit(self, key: str, type_: str, author: str, comment: str,
              payload: dict) -> None:
        with self._lock, self._conn as c:
            c.execute("INSERT INTO audit(audit_key, type, author, comment_txt, "
                      "created_ms, payload) VALUES(?,?,?,?,?,?)",
                      (key, type_, author, comment, int(time.time() * 1000),
                       json.dumps(payload)))

    def audit_log(self, key: Optional[str] = None) -> List[dict]:
        with self._lock:
            q = "SELECT audit_key, type, author, comment_txt, created_ms, payload FROM audit"
            args: tuple = ()
            if key is not None:
                q += " WHERE audit_key = ?"
                args = (key,)
            return [{"key": r[0], "type": r[1], "author": r[2],
                     "comment": r[3], "created": r[4],
                     "payload": json.loads(r[5])}
                    for r in self._conn.execute(q + " ORDER BY id", args)]

    # ---- tasks / supervisors (used by the indexing service) ------------
    def insert_task(self, task_id: str, datasource: str, status: str,
                    payload: dict) -> None:
        with self._lock, self._conn as c:
            c.execute("INSERT OR REPLACE INTO tasks(id, datasource, status, "
                      "created_ms, payload) VALUES(?,?,?,?,?)",
                      (task_id, datasource, status, int(time.time() * 1000),
                       json.dumps(payload)))

    def update_task_status(self, task_id: str, status: str) -> None:
        with self._lock, self._conn as c:
            c.execute("UPDATE tasks SET status = ? WHERE id = ?",
                      (status, task_id))

    def task(self, task_id: str) -> Optional[dict]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT id, datasource, status, payload FROM tasks WHERE id = ?",
                (task_id,))
            r = cur.fetchone()
            if r is None:
                return None
            return {"id": r[0], "datasource": r[1], "status": r[2],
                    "payload": json.loads(r[3])}

    def tasks(self, status: Optional[str] = None) -> List[dict]:
        with self._lock:
            if status is None:
                cur = self._conn.execute(
                    "SELECT id, datasource, status, payload FROM tasks")
            else:
                cur = self._conn.execute(
                    "SELECT id, datasource, status, payload FROM tasks "
                    "WHERE status = ?", (status,))
            return [{"id": r[0], "datasource": r[1], "status": r[2],
                     "payload": json.loads(r[3])} for r in cur.fetchall()]

    def set_supervisor(self, supervisor_id: str, payload: dict) -> None:
        with self._lock, self._conn as c:
            c.execute("INSERT OR REPLACE INTO supervisors(id, payload) "
                      "VALUES(?,?)", (supervisor_id, json.dumps(payload)))

    def supervisors(self) -> Dict[str, dict]:
        with self._lock:
            return {r[0]: json.loads(r[1]) for r in self._conn.execute(
                "SELECT id, payload FROM supervisors")}
