"""SQL metadata store (sqlite) — the cluster's source of truth.

Reference analogs:
  segments table + transactional publish — server/src/main/java/org/apache/
    druid/metadata/IndexerSQLMetadataStorageCoordinator.java (announceHistorical
    Segments with dataSource-metadata compare-and-swap = exactly-once streaming
    publish), MetadataSegmentManager.java (used-segment polling)
  rules table — metadata/MetadataRuleManager.java
  audit — server/audit/SQLAuditManager.java

Segments are stored as JSON descriptors (DataSegment analog); payload columns
keep (datasource, start, end, version, partition, used) queryable. The
datasource metadata CAS is the exactly-once hook used by streaming ingestion
(§3.4: offsets and segments commit in one transaction).
"""
from __future__ import annotations

import json
import sqlite3
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from druid_tpu.cluster.shardspec import NoneShardSpec, ShardSpec, shardspec_from_json
from druid_tpu.utils.intervals import Interval, ts_to_iso


class SegmentAllocationError(RuntimeError):
    """Allocation refused: the bucket conflicts with differently-aligned
    committed segments (SegmentAllocateAction returns null there)."""


class StaleTermError(RuntimeError):
    """A fenced write carried a term older than the current lease term:
    the writer's lease was taken over and it must stop acting as leader
    (the fencing-token rejection of a zombie leader's writes)."""


@dataclass(frozen=True)
class SegmentDescriptor:
    """DataSegment analog (api/.../timeline/DataSegment.java): identity +
    shard spec + size/location metadata, without the column data."""
    datasource: str
    interval: Interval
    version: str
    partition: int = 0
    shard_spec: Optional[ShardSpec] = None
    size_bytes: int = 0
    num_rows: int = 0
    load_spec: Optional[dict] = None   # where the segment file lives

    @property
    def id(self) -> str:
        return (f"{self.datasource}_{self.interval}_{self.version}"
                f"_{self.partition}")

    def to_json(self) -> dict:
        return {"dataSource": self.datasource, "interval": str(self.interval),
                "version": self.version,
                "shardSpec": (self.shard_spec.to_json() if self.shard_spec
                              else {"type": "numbered",
                                    "partitionNum": self.partition,
                                    "partitions": 0}),
                "size": self.size_bytes, "numRows": self.num_rows,
                "loadSpec": self.load_spec}

    @staticmethod
    def from_json(j: dict) -> "SegmentDescriptor":
        spec = shardspec_from_json(j.get("shardSpec"))
        return SegmentDescriptor(
            j["dataSource"], Interval.parse(j["interval"]), j["version"],
            getattr(spec, "partition_num", 0), spec,
            j.get("size", 0), j.get("numRows", 0), j.get("loadSpec"))


class MetadataStore:
    """sqlite-backed metadata store; ':memory:' for tests, a file path for
    durability. Thread-safe via one connection + lock (sqlite serializes
    writers anyway; the reference uses JDBI connection pools)."""

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        self._create_tables()

    def _create_tables(self):
        with self._lock, self._conn as c:
            c.executescript("""
            CREATE TABLE IF NOT EXISTS segments (
              id TEXT PRIMARY KEY, datasource TEXT NOT NULL,
              start INTEGER NOT NULL, end INTEGER NOT NULL,
              version TEXT NOT NULL, partition_num INTEGER NOT NULL,
              used INTEGER NOT NULL DEFAULT 1,
              created_ms INTEGER NOT NULL, payload TEXT NOT NULL);
            CREATE INDEX IF NOT EXISTS idx_segments_ds
              ON segments(datasource, used);
            CREATE TABLE IF NOT EXISTS datasource_metadata (
              datasource TEXT PRIMARY KEY, commit_metadata TEXT NOT NULL);
            CREATE TABLE IF NOT EXISTS rules (
              datasource TEXT PRIMARY KEY, payload TEXT NOT NULL,
              updated_ms INTEGER NOT NULL);
            CREATE TABLE IF NOT EXISTS config (
              name TEXT PRIMARY KEY, payload TEXT NOT NULL);
            CREATE TABLE IF NOT EXISTS audit (
              id INTEGER PRIMARY KEY AUTOINCREMENT, audit_key TEXT,
              type TEXT, author TEXT, comment_txt TEXT, created_ms INTEGER,
              payload TEXT);
            CREATE TABLE IF NOT EXISTS tasks (
              id TEXT PRIMARY KEY, datasource TEXT, status TEXT,
              created_ms INTEGER, payload TEXT);
            CREATE TABLE IF NOT EXISTS supervisors (
              id TEXT PRIMARY KEY, payload TEXT NOT NULL);
            CREATE TABLE IF NOT EXISTS pending_segments (
              id TEXT PRIMARY KEY, datasource TEXT NOT NULL,
              start INTEGER NOT NULL, end INTEGER NOT NULL,
              version TEXT NOT NULL, partition_num INTEGER NOT NULL,
              created_ms INTEGER NOT NULL);
            CREATE TABLE IF NOT EXISTS leases (
              service TEXT PRIMARY KEY, holder TEXT NOT NULL,
              term INTEGER NOT NULL, expires_ms INTEGER NOT NULL,
              meta TEXT);
            CREATE TABLE IF NOT EXISTS fence_log (
              id INTEGER PRIMARY KEY AUTOINCREMENT, service TEXT NOT NULL,
              term INTEGER NOT NULL, holder TEXT NOT NULL, op TEXT NOT NULL,
              created_ms INTEGER NOT NULL);
            """)

    # ---- leader leases (coordination source of truth) -------------------
    def try_acquire_lease(self, service: str, holder: str, now_ms: int,
                          lease_ms: int, meta: Optional[dict] = None
                          ) -> Optional[Tuple[int, int]]:
        """Atomic acquire-or-renew of the leader lease for `service`.
        Returns (term, expires_ms) when `holder` holds the lease after this
        call, None when another holder's unexpired lease blocks it.

        The term is the fencing token: it increments on every ownership
        change (including re-acquiring one's own EXPIRED lease — the gap may
        have admitted another writer), and stays fixed across renewals of a
        live lease. Writes fenced with an old term are rejected by
        check_fence even if the zombie still believes it leads."""
        expires = now_ms + lease_ms
        m = json.dumps(meta, sort_keys=True) if meta is not None else None
        with self._lock:
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                cur = self._conn.execute(
                    "SELECT holder, term, expires_ms FROM leases "
                    "WHERE service = ?", (service,))
                row = cur.fetchone()
                if row is None:
                    self._conn.execute(
                        "INSERT INTO leases(service, holder, term, "
                        "expires_ms, meta) VALUES(?,?,1,?,?)",
                        (service, holder, expires, m))
                    self._conn.execute("COMMIT")
                    return 1, expires
                cur_holder, term, cur_expires = row
                if cur_holder == holder and now_ms < cur_expires:
                    # renewal of a live lease: same term
                    self._conn.execute(
                        "UPDATE leases SET expires_ms = ?, meta = ? "
                        "WHERE service = ?", (expires, m, service))
                    self._conn.execute("COMMIT")
                    return int(term), expires
                if now_ms < cur_expires:
                    self._conn.execute("ROLLBACK")
                    return None            # someone else holds it, live
                # expired: takeover (by anyone, incl. the old holder)
                self._conn.execute(
                    "UPDATE leases SET holder = ?, term = term + 1, "
                    "expires_ms = ?, meta = ? WHERE service = ?",
                    (holder, expires, m, service))
                self._conn.execute("COMMIT")
                return int(term) + 1, expires
            except BaseException:
                try:
                    self._conn.execute("ROLLBACK")
                except sqlite3.OperationalError:
                    pass
                raise

    def read_lease(self, service: str) -> Optional[dict]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT holder, term, expires_ms, meta FROM leases "
                "WHERE service = ?", (service,))
            row = cur.fetchone()
            if row is None:
                return None
            return {"service": service, "holder": row[0], "term": int(row[1]),
                    "expiresMs": int(row[2]),
                    "meta": json.loads(row[3]) if row[3] else None}

    def release_lease(self, service: str, holder: str) -> bool:
        """Voluntary step-down (graceful shutdown): only the current holder
        may release; the row stays (term history preserved) but expires
        immediately so any standby's next heartbeat takes over."""
        with self._lock, self._conn as c:
            return c.execute(
                "UPDATE leases SET expires_ms = 0 WHERE service = ? AND "
                "holder = ?", (service, holder)).rowcount > 0

    def _check_fence_locked(self, fence: Tuple[str, int, str],
                            op: str, now_ms: int) -> None:
        """Validate (service, term, holder) against the lease row and log
        the accepted write — caller holds self._lock and an open txn (or
        the implicit one of `with self._conn`). Raises StaleTermError when
        the term is not the CURRENT term of the service's lease."""
        service, term, holder = fence
        cur = self._conn.execute(
            "SELECT holder, term FROM leases WHERE service = ?", (service,))
        row = cur.fetchone()
        if row is None:
            raise StaleTermError(
                f"fenced write [{op}] for service [{service}] but no lease "
                f"exists — writer [{holder}] was never elected")
        cur_holder, cur_term = row[0], int(row[1])
        if term != cur_term or holder != cur_holder:
            raise StaleTermError(
                f"stale fencing term for [{service}]: write [{op}] from "
                f"[{holder}] term {term} rejected — current leader is "
                f"[{cur_holder}] term {cur_term}")
        self._conn.execute(
            "INSERT INTO fence_log(service, term, holder, op, created_ms) "
            "VALUES(?,?,?,?,?)", (service, term, holder, op, now_ms))

    def fence_log(self, service: Optional[str] = None) -> List[dict]:
        """Accepted fenced writes, oldest first — the audit trail the
        single-writer-per-term safety tests assert over."""
        with self._lock:
            q = ("SELECT service, term, holder, op, created_ms FROM "
                 "fence_log")
            args: tuple = ()
            if service is not None:
                q += " WHERE service = ?"
                args = (service,)
            return [{"service": r[0], "term": int(r[1]), "holder": r[2],
                     "op": r[3], "created": int(r[4])}
                    for r in self._conn.execute(q + " ORDER BY id", args)]

    # ---- segments ------------------------------------------------------
    def publish_segments(self, descriptors: Sequence[SegmentDescriptor],
                         datasource_meta_update: Optional[Tuple[str, Optional[dict], dict]] = None,
                         fence: Optional[Tuple[str, int, str]] = None
                         ) -> bool:
        """Transactionally insert segments; optionally CAS the datasource
        commit metadata (start_metadata → end_metadata) in the SAME
        transaction — the exactly-once publish of
        IndexerSQLMetadataStorageCoordinator.announceHistoricalSegments.
        Returns False (and commits nothing) if the CAS comparison fails.

        fence: optional (service, term, holder) fencing token — the write
        commits only if `term` is still the service's CURRENT lease term
        (StaleTermError otherwise), in the same transaction, so a deposed
        leader cannot race a commit past its successor's takeover."""
        now = int(time.time() * 1000)
        with self._lock:
            try:
                self._conn.execute("BEGIN IMMEDIATE")
                if fence is not None:
                    self._check_fence_locked(fence, "publish_segments", now)
                if datasource_meta_update is not None:
                    ds, expected, new = datasource_meta_update
                    cur = self._conn.execute(
                        "SELECT commit_metadata FROM datasource_metadata "
                        "WHERE datasource = ?", (ds,))
                    row = cur.fetchone()
                    current = json.loads(row[0]) if row else None
                    if current != expected:
                        self._conn.execute("ROLLBACK")
                        return False
                    self._conn.execute(
                        "INSERT INTO datasource_metadata(datasource, commit_metadata) "
                        "VALUES(?, ?) ON CONFLICT(datasource) DO UPDATE SET "
                        "commit_metadata = excluded.commit_metadata",
                        (ds, json.dumps(new, sort_keys=True)))
                for d in descriptors:
                    self._conn.execute(
                        "INSERT OR IGNORE INTO segments(id, datasource, start, "
                        "end, version, partition_num, used, created_ms, payload) "
                        "VALUES(?,?,?,?,?,?,1,?,?)",
                        (d.id, d.datasource, d.interval.start, d.interval.end,
                         d.version, d.partition, now,
                         json.dumps(d.to_json(), sort_keys=True)))
                    self._conn.execute(
                        "DELETE FROM pending_segments WHERE id = ?", (d.id,))
                self._conn.execute("COMMIT")
                return True
            except BaseException:
                try:
                    self._conn.execute("ROLLBACK")
                except sqlite3.OperationalError:
                    pass
                raise

    def used_segments(self, datasource: Optional[str] = None
                      ) -> List[SegmentDescriptor]:
        with self._lock:
            if datasource is None:
                cur = self._conn.execute(
                    "SELECT payload FROM segments WHERE used = 1")
            else:
                cur = self._conn.execute(
                    "SELECT payload FROM segments WHERE used = 1 AND "
                    "datasource = ?", (datasource,))
            return [SegmentDescriptor.from_json(json.loads(r[0]))
                    for r in cur.fetchall()]

    def mark_unused(self, segment_ids: Sequence[str],
                    fence: Optional[Tuple[str, int, str]] = None) -> int:
        with self._lock, self._conn as c:
            if fence is not None:
                self._check_fence_locked(fence, "mark_unused",
                                         int(time.time() * 1000))
            n = 0
            for sid in segment_ids:
                n += c.execute("UPDATE segments SET used = 0 WHERE id = ?",
                               (sid,)).rowcount
            return n

    def mark_used(self, segment_ids: Sequence[str],
                  fence: Optional[Tuple[str, int, str]] = None) -> int:
        with self._lock, self._conn as c:
            if fence is not None:
                self._check_fence_locked(fence, "mark_used",
                                         int(time.time() * 1000))
            n = 0
            for sid in segment_ids:
                n += c.execute("UPDATE segments SET used = 1 WHERE id = ?",
                               (sid,)).rowcount
            return n

    def update_segment_payload(self, descriptor: SegmentDescriptor) -> bool:
        """Rewrite a segment's stored payload in place — the metadata step
        of archive/move/restore, which changes only the loadSpec."""
        with self._lock, self._conn as c:
            n = c.execute(
                "UPDATE segments SET payload = ? WHERE id = ?",
                (json.dumps(descriptor.to_json(), sort_keys=True),
                 descriptor.id)).rowcount
            return n > 0

    def delete_segments(self, segment_ids: Sequence[str],
                        fence: Optional[Tuple[str, int, str]] = None) -> int:
        """Permanent removal (the kill-task step after mark_unused)."""
        with self._lock, self._conn as c:
            if fence is not None:
                self._check_fence_locked(fence, "delete_segments",
                                         int(time.time() * 1000))
            n = 0
            for sid in segment_ids:
                n += c.execute("DELETE FROM segments WHERE id = ?",
                               (sid,)).rowcount
            return n

    def unused_segments(self, datasource: str,
                        interval: Optional[Interval] = None
                        ) -> List[SegmentDescriptor]:
        with self._lock:
            if interval is None:
                cur = self._conn.execute(
                    "SELECT payload FROM segments WHERE used = 0 AND "
                    "datasource = ?", (datasource,))
            else:
                cur = self._conn.execute(
                    "SELECT payload FROM segments WHERE used = 0 AND "
                    "datasource = ? AND start >= ? AND end <= ?",
                    (datasource, interval.start, interval.end))
            return [SegmentDescriptor.from_json(json.loads(r[0]))
                    for r in cur.fetchall()]

    def visible_segments(self, datasource: str,
                         interval: Optional[Interval] = None
                         ) -> List[SegmentDescriptor]:
        """Used segments VISIBLE under MVCC (overshadowed versions excluded)
        — what queries and compaction must operate on, vs. raw
        used_segments which may still contain not-yet-cleaned old versions."""
        from druid_tpu.cluster.shardspec import NoneShardSpec as _None
        from druid_tpu.cluster.timeline import (PartitionChunk,
                                                VersionedIntervalTimeline)
        tl: VersionedIntervalTimeline = VersionedIntervalTimeline()
        for d in self.used_segments(datasource):
            spec = d.shard_spec or _None(d.partition)
            tl.add(d.interval, d.version, PartitionChunk(spec, d))
        iv = interval if interval is not None else Interval.eternity()
        out, seen = [], set()
        for holder in tl.lookup(iv):
            for chunk in holder.partitions:
                if chunk.obj.id not in seen:
                    seen.add(chunk.obj.id)
                    out.append(chunk.obj)
        return out

    def datasources(self) -> List[str]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT DISTINCT datasource FROM segments WHERE used = 1")
            return sorted(r[0] for r in cur.fetchall())

    def max_version(self, datasource: str, interval: Interval) -> Optional[str]:
        """Highest version overlapping the interval (segment allocation)."""
        with self._lock:
            cur = self._conn.execute(
                "SELECT MAX(version) FROM segments WHERE datasource = ? AND "
                "used = 1 AND start < ? AND end > ?",
                (datasource, interval.end, interval.start))
            row = cur.fetchone()
            return row[0] if row else None

    def max_partition(self, datasource: str, interval: Interval,
                      version: str) -> int:
        with self._lock:
            cur = self._conn.execute(
                "SELECT MAX(partition_num) FROM segments WHERE datasource = ? "
                "AND version = ? AND start = ? AND end = ?",
                (datasource, version, interval.start, interval.end))
            row = cur.fetchone()
            return -1 if row is None or row[0] is None else int(row[0])

    def allocate_segment(self, datasource: str, interval: Interval,
                         version: Optional[str] = None
                         ) -> Tuple[str, int]:
        """Atomically allocate (version, partition) for a new segment in the
        given time bucket — the overlord's SegmentAllocateAction: all
        concurrent writers to one bucket get the SAME version (appends are
        siblings, not overshadowing) and unique ascending partitions, by
        transacting against used + pending segments together."""
        now = int(time.time() * 1000)
        with self._lock:
            self._conn.execute("BEGIN IMMEDIATE")
            try:
                # refuse buckets that overlap differently-aligned committed
                # segments: minting a newer version there would partially
                # overshadow (hide) their data
                cur = self._conn.execute(
                    "SELECT (SELECT COUNT(*) FROM segments WHERE "
                    "datasource = ? AND used = 1 AND start < ? AND end > ? "
                    "AND NOT (start = ? AND end = ?)) + "
                    "(SELECT COUNT(*) FROM pending_segments WHERE "
                    "datasource = ? AND start < ? AND end > ? "
                    "AND NOT (start = ? AND end = ?))",
                    (datasource, interval.end, interval.start,
                     interval.start, interval.end) * 2)
                if cur.fetchone()[0]:
                    self._conn.execute("ROLLBACK")
                    raise SegmentAllocationError(
                        f"bucket {interval} overlaps existing segments of a "
                        f"different granularity in [{datasource}]")
                if version is None:
                    cur = self._conn.execute(
                        "SELECT version FROM pending_segments WHERE "
                        "datasource = ? AND start = ? AND end = ? "
                        "UNION SELECT version FROM segments WHERE "
                        "datasource = ? AND start = ? AND end = ? AND used = 1",
                        (datasource, interval.start, interval.end) * 2)
                    versions = sorted(r[0] for r in cur.fetchall())
                    version = versions[-1] if versions else ts_to_iso(now)
                cur = self._conn.execute(
                    "SELECT MAX(partition_num) FROM (SELECT partition_num "
                    "FROM pending_segments WHERE datasource = ? AND "
                    "start = ? AND end = ? AND version = ? UNION ALL "
                    "SELECT partition_num FROM segments WHERE datasource = ? "
                    "AND start = ? AND end = ? AND version = ?)",
                    (datasource, interval.start, interval.end, version) * 2)
                row = cur.fetchone()
                part = 0 if row is None or row[0] is None else int(row[0]) + 1
                sid = f"{datasource}_{interval}_{version}_{part}"
                self._conn.execute(
                    "INSERT INTO pending_segments(id, datasource, start, end, "
                    "version, partition_num, created_ms) VALUES(?,?,?,?,?,?,?)",
                    (sid, datasource, interval.start, interval.end, version,
                     part, now))
                self._conn.execute("COMMIT")
                return version, part
            except BaseException:
                try:
                    self._conn.execute("ROLLBACK")
                except sqlite3.OperationalError:
                    pass
                raise

    def kill_pending_segments(self, datasource: str,
                              created_before_ms: Optional[int] = None) -> int:
        """Drop allocation leftovers from failed/discarded tasks
        (overlord killPendingSegments)."""
        with self._lock, self._conn as c:
            if created_before_ms is None:
                return c.execute(
                    "DELETE FROM pending_segments WHERE datasource = ?",
                    (datasource,)).rowcount
            return c.execute(
                "DELETE FROM pending_segments WHERE datasource = ? AND "
                "created_ms < ?", (datasource, created_before_ms)).rowcount

    # ---- datasource commit metadata (streaming offsets) ----------------
    def datasource_metadata(self, datasource: str) -> Optional[dict]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT commit_metadata FROM datasource_metadata WHERE "
                "datasource = ?", (datasource,))
            row = cur.fetchone()
            return json.loads(row[0]) if row else None

    def reset_datasource_metadata(self, datasource: str) -> None:
        with self._lock, self._conn as c:
            c.execute("DELETE FROM datasource_metadata WHERE datasource = ?",
                      (datasource,))

    # ---- rules ---------------------------------------------------------
    def set_rules(self, datasource: str, rules: List[dict]) -> None:
        with self._lock, self._conn as c:
            c.execute(
                "INSERT INTO rules(datasource, payload, updated_ms) "
                "VALUES(?,?,?) ON CONFLICT(datasource) DO UPDATE SET "
                "payload = excluded.payload, updated_ms = excluded.updated_ms",
                (datasource, json.dumps(rules), int(time.time() * 1000)))

    def rules_for(self, datasource: str) -> List[dict]:
        """Datasource rules + default-datasource (_default) rules appended —
        the reference's rule resolution order."""
        with self._lock:
            out = []
            for ds in (datasource, "_default"):
                cur = self._conn.execute(
                    "SELECT payload FROM rules WHERE datasource = ?", (ds,))
                row = cur.fetchone()
                if row:
                    out += json.loads(row[0])
            return out

    # ---- config / audit ------------------------------------------------
    def set_config(self, name: str, payload: dict) -> None:
        with self._lock, self._conn as c:
            c.execute("INSERT INTO config(name, payload) VALUES(?,?) "
                      "ON CONFLICT(name) DO UPDATE SET payload = excluded.payload",
                      (name, json.dumps(payload)))

    def get_config(self, name: str, default: Optional[dict] = None
                   ) -> Optional[dict]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT payload FROM config WHERE name = ?", (name,))
            row = cur.fetchone()
            return json.loads(row[0]) if row else default

    def audit(self, key: str, type_: str, author: str, comment: str,
              payload: dict) -> None:
        with self._lock, self._conn as c:
            c.execute("INSERT INTO audit(audit_key, type, author, comment_txt, "
                      "created_ms, payload) VALUES(?,?,?,?,?,?)",
                      (key, type_, author, comment, int(time.time() * 1000),
                       json.dumps(payload)))

    def audit_log(self, key: Optional[str] = None) -> List[dict]:
        with self._lock:
            q = "SELECT audit_key, type, author, comment_txt, created_ms, payload FROM audit"
            args: tuple = ()
            if key is not None:
                q += " WHERE audit_key = ?"
                args = (key,)
            return [{"key": r[0], "type": r[1], "author": r[2],
                     "comment": r[3], "created": r[4],
                     "payload": json.loads(r[5])}
                    for r in self._conn.execute(q + " ORDER BY id", args)]

    # ---- tasks / supervisors (used by the indexing service) ------------
    def insert_task(self, task_id: str, datasource: str, status: str,
                    payload: dict,
                    fence: Optional[Tuple[str, int, str]] = None) -> None:
        with self._lock, self._conn as c:
            now = int(time.time() * 1000)
            if fence is not None:
                self._check_fence_locked(fence, "insert_task", now)
            c.execute("INSERT OR REPLACE INTO tasks(id, datasource, status, "
                      "created_ms, payload) VALUES(?,?,?,?,?)",
                      (task_id, datasource, status, now,
                       json.dumps(payload)))

    def update_task_status(self, task_id: str, status: str,
                           fence: Optional[Tuple[str, int, str]] = None
                           ) -> None:
        with self._lock, self._conn as c:
            if fence is not None:
                self._check_fence_locked(fence, "update_task_status",
                                         int(time.time() * 1000))
            c.execute("UPDATE tasks SET status = ? WHERE id = ?",
                      (status, task_id))

    def task(self, task_id: str) -> Optional[dict]:
        with self._lock:
            cur = self._conn.execute(
                "SELECT id, datasource, status, payload FROM tasks WHERE id = ?",
                (task_id,))
            r = cur.fetchone()
            if r is None:
                return None
            return {"id": r[0], "datasource": r[1], "status": r[2],
                    "payload": json.loads(r[3])}

    def tasks(self, status: Optional[str] = None) -> List[dict]:
        with self._lock:
            if status is None:
                cur = self._conn.execute(
                    "SELECT id, datasource, status, payload FROM tasks")
            else:
                cur = self._conn.execute(
                    "SELECT id, datasource, status, payload FROM tasks "
                    "WHERE status = ?", (status,))
            return [{"id": r[0], "datasource": r[1], "status": r[2],
                     "payload": json.loads(r[3])} for r in cur.fetchall()]

    def set_supervisor(self, supervisor_id: str, payload: dict) -> None:
        with self._lock, self._conn as c:
            c.execute("INSERT OR REPLACE INTO supervisors(id, payload) "
                      "VALUES(?,?)", (supervisor_id, json.dumps(payload)))

    def supervisors(self) -> Dict[str, dict]:
        with self._lock:
            return {r[0]: json.loads(r[1]) for r in self._conn.execute(
                "SELECT id, payload FROM supervisors")}
