"""Realtime query serving: in-flight sinks announced into the broker view.

Reference analog: SinkQuerySegmentWalker (server/src/main/java/org/apache/
druid/segment/realtime/appenderator/SinkQuerySegmentWalker.java) — the piece
that makes streaming data queryable seconds after ingest THROUGH THE NORMAL
BROKER PATH, not via a side channel. The indexing process announces each
allocated sink as a served segment (the reference announces via ZK from the
peon; here the announcement goes straight into the InventoryView), the
broker's timeline then routes the segment to this server, and partials from
the sink's hydrants merge with historical partials exactly like any other
scatter-gather leg.

Handoff is seamless by identity: the published historical segment carries
the SAME (datasource, interval, version, partition) id, so its announcement
joins the sink's ReplicaSet; when the driver drops the sink after a
successful publish, unannouncing here leaves the historical replica serving.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from druid_tpu.cluster.metadata import SegmentDescriptor
from druid_tpu.cluster.shardspec import NumberedShardSpec
from druid_tpu.cluster.view import InventoryView
from druid_tpu.data.segment import Segment
from druid_tpu.engine.engines import AggregatePartials, make_aggregate_partials
from druid_tpu.query.model import Query


class RealtimeServer:
    """A queryable node surface over one or more Appenderators.

    Implements the same duck-typed node API the broker drives (DataNode /
    RemoteDataNodeClient): run_partials / run_rows / segments / alive.
    Results are never cached (in-flight data mutates between queries — the
    reference's CachingClusteredClient also skips caching realtime sinks).
    """

    #: broker result caching + coordinator segment management are disabled
    #: for this server (CachingClusteredClient.segmentReplicatable analog)
    segment_replicatable = False

    def __init__(self, name: str, view: InventoryView,
                 tier: str = "_realtime"):
        self.name = name
        self.view = view
        self.tier = tier
        self.alive = True
        self.cache = None
        self._apps: List[object] = []
        self._lock = threading.RLock()
        view.register(self)

    def attach(self, appenderator) -> None:
        """Start announcing an appenderator's sinks (existing + future)."""
        with self._lock:
            self._apps.append(appenderator)
        appenderator.add_listener(self)

    # ---- Appenderator sink lifecycle listener --------------------------
    def sink_created(self, ident) -> None:
        self.view.announce(self.name, self._descriptor(ident))

    def sink_dropped(self, ident) -> None:
        self.view.unannounce(self.name, ident.id)

    @staticmethod
    def _descriptor(ident) -> SegmentDescriptor:
        return SegmentDescriptor(
            ident.datasource, ident.interval, ident.version, ident.partition,
            NumberedShardSpec(ident.partition, 0))

    # ---- node query surface (duck-typed DataNode) ----------------------
    def _select(self, segment_ids: Sequence[str]
                ) -> Tuple[List[Segment], Set[str]]:
        segs: List[Segment] = []
        served: Set[str] = set()
        with self._lock:
            apps = list(self._apps)
        for sid in segment_ids:
            for app in apps:
                hydrants = app.sink_segments(str(sid))
                if hydrants is not None:
                    segs += hydrants
                    served.add(str(sid))
                    break
        return segs, served

    def run_partials(self, query: Query, segment_ids: Sequence[str],
                     check=None) -> Tuple[AggregatePartials, Set[str]]:
        if not self.alive:
            raise ConnectionError(f"server [{self.name}] is down")
        segs, served = self._select(segment_ids)
        ap = make_aggregate_partials(query, segs, clamp=False)
        return ap, served

    def run_rows(self, query: Query, segment_ids: Sequence[str]
                 ) -> Tuple[List[dict], Set[str]]:
        if not self.alive:
            raise ConnectionError(f"server [{self.name}] is down")
        from druid_tpu.engine.executor import QueryExecutor
        segs, served = self._select(segment_ids)
        rows = QueryExecutor().run(query, segments=segs)
        return rows, served

    # ---- inventory surface ---------------------------------------------
    def segments(self) -> List[Segment]:
        with self._lock:
            apps = list(self._apps)
        out: List[Segment] = []
        for app in apps:
            out += app.query_segments()
        return out

    def served_segment_ids(self) -> Set[str]:
        with self._lock:
            apps = list(self._apps)
        out: Set[str] = set()
        for app in apps:
            for ident in app.sink_ids():
                out.add(ident.id)
        return out

    def segment_count(self) -> int:
        return len(self.served_segment_ids())

    # the coordinator never manages realtime sinks; keep the node surface
    # total so a misdirected call is a no-op, not a crash
    def load_segment(self, segment, descriptor=None) -> bool:
        return False

    def drop_segment(self, segment_id: str) -> bool:
        return False

    def ping(self) -> bool:
        return self.alive
