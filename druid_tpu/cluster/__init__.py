from druid_tpu.cluster.broker import Broker, MissingSegmentsError
from druid_tpu.cluster.cache import (Cache, CacheConfig, HybridCache,
                                     LruCache, RemoteCacheClient,
                                     RemoteCacheServer)
from druid_tpu.cluster.coordinator import (Coordinator, DynamicConfig,
                                           ForeverDropRule, ForeverLoadRule,
                                           IntervalDropRule, IntervalLoadRule,
                                           PeriodDropRule, PeriodLoadRule,
                                           rule_from_json)
from druid_tpu.cluster.metadata import (MetadataStore, SegmentDescriptor,
                                        StaleTermError)
from druid_tpu.cluster.shardspec import (HashBasedNumberedShardSpec,
                                         LinearShardSpec, NoneShardSpec,
                                         NumberedShardSpec, ShardSpec,
                                         SingleDimensionShardSpec,
                                         shardspec_from_json)
from druid_tpu.cluster.timeline import (PartitionChunk, PartitionHolder,
                                        TimelineObjectHolder,
                                        VersionedIntervalTimeline)
from druid_tpu.cluster.dataserver import DataNodeServer, RemoteDataNodeClient
from druid_tpu.cluster.lookups import (LookupCoordinatorManager,
                                       LookupNodeSync)
from druid_tpu.cluster.realtime import RealtimeServer
from druid_tpu.cluster.resilience import (BrokerResilience, PartialResult,
                                          ResiliencePolicy)
from druid_tpu.cluster.view import DataNode, InventoryView, descriptor_for

__all__ = [
    "ShardSpec", "NoneShardSpec", "LinearShardSpec", "NumberedShardSpec",
    "HashBasedNumberedShardSpec", "SingleDimensionShardSpec",
    "shardspec_from_json", "PartitionChunk", "PartitionHolder",
    "TimelineObjectHolder", "VersionedIntervalTimeline",
    "MetadataStore", "SegmentDescriptor", "StaleTermError", "DataNode",
    "InventoryView",
    "descriptor_for", "Broker", "MissingSegmentsError", "LruCache",
    "Cache", "HybridCache", "RemoteCacheClient", "RemoteCacheServer",
    "CacheConfig", "Coordinator", "DynamicConfig", "ForeverLoadRule",
    "PeriodLoadRule", "IntervalLoadRule", "ForeverDropRule", "PeriodDropRule",
    "IntervalDropRule", "rule_from_json", "DataNodeServer",
    "RemoteDataNodeClient", "RealtimeServer", "LookupCoordinatorManager",
    "LookupNodeSync", "ResiliencePolicy", "BrokerResilience",
    "PartialResult",
]
