from druid_tpu.cluster.shardspec import (HashBasedNumberedShardSpec,
                                         LinearShardSpec, NoneShardSpec,
                                         NumberedShardSpec, ShardSpec,
                                         SingleDimensionShardSpec,
                                         shardspec_from_json)
from druid_tpu.cluster.timeline import (PartitionChunk, PartitionHolder,
                                        TimelineObjectHolder,
                                        VersionedIntervalTimeline)

__all__ = [
    "ShardSpec", "NoneShardSpec", "LinearShardSpec", "NumberedShardSpec",
    "HashBasedNumberedShardSpec", "SingleDimensionShardSpec",
    "shardspec_from_json", "PartitionChunk", "PartitionHolder",
    "TimelineObjectHolder", "VersionedIntervalTimeline",
]
