"""VersionedIntervalTimeline: the MVCC (interval, version, partition) map.

Capability parity with the reference's core data structure
(common/.../timeline/VersionedIntervalTimeline.java:68): atomic segment
replacement by version string, overshadowing, partition-chunk completeness,
interval splitting on lookup. Used by the broker (cluster view), data nodes
(local segments), coordinator (rules) and ingestion (lock/publish checks).

Semantics mirrored from the reference:
  * versions compare LEXICOGRAPHICALLY (they are timestamps in practice);
  * a (interval, version) entry becomes visible only when its partition set
    is complete (ShardSpec.complete_set);
  * for any instant, the visible entry is the highest-version complete entry
    whose interval covers it; lower versions show through where a higher
    version does NOT cover (partial overshadowing splits holders);
  * removing a chunk resurrects what it overshadowed.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Generic, List, Optional, Tuple, TypeVar

from druid_tpu.cluster.shardspec import NoneShardSpec, ShardSpec
from druid_tpu.utils.intervals import Interval, condense

T = TypeVar("T")


@dataclass(frozen=True)
class PartitionChunk(Generic[T]):
    shard_spec: ShardSpec
    obj: T

    @property
    def partition_num(self) -> int:
        return self.shard_spec.partition_num


class PartitionHolder(Generic[T]):
    """partition_num -> chunk (reference timeline/partition/PartitionHolder)."""

    def __init__(self):
        self.chunks: Dict[int, PartitionChunk[T]] = {}

    def add(self, chunk: PartitionChunk[T]):
        self.chunks[chunk.partition_num] = chunk

    def remove(self, partition_num: int) -> Optional[PartitionChunk[T]]:
        return self.chunks.pop(partition_num, None)

    def is_complete(self) -> bool:
        if not self.chunks:
            return False
        specs = [c.shard_spec for c in self.chunks.values()]
        return specs[0].complete_set(specs)

    def __iter__(self):
        return iter(sorted(self.chunks.values(),
                           key=lambda c: c.partition_num))

    def __len__(self):
        return len(self.chunks)


@dataclass(frozen=True)
class TimelineObjectHolder(Generic[T]):
    interval: Interval
    version: str
    partitions: Tuple[PartitionChunk[T], ...]

    def payloads(self) -> List[T]:
        return [c.obj for c in self.partitions]


class VersionedIntervalTimeline(Generic[T]):
    """Thread-safe MVCC timeline."""

    def __init__(self):
        # (interval, version) -> PartitionHolder
        self._entries: Dict[Tuple[Interval, str], PartitionHolder[T]] = {}
        self._lock = threading.RLock()

    # -- mutation --------------------------------------------------------
    def add(self, interval: Interval, version: str,
            chunk: PartitionChunk[T]):
        with self._lock:
            holder = self._entries.get((interval, version))
            if holder is None:
                holder = self._entries[(interval, version)] = PartitionHolder()
            holder.add(chunk)

    def remove(self, interval: Interval, version: str,
               partition_num: int = 0) -> Optional[PartitionChunk[T]]:
        with self._lock:
            holder = self._entries.get((interval, version))
            if holder is None:
                return None
            chunk = holder.remove(partition_num)
            if not len(holder):
                del self._entries[(interval, version)]
            return chunk

    # -- lookup ----------------------------------------------------------
    def lookup(self, interval: Interval) -> List[TimelineObjectHolder[T]]:
        """Visible holders overlapping `interval`, split at overshadowing
        boundaries, clipped to `interval`, ordered by time."""
        return self._lookup(interval, complete_only=True)

    def lookup_with_incomplete(self, interval: Interval) \
            -> List[TimelineObjectHolder[T]]:
        return self._lookup(interval, complete_only=False)

    def _lookup(self, interval: Interval, complete_only: bool):
        with self._lock:
            cands = [
                (iv, ver, holder)
                for (iv, ver), holder in self._entries.items()
                if iv.overlaps(interval)
                and (not complete_only or holder.is_complete())
            ]
            if not cands:
                return []
            # sweep over elementary boundaries
            pts = set()
            for iv, _, _ in cands:
                pts.add(max(iv.start, interval.start))
                pts.add(min(iv.end, interval.end))
            pts.add(interval.start)
            pts.add(interval.end)
            bounds = sorted(p for p in pts
                            if interval.start <= p <= interval.end)
            out: List[TimelineObjectHolder[T]] = []
            for a, b in zip(bounds, bounds[1:]):
                if a >= b:
                    continue
                best = None
                for iv, ver, holder in cands:
                    if iv.start <= a and b <= iv.end:
                        if best is None or ver > best[1]:
                            best = (iv, ver, holder)
                if best is None:
                    continue
                iv, ver, holder = best
                piece = Interval(a, b)
                if out and out[-1].version == ver \
                        and self._same_holder(out[-1], holder) \
                        and out[-1].interval.end == a:
                    # merge adjacent pieces of the same entry
                    out[-1] = TimelineObjectHolder(
                        Interval(out[-1].interval.start, b), ver,
                        out[-1].partitions)
                else:
                    out.append(TimelineObjectHolder(
                        piece, ver, tuple(holder)))
            return out

    @staticmethod
    def _same_holder(holder_out: TimelineObjectHolder,
                     holder: PartitionHolder) -> bool:
        return list(holder_out.partitions) == list(holder)

    # -- overshadowing ---------------------------------------------------
    def is_overshadowed(self, interval: Interval, version: str) -> bool:
        """Would an entry at (interval, version) be fully hidden by
        higher-version complete entries?"""
        with self._lock:
            covers = [
                iv for (iv, ver), holder in self._entries.items()
                if ver > version and holder.is_complete()
                and iv.overlaps(interval)
            ]
            return _covered(interval, covers)

    def find_fully_overshadowed(self) -> List[TimelineObjectHolder[T]]:
        """All entries completely hidden by higher versions — what the
        coordinator marks unused (DruidCoordinatorCleanupOvershadowed)."""
        with self._lock:
            out = []
            for (iv, ver), holder in self._entries.items():
                if self.is_overshadowed(iv, ver):
                    out.append(TimelineObjectHolder(iv, ver, tuple(holder)))
            return out

    # -- introspection ---------------------------------------------------
    def all_entries(self) -> List[TimelineObjectHolder[T]]:
        with self._lock:
            return [TimelineObjectHolder(iv, ver, tuple(holder))
                    for (iv, ver), holder in sorted(
                        self._entries.items(),
                        key=lambda kv: (kv[0][0], kv[0][1]))]

    def is_empty(self) -> bool:
        with self._lock:
            return not self._entries

    def first_entry_interval(self) -> Optional[Interval]:
        with self._lock:
            if not self._entries:
                return None
            return min(iv for iv, _ in self._entries)


def _covered(interval: Interval, covers: List[Interval]) -> bool:
    """Is `interval` fully covered by the union of `covers`?"""
    return any(iv.contains_interval(interval) for iv in condense(covers))
