"""Query caches.

Reference analogs: client/cache/Cache.java SPI with Caffeine local cache
(client/cache/CaffeineCache.java) + CacheConfig; used at the segment level
by the historical's CachingQueryRunner and at the result level by the
broker's ResultLevelCachingQueryRunner. Cache keys come from per-query-type
CacheStrategy (query/CacheStrategy.java).

Here: an LRU local cache keyed by (namespace, key). Segment-level entries
hold per-segment partial states (exact merges — the analog of caching
non-finalized per-segment results); result-level entries hold final rows,
keyed by the query plus the exact segment-version set so any timeline
change (new version, compaction) invalidates naturally (the reference's
etag mechanism).
"""
from __future__ import annotations

import json
import logging
import threading
from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple

log = logging.getLogger(__name__)


class CacheStats:
    def __init__(self):
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.puts = 0
        #: puts a remote tier refused to ship (value not wire-serializable)
        self.dropped_puts = 0


class Cache:
    """Pluggable cache SPI (reference: client/cache/Cache.java — local
    Caffeine, memcached, hybrid impls chosen by config)."""

    def get(self, namespace: str, key: str):
        raise NotImplementedError

    def put(self, namespace: str, key: str, value) -> None:
        raise NotImplementedError

    def invalidate_namespace(self, namespace: str) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LruCache(Cache):
    """Thread-safe LRU with entry-count bound (the CaffeineCache role)."""

    def __init__(self, max_entries: int = 10_000):
        self.max_entries = max_entries
        self._data: "OrderedDict[Tuple[str, str], object]" = OrderedDict()
        self._lock = threading.Lock()
        self.stats = CacheStats()

    def get(self, namespace: str, key: str):
        with self._lock:
            k = (namespace, key)
            if k in self._data:
                self._data.move_to_end(k)
                self.stats.hits += 1
                return self._data[k]
            self.stats.misses += 1
            return None

    def put(self, namespace: str, key: str, value) -> None:
        with self._lock:
            k = (namespace, key)
            self._data[k] = value
            self._data.move_to_end(k)
            self.stats.puts += 1
            while len(self._data) > self.max_entries:
                self._data.popitem(last=False)
                self.stats.evictions += 1

    def invalidate_namespace(self, namespace: str) -> int:
        with self._lock:
            doomed = [k for k in self._data if k[0] == namespace]
            for k in doomed:
                del self._data[k]
            return len(doomed)

    def __len__(self):
        with self._lock:
            return len(self._data)


class HybridCache(Cache):
    """L1 local + L2 remote with L1 population on L2 hits (reference:
    client/cache/HybridCache.java — Caffeine in front of memcached)."""

    def __init__(self, l1: Cache, l2: Cache, populate_l1: bool = True):
        self.l1 = l1
        self.l2 = l2
        self.populate_l1 = populate_l1
        self.stats = CacheStats()
        # counter increments are read-modify-write: broker pool threads
        # hitting both tiers concurrently would lose updates unguarded
        self._stats_lock = threading.Lock()

    def get(self, namespace, key):
        v = self.l1.get(namespace, key)
        if v is None:
            v = self.l2.get(namespace, key)
            if v is not None and self.populate_l1:
                self.l1.put(namespace, key, v)
        with self._stats_lock:
            if v is None:
                self.stats.misses += 1
            else:
                self.stats.hits += 1
        return v

    def put(self, namespace, key, value):
        self.l1.put(namespace, key, value)
        self.l2.put(namespace, key, value)
        with self._stats_lock:
            self.stats.puts += 1

    def invalidate_namespace(self, namespace):
        n = self.l1.invalidate_namespace(namespace)
        return max(n, self.l2.invalidate_namespace(namespace))

    def close(self):
        self.l1.close()
        self.l2.close()


class RemoteCacheServer:
    """Shared cache node: the memcached role. Length-prefixed JSON frames
    over TCP — data-only on the wire, so a peer that can reach the port
    can at worst poison cache entries, never execute code (the pickle
    frames this replaces were arbitrary-code-execution for anyone who
    could connect). Values that do not JSON-serialize are dropped by the
    client's put (a cache is allowed to forget)."""

    def __init__(self, max_entries: int = 100_000, port: int = 0,
                 host: str = "127.0.0.1"):
        import socketserver

        if host not in ("127.0.0.1", "localhost", "::1"):
            # loud by design: there is no authentication on this protocol
            log.warning(
                "RemoteCacheServer binding to NON-LOOPBACK host %r — the "
                "cache protocol is unauthenticated; anyone who can reach "
                "this port can read and poison cache entries. Bind to "
                "127.0.0.1 or firewall the port to the cluster.", host)

        store = LruCache(max_entries)
        self.store = store

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        req = _recv_frame(self.request)
                        if req is None:
                            return
                        op = req.get("op")
                        if op == "get":
                            out = {"value": store.get(req["ns"], req["key"])}
                        elif op == "put":
                            store.put(req["ns"], req["key"], req["value"])
                            out = {"ok": True}
                        elif op == "invalidate":
                            out = {"n": store.invalidate_namespace(req["ns"])}
                        else:
                            out = {"error": f"bad op {op!r}"}
                        _send_frame(self.request, out)
                except (ConnectionError, OSError, ValueError):
                    # ValueError covers malformed frames (non-JSON bytes —
                    # e.g. a legacy/hostile pickle payload): drop the
                    # connection, never interpret the bytes
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        # reap the serve_forever thread: a stop() that returns while the
        # acceptor still winds down strands one thread per server cycle
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)


class RemoteCacheClient(Cache):
    """Cache over a RemoteCacheServer. Degrades like memcached: any
    connection failure is a miss / dropped put, never a query failure."""

    def __init__(self, host: str, port: int, timeout: float = 2.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.stats = CacheStats()
        self._sock = None
        self._lock = threading.Lock()
        # separate from the socket lock: a counter bump must not queue
        # behind a remote round-trip
        self._stats_lock = threading.Lock()
        self._warned_drop = False

    def _call(self, req):
        import socket
        with self._lock:
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(
                        (self.host, self.port), timeout=self.timeout)
                _send_frame(self._sock, req)
                return _recv_frame(self._sock)
            except (ConnectionError, OSError, ValueError):
                # ValueError: non-JSON reply (legacy/misbehaving peer) —
                # the stream is desynced, so drop the socket; like any
                # failure here it degrades to a miss, never a query error
                try:
                    if self._sock is not None:
                        self._sock.close()
                finally:
                    self._sock = None
                return None

    def get(self, namespace, key):
        out = self._call({"op": "get", "ns": namespace, "key": key})
        v = out.get("value") if out else None
        with self._stats_lock:
            if v is None:
                self.stats.misses += 1
            else:
                self.stats.hits += 1
        return v

    def put(self, namespace, key, value):
        try:
            # encode ONCE: serializability probe and wire bytes in one go
            payload = _encode_frame({"op": "put", "ns": namespace,
                                     "key": key, "value": value})
        except (TypeError, ValueError):
            # non-JSON-serializable value (e.g. device partial states):
            # drop the put — remote tiers carry data-only entries. Counted
            # (and logged once) so a pure-remote deployment whose values
            # never serialize shows WHY its hit rate is zero, instead of
            # silently recomputing everything forever.
            with self._stats_lock:
                self.stats.dropped_puts += 1
                warn_now = not self._warned_drop
                self._warned_drop = True
            if warn_now:
                log.warning(
                    "remote cache dropping non-serializable puts (first: "
                    "namespace %r, %s) — these entries only cache in a "
                    "local tier; see CacheStats.dropped_puts", namespace,
                    type(value).__name__)
            return
        self._call(payload)
        with self._stats_lock:
            self.stats.puts += 1

    def invalidate_namespace(self, namespace):
        out = self._call({"op": "invalidate", "ns": namespace})
        return out.get("n", 0) if out else 0

    def close(self):
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None


#: refuse absurd frames before allocating for them (a hostile peer on the
#: unauthenticated port must not be able to OOM the process with a header)
MAX_FRAME_BYTES = 64 * 1024 * 1024


def _frame_json_default(obj):
    """Data-only lowering for the wire: numpy scalars/arrays become plain
    JSON numbers/lists (the only non-builtin types result rows carry).
    Anything else is a TypeError — the put is then dropped client-side."""
    import numpy as np
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError(f"not cacheable over the wire: {type(obj).__name__}")


def _encode_frame(obj) -> bytes:
    return json.dumps(obj, default=_frame_json_default).encode()


def _send_frame(sock, obj) -> None:
    """`obj` may be pre-encoded bytes (a caller that already probed
    serializability) or any JSON-able value."""
    import struct
    payload = obj if isinstance(obj, bytes) else _encode_frame(obj)
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_frame(sock):
    import struct
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack(">I", hdr)
    if n > MAX_FRAME_BYTES:
        raise ConnectionError(f"cache frame of {n} bytes exceeds the "
                              f"{MAX_FRAME_BYTES}-byte bound")
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return json.loads(body.decode())


def _recv_exact(sock, n: int):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class CacheConfig:
    """Which levels populate/use cache (reference: CacheConfig +
    CacheStrategy.isCacheable per query type)."""

    UNCACHEABLE = {"scan", "select", "dataSourceMetadata"}

    def __init__(self, use_segment_cache: bool = True,
                 populate_segment_cache: bool = True,
                 use_result_cache: bool = True,
                 populate_result_cache: bool = True):
        self.use_segment_cache = use_segment_cache
        self.populate_segment_cache = populate_segment_cache
        self.use_result_cache = use_result_cache
        self.populate_result_cache = populate_result_cache

    def cacheable(self, query) -> bool:
        return query.query_type not in self.UNCACHEABLE


def query_cache_key(query) -> str:
    """Canonical per-query cache key from the wire format, excluding
    context (reference: per-toolchest computeCacheKey)."""
    j = query.to_json()
    j.pop("context", None)
    return json.dumps(j, sort_keys=True)


def result_level_key(query, segment_versions: Sequence[str]) -> str:
    """Result-level key: query + exact segment-id/version set (etag)."""
    return query_cache_key(query) + "|" + ",".join(sorted(segment_versions))
