"""Query caches.

Reference analogs: client/cache/Cache.java SPI with Caffeine local cache
(client/cache/CaffeineCache.java) + CacheConfig; used at the segment level
by the historical's CachingQueryRunner and at the result level by the
broker's ResultLevelCachingQueryRunner. Cache keys come from per-query-type
CacheStrategy (query/CacheStrategy.java).

Here: an LRU local cache keyed by (namespace, key). Segment-level entries
hold per-segment partial states (exact merges — the analog of caching
non-finalized per-segment results); result-level entries hold final rows,
keyed by the query plus the exact segment-version set so any timeline
change (new version, compaction) invalidates naturally (the reference's
etag mechanism).
"""
from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple


class CacheStats:
    def __init__(self):
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.puts = 0


class LruCache:
    """Thread-safe LRU with entry-count bound (Cache SPI analog)."""

    def __init__(self, max_entries: int = 10_000):
        self.max_entries = max_entries
        self._data: "OrderedDict[Tuple[str, str], object]" = OrderedDict()
        self._lock = threading.Lock()
        self.stats = CacheStats()

    def get(self, namespace: str, key: str):
        with self._lock:
            k = (namespace, key)
            if k in self._data:
                self._data.move_to_end(k)
                self.stats.hits += 1
                return self._data[k]
            self.stats.misses += 1
            return None

    def put(self, namespace: str, key: str, value) -> None:
        with self._lock:
            k = (namespace, key)
            self._data[k] = value
            self._data.move_to_end(k)
            self.stats.puts += 1
            while len(self._data) > self.max_entries:
                self._data.popitem(last=False)
                self.stats.evictions += 1

    def invalidate_namespace(self, namespace: str) -> int:
        with self._lock:
            doomed = [k for k in self._data if k[0] == namespace]
            for k in doomed:
                del self._data[k]
            return len(doomed)

    def __len__(self):
        with self._lock:
            return len(self._data)


class CacheConfig:
    """Which levels populate/use cache (reference: CacheConfig +
    CacheStrategy.isCacheable per query type)."""

    UNCACHEABLE = {"scan", "select", "dataSourceMetadata"}

    def __init__(self, use_segment_cache: bool = True,
                 populate_segment_cache: bool = True,
                 use_result_cache: bool = True,
                 populate_result_cache: bool = True):
        self.use_segment_cache = use_segment_cache
        self.populate_segment_cache = populate_segment_cache
        self.use_result_cache = use_result_cache
        self.populate_result_cache = populate_result_cache

    def cacheable(self, query) -> bool:
        return query.query_type not in self.UNCACHEABLE


def query_cache_key(query) -> str:
    """Canonical per-query cache key from the wire format, excluding
    context (reference: per-toolchest computeCacheKey)."""
    j = query.to_json()
    j.pop("context", None)
    return json.dumps(j, sort_keys=True)


def result_level_key(query, segment_versions: Sequence[str]) -> str:
    """Result-level key: query + exact segment-id/version set (etag)."""
    return query_cache_key(query) + "|" + ",".join(sorted(segment_versions))
