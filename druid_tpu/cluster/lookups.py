"""Cluster-wide lookup management: coordinator-owned specs, node sync.

Reference analog: server/src/main/java/org/apache/druid/server/lookup/cache/
LookupCoordinatorManager.java — lookup definitions live in the metadata
store keyed by TIER; the coordinator pushes them to every node in that
tier; nodes apply version-gated updates into their process-local
LookupReferencesManager (query/lookup.py). A node that (re)starts syncs to
the current spec set on its next poll — the same convergence contract as
the reference's periodic lookup management loop.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence

from druid_tpu.cluster.metadata import MetadataStore
from druid_tpu.query.lookup import LookupReferencesManager
from druid_tpu.utils.intervals import parse_period_ms

_CONFIG_KEY = "lookups"

#: extractionNamespace type → loader(namespace_spec) -> Dict[str, str]
#: (the lookups-cached-global extension registers "uri" here)
_NAMESPACE_LOADERS: Dict[str, object] = {}


def register_namespace_loader(type_name: str, loader) -> None:
    _NAMESPACE_LOADERS[type_name] = loader


def _period_seconds(val) -> float:
    """pollPeriod as seconds: numbers pass through, ISO-8601 periods
    ("PT5M") parse like the reference's Period configs; anything
    malformed disables periodic refresh for THAT lookup instead of
    crashing the whole poll."""
    if val is None:
        return 0.0
    try:
        return float(val)
    except (TypeError, ValueError):
        pass
    try:
        return parse_period_ms(str(val)) / 1000.0
    except (TypeError, ValueError):
        return 0.0


class LookupCoordinatorManager:
    """Authoritative lookup spec store + push loop."""

    def __init__(self, metadata: MetadataStore):
        self.metadata = metadata
        self._lock = threading.Lock()

    # ---- spec CRUD (POST /druid/coordinator/v1/lookups analog) ---------
    def _load(self) -> Dict[str, Dict[str, dict]]:
        return self.metadata.get_config(_CONFIG_KEY, {}) or {}

    def _store(self, specs: Dict[str, Dict[str, dict]]) -> None:
        self.metadata.set_config(_CONFIG_KEY, specs)

    def _next_version(self, tier_specs: Dict[str, dict], name: str,
                      version: Optional[str]) -> str:
        if version is not None:
            return version
        cur = tier_specs.get(name, {}).get("version")
        return f"v{int(cur[1:]) + 1}" \
            if cur and cur[0] == "v" and cur[1:].isdigit() else \
            (f"v{int(time.time() * 1000)}" if cur else "v0")

    def set_lookup(self, tier: str, name: str, mapping: Dict[str, str],
                   version: Optional[str] = None) -> str:
        """Create/update one lookup; bumps the version unless given."""
        with self._lock:
            specs = self._load()
            tier_specs = specs.setdefault(tier, {})
            version = self._next_version(tier_specs, name, version)
            tier_specs[name] = {"version": version,
                                "lookupExtractorFactory": {
                                    "type": "map", "map": dict(mapping)}}
            self._store(specs)
            return version

    def set_namespace_lookup(self, tier: str, name: str, namespace: dict,
                             version: Optional[str] = None) -> str:
        """Register a namespace-backed lookup (reference: the
        lookups-cached-global cachedNamespace factory): nodes materialize
        the map by running the namespace's registered loader and re-poll it
        every `pollPeriod` seconds."""
        with self._lock:
            specs = self._load()
            tier_specs = specs.setdefault(tier, {})
            version = self._next_version(tier_specs, name, version)
            tier_specs[name] = {"version": version,
                                "lookupExtractorFactory": {
                                    "type": "cachedNamespace",
                                    "extractionNamespace": dict(namespace)}}
            self._store(specs)
            return version

    def delete_lookup(self, tier: str, name: str) -> bool:
        with self._lock:
            specs = self._load()
            if name not in specs.get(tier, {}):
                return False
            del specs[tier][name]
            self._store(specs)
            return True

    def get_tier(self, tier: str) -> Dict[str, dict]:
        return dict(self._load().get(tier, {}))

    def tiers(self) -> List[str]:
        return sorted(self._load())


class LookupNodeSync:
    """Node-side sync: pull the tier's specs and apply version-gated
    updates into the local registry (LookupReferencesManager start-and-
    listen behavior). Call poll() from the node's periodic loop."""

    def __init__(self, manager: LookupCoordinatorManager, tier: str,
                 registry: LookupReferencesManager):
        self.manager = manager
        self.tier = tier
        self.registry = registry
        self._owner = f"lookup-sync:{tier}"
        self._ns_loaded: Dict[str, float] = {}   # name → last load ts

    def poll(self) -> int:
        """Apply current specs; returns how many lookups changed.

        Authority follows the registry's explicit `owner` field: this sync
        only ever replaces or deletes entries it owns. Process-local
        register_lookup() entries (owner None) and other tiers' entries
        are untouchable — a name collision means the first writer wins
        and the spec is skipped."""
        import re
        specs = self.manager.get_tier(self.tier)
        changed = 0
        for name, spec in specs.items():
            factory = spec.get("lookupExtractorFactory", {})
            version = spec.get("version", "v0")
            cur = self.registry.get(name)
            if cur is not None and cur.owner != self._owner:
                continue          # not ours: never overwrite, never load
            if factory.get("type") == "map":
                if cur is not None and re.search(r"\+\d{9}$", cur.version):
                    # converting a namespace lookup (reload-STAMP version,
                    # ours by the owner check) back to a plain map: even an
                    # identical spec version would be outranked by its own
                    # longer stamp — swap atomically, no unregistered gap
                    if self.registry.force_replace(
                            name, factory.get("map", {}), version,
                            self._owner):
                        self._ns_loaded.pop(name, None)
                        changed += 1
                elif self.registry.add(name, factory.get("map", {}),
                                       version=version, owner=self._owner):
                    changed += 1
            elif factory.get("type") == "cachedNamespace":
                if self._poll_namespace(name, factory, version, cur):
                    changed += 1
        for name in self.registry.names():
            if name in specs:
                continue
            cur = self.registry.get(name)
            if cur is not None and cur.owner == self._owner:
                self.registry.remove(name)
                self._ns_loaded.pop(name, None)
                changed += 1
        return changed

    def _poll_namespace(self, name: str, factory: dict, version: str,
                        cur) -> bool:
        """(Re)load a namespace-backed lookup when the spec version moved
        or pollPeriod elapsed. A failed load KEEPS the last good mapping
        (the reference's cached-namespace behavior). `cur` is this sync's
        own entry or None (foreign entries were filtered by the caller)."""
        ns = factory.get("extractionNamespace", {})
        loader = _NAMESPACE_LOADERS.get(str(ns.get("type")))
        if loader is None:
            return False          # extension not loaded on this node
        import re
        period = _period_seconds(ns.get("pollPeriod"))
        now = time.time()
        last = self._ns_loaded.get(name)
        stamp = None if cur is None else re.match(
            rf"^{re.escape(version)}\+(\d{{9}})$", cur.version)
        spec_changed = stamp is None
        # `last is None` counts as due: a recreated sync over a registry
        # that already holds the lookup must still honor pollPeriod
        due = spec_changed or (period > 0
                               and (last is None or now - last >= period))
        if not due:
            return False
        try:
            mapping = loader(ns)
        except Exception:
            # keep serving the last good mapping
            logging.getLogger(__name__).warning(
                "namespace load for lookup [%s] failed; keeping previous "
                "mapping", name, exc_info=True)
            return False
        self._ns_loaded[name] = now
        if not spec_changed and cur is not None \
                and mapping == cur.mapping:
            return False          # unchanged content: no registry churn
        if spec_changed and cur is not None:
            # our entry under an older spec version: swap atomically (the
            # old stamp could outrank the new version string, and a
            # remove+add gap would briefly 404 concurrent get_lookup())
            return self.registry.force_replace(
                name, mapping, f"{version}+{0:09d}", self._owner)
        # stamped reload counter keeps periodic refreshes version-ascending
        n = 0 if spec_changed else int(stamp.group(1)) + 1
        return self.registry.add(name, mapping,
                                 version=f"{version}+{n:09d}",
                                 owner=self._owner)
