"""Cluster-wide lookup management: coordinator-owned specs, node sync.

Reference analog: server/src/main/java/org/apache/druid/server/lookup/cache/
LookupCoordinatorManager.java — lookup definitions live in the metadata
store keyed by TIER; the coordinator pushes them to every node in that
tier; nodes apply version-gated updates into their process-local
LookupReferencesManager (query/lookup.py). A node that (re)starts syncs to
the current spec set on its next poll — the same convergence contract as
the reference's periodic lookup management loop.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from druid_tpu.cluster.metadata import MetadataStore
from druid_tpu.query.lookup import LookupReferencesManager

_CONFIG_KEY = "lookups"


class LookupCoordinatorManager:
    """Authoritative lookup spec store + push loop."""

    def __init__(self, metadata: MetadataStore):
        self.metadata = metadata
        self._lock = threading.Lock()

    # ---- spec CRUD (POST /druid/coordinator/v1/lookups analog) ---------
    def _load(self) -> Dict[str, Dict[str, dict]]:
        return self.metadata.get_config(_CONFIG_KEY, {}) or {}

    def _store(self, specs: Dict[str, Dict[str, dict]]) -> None:
        self.metadata.set_config(_CONFIG_KEY, specs)

    def set_lookup(self, tier: str, name: str, mapping: Dict[str, str],
                   version: Optional[str] = None) -> str:
        """Create/update one lookup; bumps the version unless given."""
        with self._lock:
            specs = self._load()
            tier_specs = specs.setdefault(tier, {})
            if version is None:
                cur = tier_specs.get(name, {}).get("version")
                version = f"v{int(cur[1:]) + 1}" \
                    if cur and cur[0] == "v" and cur[1:].isdigit() else \
                    (f"v{int(time.time() * 1000)}" if cur else "v0")
            tier_specs[name] = {"version": version,
                                "lookupExtractorFactory": {
                                    "type": "map", "map": dict(mapping)}}
            self._store(specs)
            return version

    def delete_lookup(self, tier: str, name: str) -> bool:
        with self._lock:
            specs = self._load()
            if name not in specs.get(tier, {}):
                return False
            del specs[tier][name]
            self._store(specs)
            return True

    def get_tier(self, tier: str) -> Dict[str, dict]:
        return dict(self._load().get(tier, {}))

    def tiers(self) -> List[str]:
        return sorted(self._load())


class LookupNodeSync:
    """Node-side sync: pull the tier's specs and apply version-gated
    updates into the local registry (LookupReferencesManager start-and-
    listen behavior). Call poll() from the node's periodic loop."""

    def __init__(self, manager: LookupCoordinatorManager, tier: str,
                 registry: LookupReferencesManager):
        self.manager = manager
        self.tier = tier
        self.registry = registry

    def poll(self) -> int:
        """Apply current specs; returns how many lookups changed."""
        specs = self.manager.get_tier(self.tier)
        changed = 0
        for name, spec in specs.items():
            factory = spec.get("lookupExtractorFactory", {})
            if factory.get("type") != "map":
                continue
            if self.registry.add(name, factory.get("map", {}),
                                 version=spec.get("version", "v0")):
                changed += 1
        # drop local lookups the coordinator no longer defines
        for name in self.registry.names():
            if name not in specs:
                self.registry.remove(name)
                changed += 1
        return changed
