"""Coordinator: the cluster control loop.

Reference analogs (server/src/main/java/org/apache/druid/server/coordinator/):
  DruidCoordinator.java:95            — leader control loop
  helper/DruidCoordinatorRuleRunner   — apply load/drop rules
  rules/LoadRule.java, PeriodLoadRule, IntervalLoadRule, ForeverLoadRule,
  *DropRule                           — retention rules
  CostBalancerStrategy.java           — segment placement cost
  helper/DruidCoordinatorBalancer     — move segments between nodes
  ReplicationThrottler.java           — bound replica creation per run
  "markAsUnusedOvershadowedSegments"  — MVCC cleanup of overshadowed versions
  CoordinatorDynamicConfig.java       — runtime knobs

One `run_once()` = one coordinator period. Segments are pulled from a
`segment_source` (the deep-storage puller analog — see
druid_tpu/storage/format.py for the on-disk source) and announced into the
InventoryView, which is what the broker routes by.
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from druid_tpu.cluster.metadata import MetadataStore, SegmentDescriptor
from druid_tpu.cluster.timeline import PartitionChunk, VersionedIntervalTimeline
from druid_tpu.cluster.view import DataNode, InventoryView
from druid_tpu.cluster.shardspec import NoneShardSpec
from druid_tpu.data.segment import Segment
from druid_tpu.utils.intervals import Interval

MS_PER_DAY = 86_400_000


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

class Rule:
    def applies(self, d: SegmentDescriptor, now_ms: int) -> bool:
        raise NotImplementedError

    def is_load(self) -> bool:
        raise NotImplementedError

    tiered_replicants: Dict[str, int] = {}


@dataclass
class ForeverLoadRule(Rule):
    tiered_replicants: Dict[str, int] = field(
        default_factory=lambda: {"_default_tier": 2})

    def applies(self, d, now_ms):
        return True

    def is_load(self):
        return True


@dataclass
class PeriodLoadRule(Rule):
    """Load segments younger than `period_ms` (measured from segment
    interval end to now — reference PeriodLoadRule.appliesTo)."""
    period_ms: int = 30 * MS_PER_DAY
    include_future: bool = True
    tiered_replicants: Dict[str, int] = field(
        default_factory=lambda: {"_default_tier": 2})

    def applies(self, d, now_ms):
        if d.interval.end >= now_ms - self.period_ms:
            return self.include_future or d.interval.start <= now_ms
        return False

    def is_load(self):
        return True


@dataclass
class IntervalLoadRule(Rule):
    interval: Interval = None
    tiered_replicants: Dict[str, int] = field(
        default_factory=lambda: {"_default_tier": 2})

    def applies(self, d, now_ms):
        return self.interval.contains_interval(d.interval)

    def is_load(self):
        return True


@dataclass
class ForeverDropRule(Rule):
    def applies(self, d, now_ms):
        return True

    def is_load(self):
        return False


@dataclass
class PeriodDropRule(Rule):
    """Drop segments entirely older than `period_ms`."""
    period_ms: int = 365 * MS_PER_DAY

    def applies(self, d, now_ms):
        return d.interval.end < now_ms - self.period_ms

    def is_load(self):
        return False


@dataclass
class IntervalDropRule(Rule):
    interval: Interval = None

    def applies(self, d, now_ms):
        return self.interval.contains_interval(d.interval)

    def is_load(self):
        return False


def rule_from_json(j: dict) -> Rule:
    t = j["type"]
    reps = j.get("tieredReplicants", {"_default_tier": 2})
    if t == "loadForever":
        return ForeverLoadRule(dict(reps))
    if t == "loadByPeriod":
        return PeriodLoadRule(int(j.get("periodMs", 30 * MS_PER_DAY)),
                              j.get("includeFuture", True), dict(reps))
    if t == "loadByInterval":
        return IntervalLoadRule(Interval.parse(j["interval"]), dict(reps))
    if t == "dropForever":
        return ForeverDropRule()
    if t == "dropByPeriod":
        return PeriodDropRule(int(j.get("periodMs", 365 * MS_PER_DAY)))
    if t == "dropByInterval":
        return IntervalDropRule(Interval.parse(j["interval"]))
    raise ValueError(f"unknown rule type {t!r}")


DEFAULT_RULES = [ForeverLoadRule()]


# ---------------------------------------------------------------------------
# Placement cost (CostBalancerStrategy)
# ---------------------------------------------------------------------------

_HALF_LIFE_MS = 7 * MS_PER_DAY


def _interval_cost(a: Interval, b: Interval) -> float:
    """Exponential-decay proximity cost between two segment intervals —
    co-locating temporally-close segments is expensive because queries hit
    them together (the insight of CostBalancerStrategy.computeJointSegmentsCost)."""
    gap = max(b.start - a.end, a.start - b.end, 0)
    return math.exp(-gap / _HALF_LIFE_MS)


def placement_cost(d: SegmentDescriptor, server_segments:
                   Sequence[SegmentDescriptor]) -> float:
    cost = 0.0
    for s in server_segments:
        c = _interval_cost(d.interval, s.interval)
        if s.datasource == d.datasource:
            c *= 2.0
        cost += c
    return cost


@dataclass
class DynamicConfig:
    """CoordinatorDynamicConfig analog."""
    max_segments_to_move: int = 5
    replication_throttle_limit: int = 10
    max_non_primary_replicants: int = 10_000
    max_segments_in_node_loading_queue: int = 100


@dataclass
class CoordinatorStats:
    assigned: int = 0
    dropped: int = 0
    moved: int = 0
    overshadowed_marked: int = 0
    deleted: int = 0
    unassigned: int = 0
    nodes_removed: int = 0
    #: True when the cycle was a no-op because this node is not the leader
    skipped_not_leader: bool = False
    #: fencing term the cycle's writes carried (-1 when unfenced)
    leader_term: int = -1


class Coordinator:
    """Leader-elected control loop. With a `leader` participant attached
    (coordination.LeaderParticipant — the CuratorDruidLeaderSelector
    analog) the duty cycle runs ONLY while holding the lease, and every
    metadata write carries the lease's fencing term so a deposed
    coordinator's in-flight writes are rejected by the store
    (StaleTermError) instead of corrupting its successor's state."""

    def __init__(self, metadata: MetadataStore, view: InventoryView,
                 segment_source: Callable[[SegmentDescriptor], Segment],
                 config: Optional[DynamicConfig] = None,
                 async_loading: bool = False,
                 leader=None):
        """async_loading=True assigns loads through per-server
        LoadQueuePeons (bounded queues, background workers) instead of
        blocking the cycle on each segment pull — the reference's
        LoadQueuePeon model. The announcement then happens when the worker
        finishes, so a load counts as `assigned` when enqueued."""
        self.metadata = metadata
        self.view = view
        self.segment_source = segment_source
        self.config = config or DynamicConfig()
        self.async_loading = async_loading
        self.leader = leader
        self._peons: Dict[str, "LoadQueuePeon"] = {}

    def _fence(self) -> Optional[tuple]:
        return self.leader.fence() if self.leader is not None else None

    def _peon_for(self, node: DataNode) -> "LoadQueuePeon":
        from druid_tpu.cluster.loadqueue import LoadQueuePeon
        peon = self._peons.get(node.name)
        if peon is None:
            peon = self._peons[node.name] = LoadQueuePeon(
                node, self.view, self.segment_source,
                max_queue_size=self.config.max_segments_in_node_loading_queue)
        return peon

    def wait_loads(self, timeout: float = 30.0) -> bool:
        """Drain every peon queue (tests / controlled handover)."""
        return all(p.wait_idle(timeout) for p in self._peons.values())

    def stop(self) -> None:
        for p in self._peons.values():
            p.stop()

    # ---- one coordinator period ---------------------------------------
    def run_once(self, now_ms: Optional[int] = None) -> CoordinatorStats:
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        stats = CoordinatorStats()
        if self.leader is not None:
            # duty loops idle entirely on non-leaders — not even liveness
            # probes run, so a standby is invisible to the cluster until
            # promoted (DruidCoordinator.coordinatorLeaderSelector gating)
            if not self.leader.is_leader():
                stats.skipped_not_leader = True
                return stats
            stats.leader_term = self.leader.term
        # failure detection first: dead servers leave the view (their
        # announcements retract), so this same cycle's rule run sees the
        # replica deficit and re-replicates from deep storage
        dead = self.view.check_liveness()
        stats.nodes_removed = len(dead)
        for name in dead:
            # a removed server's peon must stop, or its queued loads would
            # ghost-announce for a node no broker can reach. No join: a
            # worker mid-pull must not stall failure detection (the worker
            # already refuses to announce for unregistered nodes)
            peon = self._peons.pop(name, None)
            if peon is not None:
                peon.stop(join=False)
        self._mark_overshadowed(stats)
        used = self.metadata.used_segments()
        self._run_rules(used, now_ms, stats)
        self._balance(stats)
        return stats

    # ---- MVCC cleanup ---------------------------------------------------
    def _mark_overshadowed(self, stats: CoordinatorStats) -> None:
        """Build a metadata timeline per datasource and mark fully
        overshadowed segments unused (atomic replacement completion)."""
        by_ds: Dict[str, List[SegmentDescriptor]] = {}
        for d in self.metadata.used_segments():
            by_ds.setdefault(d.datasource, []).append(d)
        for ds, descs in by_ds.items():
            tl: VersionedIntervalTimeline = VersionedIntervalTimeline()
            for d in descs:
                spec = d.shard_spec or NoneShardSpec(d.partition)
                tl.add(d.interval, d.version, PartitionChunk(spec, d))
            doomed = []
            for holder in tl.find_fully_overshadowed():
                doomed += [c.obj.id for c in holder.partitions]
            if doomed:
                stats.overshadowed_marked += self.metadata.mark_unused(
                    doomed, fence=self._fence())

    # ---- rules ----------------------------------------------------------
    def _rules_for(self, datasource: str) -> List[Rule]:
        payload = self.metadata.rules_for(datasource)
        if not payload:
            return list(DEFAULT_RULES)
        return [rule_from_json(j) for j in payload]

    def _nodes_by_tier(self) -> Dict[str, List[DataNode]]:
        # only segment-replicatable (historical) servers participate in
        # rule-driven load/drop/balancing — realtime servers announce their
        # own in-flight sinks and manage their own lifecycle (the reference's
        # DruidCluster keeps realtime servers out of coordinator duties)
        tiers: Dict[str, List[DataNode]] = {}
        for n in self.view.nodes():
            if getattr(n, "segment_replicatable", True):
                tiers.setdefault(n.tier, []).append(n)
        return tiers

    def _run_rules(self, used: List[SegmentDescriptor], now_ms: int,
                   stats: CoordinatorStats) -> None:
        tiers = self._nodes_by_tier()
        served_by: Dict[str, List[SegmentDescriptor]] = {
            n.name: self.view.served_segments(n.name)
            for ns in tiers.values() for n in ns}
        # one pending-set snapshot per peon per cycle (not one lock take
        # per segment x peon)
        pending_by_server = {name: peon.pending_ids()
                             for name, peon in self._peons.items()} \
            if self.async_loading else {}
        replicas_created = 0
        rules_cache: Dict[str, List[Rule]] = {}
        for d in used:
            rules = rules_cache.get(d.datasource)
            if rules is None:
                rules = rules_cache[d.datasource] = \
                    self._rules_for(d.datasource)
            rule = next((r for r in rules if r.applies(d, now_ms)), None)
            if rule is None or not rule.is_load():
                # drop from every HISTORICAL server holding it; a realtime
                # server's sink announcement is its own to retract (handoff)
                rs = self.view.replica_set(d.id)
                if rs is not None:
                    for server in sorted(rs.servers):
                        node = self.view.node(server)
                        if node is not None and \
                                not getattr(node, "segment_replicatable", True):
                            continue
                        if node is not None:
                            node.drop_segment(d.id)
                        self.view.unannounce(server, d.id)
                        stats.dropped += 1
                continue
            rs = self.view.replica_set(d.id)
            announced = set(rs.servers) if rs is not None else set()
            holders = set(announced)
            pending_holders = set()
            if self.async_loading:
                # an enqueued-but-unannounced load counts as a holder, or
                # every cycle until the worker finishes would pile extra
                # replicas onto OTHER nodes (currentlyLoading accounting)
                pending_holders = {name for name, ids in pending_by_server
                                   .items() if d.id in ids}
                holders |= pending_holders
            for tier, wanted in rule.tiered_replicants.items():
                nodes = tiers.get(tier, [])
                tier_holders = [n for n in nodes if n.name in holders]
                deficit = wanted - len(tier_holders)
                # drop excess ANNOUNCED replicas — but never while a load
                # for this segment is in flight: the "excess" may be a
                # balancer move's still-serving source, and dropping it
                # opens a zero-replica window until (or forever if) the
                # destination's load completes
                droppable = [] if pending_holders else \
                    [n for n in tier_holders if n.name in announced]
                while deficit < 0 and droppable:
                    victim = droppable.pop()
                    victim.drop_segment(d.id)
                    self.view.unannounce(victim.name, d.id)
                    served_by[victim.name] = [
                        s for s in served_by[victim.name] if s.id != d.id]
                    stats.dropped += 1
                    deficit += 1
                # assign missing replicas, throttled
                candidates = [n for n in nodes if n.name not in holders]
                while deficit > 0 and candidates:
                    is_primary = not holders
                    if not is_primary and \
                            replicas_created >= self.config.replication_throttle_limit:
                        break
                    best = min(candidates, key=lambda n: placement_cost(
                        d, served_by[n.name]))
                    if not self._load_on(best, d):
                        candidates.remove(best)
                        continue
                    served_by[best.name].append(d)
                    holders.add(best.name)
                    candidates.remove(best)
                    stats.assigned += 1
                    if not is_primary:
                        replicas_created += 1
                    deficit -= 1
                if deficit > 0:
                    stats.unassigned += deficit

    def _load_on(self, node: DataNode, d: SegmentDescriptor) -> bool:
        if self.async_loading:
            # enqueue-and-continue: the peon's worker pulls, loads, and
            # announces; a full queue defers to the next cycle
            return self._peon_for(node).load(d)
        segment = self.segment_source(d)
        if segment is None or not node.load_segment(segment, d):
            return False
        self.view.announce(node.name, d)
        return True

    # ---- balancing ------------------------------------------------------
    def _balance(self, stats: CoordinatorStats) -> None:
        """Move segments from loaded → underloaded nodes within a tier,
        min-cost placement (DruidCoordinatorBalancer + CostBalancerStrategy)."""
        for tier, nodes in self._nodes_by_tier().items():
            if len(nodes) < 2:
                continue
            moves_left = self.config.max_segments_to_move
            in_flight_out: Dict[str, int] = {}
            while moves_left > 0:
                # async: a scheduled move means src WILL lose one and dst
                # WILL gain one — account for it, or a gated worker makes
                # the stale counts re-move everything src holds
                counts = {}
                for n in nodes:
                    c = n.segment_count() - in_flight_out.get(n.name, 0)
                    if self.async_loading and n.name in self._peons:
                        c += self._peons[n.name].pending_count()
                    counts[n.name] = c
                src = max(nodes, key=lambda n: counts[n.name])
                dst = min(nodes, key=lambda n: counts[n.name])
                if counts[src.name] - counts[dst.name] < 2:
                    break
                dst_served = self.view.served_segments(dst.name)
                dst_ids = {d.id for d in dst_served}
                movable = [d for d in self.view.served_segments(src.name)
                           if d.id not in dst_ids]
                if self.async_loading:
                    dst_peon = self._peon_for(dst)
                    movable = [m for m in movable
                               if not dst_peon.is_pending(m.id)]
                if not movable:
                    break
                d = min(movable,
                        key=lambda m: placement_cost(m, dst_served))
                if self.async_loading:
                    # load-then-drop: the source replica must stay
                    # announced until the destination's worker FINISHES —
                    # dropping on enqueue would leave a window (or, on a
                    # failed load, an eternity) with zero replicas
                    def after(ok, s=src, dd=d):
                        if ok:
                            s.drop_segment(dd.id)
                            self.view.unannounce(s.name, dd.id)
                    if not self._peon_for(dst).load(d, callback=after):
                        break
                    in_flight_out[src.name] = \
                        in_flight_out.get(src.name, 0) + 1
                else:
                    if not self._load_on(dst, d):
                        break
                    src.drop_segment(d.id)
                    self.view.unannounce(src.name, d.id)
                stats.moved += 1
                moves_left -= 1

    # ---- auto-compaction (DruidCoordinatorSegmentCompactor +
    # NewestSegmentFirstPolicy) -------------------------------------------
    def schedule_compaction(self, overlord, datasource: str,
                            metric_specs,
                            min_segments_per_bucket: int = 2,
                            max_tasks: int = 1) -> List[str]:
        """Submit CompactionTasks for the newest intervals fragmented into
        >= min_segments_per_bucket MVCC-visible segments."""
        from druid_tpu.indexing.task import CompactionTask
        by_bucket: Dict[Tuple[int, int], List[SegmentDescriptor]] = {}
        for d in self.metadata.visible_segments(datasource):
            by_bucket.setdefault((d.interval.start, d.interval.end),
                                 []).append(d)
        candidates = sorted(
            (b for b, descs in by_bucket.items()
             if len(descs) >= min_segments_per_bucket),
            key=lambda b: -b[0])    # newest first
        out = []
        for start, end in candidates[:max_tasks]:
            task = CompactionTask(datasource, Interval(start, end),
                                  metric_specs)
            out.append(overlord.submit(task))
        return out

    # ---- kill (permanent deletion of unused segments) -------------------
    def kill_unused(self, datasource: str) -> int:
        """KillTask analog: permanently delete unused segments' metadata."""
        ids = [d.id for d in self.metadata.unused_segments(datasource)]
        return self.metadata.delete_segments(ids, fence=self._fence())
