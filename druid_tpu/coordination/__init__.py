"""HA coordination: lease-based leader election, fencing, discovery.

The control-plane counterpart of the reference's DruidLeaderSelector /
CuratorDruidLeaderSelector / DruidLeaderClient triple, backed by the SQL
metadata store instead of ZooKeeper, with fencing terms enforced at the
metadata-write layer and a chaos harness for failover testing.
"""
from druid_tpu.coordination.chaos import (ChaosHarness, ManualClock,
                                          PartitionedError)
from druid_tpu.coordination.discovery import LeaderClient, NoLeaderError
from druid_tpu.coordination.latch import (LeaderLease, LeaderMonitor,
                                          LeaderParticipant, LeaseStore,
                                          MetadataLeaseStore, NotLeaderError,
                                          StaleTermError)

__all__ = [
    "ChaosHarness", "ManualClock", "PartitionedError",
    "LeaderClient", "NoLeaderError",
    "LeaderLease", "LeaderMonitor", "LeaderParticipant", "LeaseStore",
    "MetadataLeaseStore", "NotLeaderError", "StaleTermError",
]
