"""Leader discovery + request redirect for clients.

Reference analogs:
  discovery/DruidLeaderClient.java — clients of the coordinator/overlord
    APIs resolve the current leader, send there, and on a redirect or
    connection failure re-resolve and retry (the HTTP 307 dance every
    non-leader coordinator/overlord answers with)
  server/http/security + CliBroker wiring — resolution is cheap reads of
    the same lease row the latch heartbeats through, never a query-path
    dependency.
"""
from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

from druid_tpu.coordination.latch import LeaderLease, LeaseStore

log = logging.getLogger(__name__)


class NoLeaderError(RuntimeError):
    """No live leader for the service after the configured retries."""


class LeaderClient:
    """Resolve + talk to the current leader of one service.

    The resolved URL is cached and invalidated on failure/redirect, so the
    common case is zero extra store reads per request (DruidLeaderClient
    caches `currentKnownLeader` the same way)."""

    def __init__(self, store: LeaseStore, service: str,
                 clock: Optional[Callable[[], int]] = None):
        self.store = store
        self.service = service
        self.clock = clock or (lambda: int(time.time() * 1000))
        self._cached_url: Optional[str] = None

    def leader(self) -> Optional[LeaderLease]:
        """The current UNEXPIRED lease, or None (election in progress)."""
        try:
            lease = self.store.read(self.service)
        except Exception:
            log.debug("lease read for [%s] failed; reporting no leader",
                      self.service, exc_info=True)
            return None
        if lease is None or self.clock() >= lease.expires_ms:
            return None
        return lease

    def leader_url(self, use_cache: bool = True) -> Optional[str]:
        if use_cache and self._cached_url is not None:
            return self._cached_url
        lease = self.leader()
        self._cached_url = lease.url if lease is not None else None
        return self._cached_url

    def invalidate(self) -> None:
        self._cached_url = None

    def request(self, send: Callable[[str], object], retries: int = 3,
                backoff_s: float = 0.05):
        """Run `send(leader_url)`, re-resolving and retrying on connection
        failures — the pattern DruidLeaderClient.go implements over HTTP,
        transport-agnostic here so in-process targets work too."""
        last: Optional[BaseException] = None
        for attempt in range(retries):
            url = self.leader_url(use_cache=(attempt == 0))
            if url is None:
                last = NoLeaderError(
                    f"no live leader for [{self.service}]")
            else:
                try:
                    return send(url)
                except urllib.error.HTTPError:
                    # a definitive HTTP answer FROM the leader (404/403/
                    # 500…) is the caller's to see — retrying would re-send
                    # non-idempotent requests a live leader already judged
                    raise
                except (ConnectionError, OSError, urllib.error.URLError) as e:
                    last = e
            self.invalidate()
            if attempt < retries - 1 and backoff_s:
                time.sleep(backoff_s * (attempt + 1))
        if isinstance(last, NoLeaderError):
            raise last
        raise NoLeaderError(
            f"leader of [{self.service}] unreachable after {retries} "
            f"attempts: {last}")

    # ---- HTTP convenience (the literal DruidLeaderClient.go) -----------
    def go(self, path: str, payload: Optional[dict] = None,
           timeout: float = 30.0, retries: int = 3):
        """GET (payload None) or POST JSON `path` on the current leader,
        following one same-request 307 hop (a just-deposed leader redirects
        to its successor before the lease row catches up)."""

        def send(url: str):
            target = url.rstrip("/") + path
            data = None if payload is None else json.dumps(payload).encode()
            req = urllib.request.Request(
                target, data=data,
                headers={"Content-Type": "application/json"},
                method="GET" if payload is None else "POST")
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return json.loads(r.read() or b"null")
            except urllib.error.HTTPError as e:
                if e.code in (302, 307) and e.headers.get("Location"):
                    loc = e.headers["Location"]
                    base = loc.split("/druid/", 1)[0]
                    self._cached_url = base
                    req2 = urllib.request.Request(
                        loc, data=data,
                        headers={"Content-Type": "application/json"},
                        method="GET" if payload is None else "POST")
                    with urllib.request.urlopen(req2, timeout=timeout) as r:
                        return json.loads(r.read() or b"null")
                raise

        return self.request(send, retries=retries)
