"""Lease-based leader latch with fencing terms.

Reference analogs:
  discovery/DruidLeaderSelector.java        — the latch SPI: isLeader,
    localTerm, registerListener(becomeLeader/stopBeingLeader)
  curator/discovery/CuratorDruidLeaderSelector.java — the Curator
    LeaderLatch-backed impl; here the latch is a lease row in the SQL
    metadata store (no ZK in this stack), which doubles as the fencing
    authority: every ownership change mints a new monotonically increasing
    term, and metadata writes carrying an old term are rejected
    (MetadataStore.check_fence) even if the deposed leader still runs.

Safety model (TiLT-style: control plane off the query hot path):
  - liveness: a standby's heartbeat takes the lease over once it EXPIRES,
    so failover is bounded by lease_ms + one heartbeat period;
  - safety: leadership is advisory — is_leader() self-fences on the LOCAL
    clock the moment the last successful renewal is older than the lease,
    and the metadata store's term check is the hard backstop for the
    clock-skew/zombie window in between.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from druid_tpu.cluster.metadata import MetadataStore, StaleTermError  # noqa: F401  (re-export)
from druid_tpu.utils.emitter import ServiceEmitter

log = logging.getLogger(__name__)


class NotLeaderError(RuntimeError):
    """An operation that only the leader may perform reached a non-leader;
    carries the current leader's advertised location for redirect."""

    def __init__(self, message: str, leader_url: Optional[str] = None):
        super().__init__(message)
        self.leader_url = leader_url


@dataclass(frozen=True)
class LeaderLease:
    """One service's lease row: who leads, under which fencing term,
    until when (store clock), and where to reach them (advertised meta)."""
    service: str
    holder: str
    term: int
    expires_ms: int
    meta: Optional[dict] = None

    @property
    def url(self) -> Optional[str]:
        return (self.meta or {}).get("url")


class LeaseStore:
    """Pluggable lease backend (the Curator role). All methods may raise
    (store down / partition); callers treat that as a failed heartbeat."""

    def try_acquire(self, service: str, holder: str, now_ms: int,
                    lease_ms: int, meta: Optional[dict] = None
                    ) -> Optional[LeaderLease]:
        raise NotImplementedError

    def read(self, service: str) -> Optional[LeaderLease]:
        raise NotImplementedError

    def release(self, service: str, holder: str) -> bool:
        raise NotImplementedError


class MetadataLeaseStore(LeaseStore):
    """Lease rows in the SQL metadata store — the same transactional
    authority that fences writes, so term checks and lease state can never
    disagree."""

    def __init__(self, metadata: MetadataStore):
        self.metadata = metadata

    def try_acquire(self, service, holder, now_ms, lease_ms, meta=None):
        got = self.metadata.try_acquire_lease(service, holder, now_ms,
                                              lease_ms, meta)
        if got is None:
            return None
        term, expires = got
        return LeaderLease(service, holder, term, expires, meta)

    def read(self, service):
        row = self.metadata.read_lease(service)
        if row is None:
            return None
        return LeaderLease(service, row["holder"], row["term"],
                           row["expiresMs"], row["meta"])

    def release(self, service, holder):
        return self.metadata.release_lease(service, holder)


class LeaderParticipant:
    """One node's handle on a leader latch (DruidLeaderSelector analog).

    tick() is one heartbeat: acquire-or-renew the lease and fire
    become/stop listeners on transitions. start() drives tick() from a
    daemon thread at lease_ms/3; tests drive tick() manually against an
    injected clock. is_leader() self-fences on the local clock between
    ticks — an expired local lease reads as non-leader immediately, no
    store round-trip."""

    def __init__(self, store: LeaseStore, service: str, node_id: str,
                 lease_ms: int = 3_000, meta: Optional[dict] = None,
                 clock: Optional[Callable[[], int]] = None,
                 emitter: Optional[ServiceEmitter] = None):
        self.store = store
        self.service = service
        self.node_id = node_id
        self.lease_ms = int(lease_ms)
        self.meta = dict(meta or {})
        self.clock = clock or (lambda: int(time.time() * 1000))
        self.emitter = emitter
        self.transitions = 0           # becomeLeader + stopBeingLeader count
        self._lease: Optional[LeaderLease] = None
        self._last_renew_ms: Optional[int] = None
        self._leading = False
        self._dead = False             # chaos kill: simulated process death
        self.drop_heartbeats = False   # chaos: ticks run, renewals are lost
        self._listeners: List[tuple] = []
        self._lock = threading.RLock()
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- listener SPI (DruidLeaderSelector.Listener) -------------------
    def register_listener(self, on_become: Optional[Callable[[int], None]] = None,
                          on_stop: Optional[Callable[[], None]] = None) -> None:
        self._listeners.append((on_become, on_stop))

    # ---- state ----------------------------------------------------------
    @property
    def term(self) -> int:
        """Local term (DruidLeaderSelector.localTerm): the fencing token
        of the currently/most recently held lease; -1 before first win."""
        with self._lock:
            return self._lease.term if self._lease is not None else -1

    def fence(self) -> Optional[tuple]:
        """(service, term, holder) for fenced metadata writes — None until
        this node has ever won the latch."""
        with self._lock:
            if self._lease is None:
                return None
            return (self.service, self._lease.term, self.node_id)

    def is_leader(self) -> bool:
        """Self-fencing read: leading AND the last successful renewal is
        younger than the lease, by the LOCAL clock. Needs no store call,
        so duty loops can gate on it per-cycle for free."""
        with self._lock:
            if self._dead or not self._leading:
                return False
            if self._last_renew_ms is None:
                return False
            return self.clock() < self._last_renew_ms + self.lease_ms

    def lease_age_ms(self) -> Optional[int]:
        """Time since the last successful renewal (None if never renewed)
        — the coordination/lease/ageMs observable; age past lease_ms on a
        leader means it is about to (or already did) self-fence."""
        with self._lock:
            if self._last_renew_ms is None:
                return None
            return max(0, self.clock() - self._last_renew_ms)

    def transition_count(self) -> int:
        """Locked read of the become/stop transition counter — monitor
        ticks run on the scheduler thread while the heartbeat thread
        writes it."""
        with self._lock:
            return self.transitions

    # ---- one heartbeat ---------------------------------------------------
    def tick(self) -> bool:
        """Acquire-or-renew once; returns is_leader() after the attempt.
        A failed renewal (store unreachable, heartbeat dropped, lease taken)
        steps down as soon as the local lease expires."""
        with self._lock:
            if self._dead:
                return False
            now = self.clock()
            # pre-renew age: how stale the lease had grown by this beat —
            # the coordination/lease/ageMs observable (0 is uninteresting;
            # a value near lease_ms means renewals are being missed)
            age = None if self._last_renew_ms is None \
                else max(0, now - self._last_renew_ms)
            got: Optional[LeaderLease] = None
            if not self.drop_heartbeats:
                try:
                    got = self.store.try_acquire(
                        self.service, self.node_id, now, self.lease_ms,
                        self.meta)
                except Exception:
                    got = None        # partitioned from the lease store
                    log.warning("lease store unreachable for [%s] from "
                                "[%s]; treating as lost heartbeat",
                                self.service, self.node_id, exc_info=True)
            if got is not None:
                self._lease = got
                self._last_renew_ms = now
                if not self._leading:
                    self._leading = True
                    self._fire_transition("become", got.term)
            elif self._leading and \
                    now >= (self._last_renew_ms or 0) + self.lease_ms:
                # could not renew for a whole lease: someone may hold it now
                self._leading = False
                self._fire_transition("stop", self.term)
            if self.emitter is not None and age is not None:
                self.emitter.metric(
                    "coordination/lease/ageMs", age,
                    service=self.service, node=self.node_id,
                    leader=self._leading)
            return self.is_leader()

    def _fire_transition(self, event: str, term: int) -> None:
        # called under _lock; listener exceptions must not kill heartbeats
        self.transitions += 1
        log.info("[%s] %s %s leader (term %d)", self.service, self.node_id,
                 "became" if event == "become" else "stopped being", term)
        if self.emitter is not None:
            self.emitter.metric("coordination/leader/transitions",
                                self.transitions, service=self.service,
                                node=self.node_id, event=event, term=term)
        for on_become, on_stop in list(self._listeners):
            fn = on_become if event == "become" else on_stop
            if fn is None:
                continue
            try:
                fn(term) if event == "become" else fn()
            except Exception:
                log.exception("leader listener failed (%s)", event)

    # ---- lifecycle -------------------------------------------------------
    def start(self, period_s: Optional[float] = None) -> "LeaderParticipant":
        """Spawn the heartbeat thread (default period lease_ms/3 — two
        missable beats before the lease lapses)."""
        if self._thread is not None:
            return self
        period = period_s if period_s is not None else self.lease_ms / 3000.0
        with self._lock:
            self._dead = False        # restart after stop() rejoins
        self._stop_event.clear()

        def loop():
            self.tick()
            while not self._stop_event.wait(period):
                self.tick()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"leader-{self.service}-{self.node_id}")
        self._thread.start()
        return self

    def stop(self, release: bool = True) -> None:
        """Graceful shutdown: leave the latch (no more heartbeats, manual
        ticks no-op until start() rejoins), fire stop listeners, and (by
        default) release the lease so a standby takes over on its next
        heartbeat instead of waiting out the expiry."""
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            was_leading = self._leading
            self._leading = False
            self._dead = True
            if was_leading:
                self._fire_transition("stop", self.term)
        if release and was_leading:
            try:
                self.store.release(self.service, self.node_id)
            except Exception:
                # store down: expiry handles it
                log.debug("lease release for [%s] failed; standbys take "
                          "over on expiry", self.service, exc_info=True)

    def kill(self) -> None:
        """Simulated process death (chaos): heartbeats halt WITHOUT
        releasing the lease — exactly what a crashed leader leaves behind.
        No stop listeners fire; a dead process runs nothing."""
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            self._dead = True
            self._leading = False


class LeaderMonitor:
    """MonitorScheduler-compatible monitor: emits the participant's
    transition count and lease age each monitoring period (the
    coordination observables of the ISSUE contract)."""

    def __init__(self, participant: LeaderParticipant):
        self.participant = participant

    def do_monitor(self, emitter) -> None:
        p = self.participant
        emitter.metric("coordination/leader/transitions",
                       p.transition_count(),
                       service=p.service, node=p.node_id,
                       leader=p.is_leader())
        age = p.lease_age_ms()
        if age is not None:
            emitter.metric("coordination/lease/ageMs", age,
                           service=p.service, node=p.node_id,
                           leader=p.is_leader())
