"""Fault-injection harness for the coordination subsystem.

The harness builds LeaderParticipants whose lease-store traffic routes
through per-node fault gates, then injects the three canonical control-
plane faults:

  kill_leader()      — process death: heartbeats halt, lease NOT released
  drop_heartbeats(n) — the node runs but its renewals are lost in flight
  partition(n)       — the node is cut off from the lease store entirely
                       (every store op raises), the registry-partition case

Tests drive time with ManualClock + tick_all() so failover bounds are
asserted in LEASE INTERVALS, not wall seconds — deterministic under any
scheduler. await_leader() returns how many intervals promotion took,
which is the bounded-failover assertion of the ISSUE contract.

Reference analog: none 1:1 — Druid leans on Curator's TestingCluster for
ZK chaos (server/.../CuratorDruidLeaderSelectorTest); this plays that
role for the lease latch.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from druid_tpu.coordination.latch import (LeaderParticipant, LeaseStore,
                                          MetadataLeaseStore)


class ManualClock:
    """Deterministic ms clock shared by every participant and the store
    checks (tests advance it explicitly)."""

    def __init__(self, start_ms: int = 1_000_000):
        self._now = int(start_ms)
        self._lock = threading.Lock()

    def __call__(self) -> int:
        with self._lock:
            return self._now

    def advance(self, ms: int) -> int:
        with self._lock:
            self._now += int(ms)
            return self._now


class PartitionedError(ConnectionError):
    """The injected fault: this node cannot reach the lease store."""


class _FaultGateStore(LeaseStore):
    """Per-node view of the shared store; consults the harness's fault
    table on every call so partitions can be injected/healed live."""

    def __init__(self, inner: LeaseStore, node_id: str,
                 partitioned: Dict[str, bool]):
        self.inner = inner
        self.node_id = node_id
        self._partitioned = partitioned

    def _check(self):
        if self._partitioned.get(self.node_id):
            raise PartitionedError(
                f"[{self.node_id}] partitioned from the lease store")

    def try_acquire(self, service, holder, now_ms, lease_ms, meta=None):
        self._check()
        return self.inner.try_acquire(service, holder, now_ms, lease_ms,
                                      meta)

    def read(self, service):
        self._check()
        return self.inner.read(service)

    def release(self, service, holder):
        self._check()
        return self.inner.release(service, holder)


class ChaosHarness:
    """Builds and faults a fleet of latch participants over one store."""

    def __init__(self, store: LeaseStore, service: str,
                 lease_ms: int = 1_000,
                 clock: Optional[ManualClock] = None):
        self.store = store
        self.service = service
        self.lease_ms = int(lease_ms)
        self.clock = clock or ManualClock()
        self.participants: List[LeaderParticipant] = []
        self._partitioned: Dict[str, bool] = {}

    @classmethod
    def over_metadata(cls, metadata, service: str, lease_ms: int = 1_000,
                      clock: Optional[ManualClock] = None) -> "ChaosHarness":
        return cls(MetadataLeaseStore(metadata), service, lease_ms, clock)

    def participant(self, node_id: str, meta: Optional[dict] = None,
                    emitter=None) -> LeaderParticipant:
        gated = _FaultGateStore(self.store, node_id, self._partitioned)
        p = LeaderParticipant(gated, self.service, node_id,
                              lease_ms=self.lease_ms, meta=meta,
                              clock=self.clock, emitter=emitter)
        self.participants.append(p)
        return p

    # ---- fault injection -------------------------------------------------
    def leader(self) -> Optional[LeaderParticipant]:
        for p in self.participants:
            if p.is_leader():
                return p
        return None

    def kill_leader(self) -> LeaderParticipant:
        p = self.leader()
        if p is None:
            raise AssertionError("no leader to kill")
        p.kill()
        return p

    def kill(self, node_id: str) -> None:
        self._by_id(node_id).kill()

    def drop_heartbeats(self, node_id: str) -> None:
        self._by_id(node_id).drop_heartbeats = True

    def partition(self, node_id: str) -> None:
        self._partitioned[node_id] = True

    def heal(self, node_id: str) -> None:
        self._partitioned.pop(node_id, None)
        self._by_id(node_id).drop_heartbeats = False

    def _by_id(self, node_id: str) -> LeaderParticipant:
        for p in self.participants:
            if p.node_id == node_id:
                return p
        raise KeyError(node_id)

    # ---- deterministic driving -------------------------------------------
    def tick_all(self) -> Optional[LeaderParticipant]:
        """One heartbeat round for every live participant; returns the
        leader after the round (None mid-election)."""
        for p in self.participants:
            p.tick()
        return self.leader()

    def await_leader(self, max_intervals: int = 5,
                     ticks_per_interval: int = 3,
                     exclude: Optional[LeaderParticipant] = None) -> tuple:
        """Advance time + heartbeats until some participant OTHER than
        `exclude` leads, failing after `max_intervals` lease intervals —
        the bounded-failover assertion (exclude the deposed leader for
        heartbeat-drop/partition faults, where it legitimately stays
        leader until its lease lapses). Returns (leader,
        intervals_elapsed) with intervals a float in lease units."""
        step = self.lease_ms // ticks_per_interval or 1
        for i in range(max_intervals * ticks_per_interval + 1):
            self.tick_all()
            for p in self.participants:
                if p.is_leader() and p is not exclude:
                    return p, i * step / self.lease_ms
            self.clock.advance(step)
        raise AssertionError(
            f"no leader for [{self.service}] within {max_intervals} lease "
            f"intervals")
