"""Realtime appenderator: in-process streaming ingest + query + publish.

Reference analogs (server/src/main/java/org/apache/druid/segment/realtime/
appenderator/):
  Appenderator/AppenderatorImpl.java — manages per-segment Sinks, each a
    chain of FireHydrants (IncrementalIndexes), incremental persists,
    background merge+push
  plumber/Sink.java — hydrant chain for one segment
  StreamAppenderatorDriver.java / BaseAppenderatorDriver — the add →
    persist → publish → handoff state machine with exactly-once
    transactional publish (SegmentTransactionalInsertAction)
  SinkQuerySegmentWalker.java — makes in-flight data queryable
  SegmentAllocateAction — allocates (interval, version, partition) against
    the metadata store

TPU-first: hydrants are vectorized-rollup IncrementalIndexes whose
snapshots are ordinary immutable Segments, so realtime queries use the
exact same device kernels as historical ones — no separate realtime path.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.cluster.metadata import MetadataStore, SegmentDescriptor
from druid_tpu.cluster.shardspec import NumberedShardSpec
from druid_tpu.data.segment import Segment, SegmentId
from druid_tpu.ingest.incremental import IncrementalIndex
from druid_tpu.ingest.input import RowBatch
from druid_tpu.ingest.merger import merge_segments
from druid_tpu.query import aggregators as A
from druid_tpu.storage.deep import DeepStorage
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval


@dataclass(frozen=True)
class SegmentIdWithShard:
    """Allocated identity for an in-flight segment."""
    datasource: str
    interval: Interval
    version: str
    partition: int

    @property
    def id(self) -> str:
        return (f"{self.datasource}_{self.interval}_{self.version}"
                f"_{self.partition}")


class SegmentAllocator:
    """Allocates segment identities via the metadata store's atomic
    pending-segments transaction (SegmentAllocateAction analog): one
    (interval, version) per segment-granularity bucket; concurrent
    allocators for the same bucket receive the SAME version and unique
    partitions, so streamed appends are siblings, never overshadowing."""

    def __init__(self, metadata: MetadataStore,
                 segment_granularity: str | Granularity = "hour"):
        self.metadata = metadata
        self.granularity = (segment_granularity
                            if isinstance(segment_granularity, Granularity)
                            else Granularity.of(segment_granularity))

    def bucket(self, ts_ms: int) -> Interval:
        if self.granularity.is_all:
            raise ValueError("segmentGranularity must be uniform")
        start = self.granularity.bucket_start(ts_ms)
        return Interval(start, self.granularity.next_bucket(start))

    def allocate(self, datasource: str, ts_ms: int,
                 version: Optional[str] = None) -> SegmentIdWithShard:
        iv = self.bucket(ts_ms)
        version, part = self.metadata.allocate_segment(datasource, iv, version)
        return SegmentIdWithShard(datasource, iv, version, part)


class Sink:
    """One in-flight segment: the current writable hydrant + persisted
    (immutable snapshot) hydrants (reference: plumber/Sink.java)."""

    def __init__(self, ident: SegmentIdWithShard,
                 metric_specs: Sequence[A.AggregatorSpec],
                 dimensions: Optional[Sequence[str]],
                 query_granularity: str,
                 max_rows_per_hydrant: int):
        self.ident = ident
        self.metric_specs = list(metric_specs)
        self.dimensions = dimensions
        self.query_granularity = query_granularity
        self.max_rows_per_hydrant = max_rows_per_hydrant
        self.hydrants: List[Segment] = []      # persisted snapshots
        self.index = self._new_index()
        self.num_rows_added = 0

    def _new_index(self) -> IncrementalIndex:
        return IncrementalIndex(
            self.ident.datasource, self.ident.interval, self.metric_specs,
            dimensions=self.dimensions,
            query_granularity=self.query_granularity,
            max_rows_in_memory=self.max_rows_per_hydrant)

    def add_batch(self, batch: RowBatch) -> None:
        self.index.add_batch(batch)
        self.num_rows_added += len(batch.timestamps)

    def persist_hydrant(self) -> None:
        """Seal the writable hydrant into an immutable snapshot (the
        incremental-persist step that bounds ingest memory)."""
        if self.index.n_rows == 0:
            return
        self.hydrants.append(
            self.index.to_segment(self.ident.version, self.ident.partition))
        self.index = self._new_index()

    def needs_persist(self) -> bool:
        return not self.index.can_append()

    def query_segments(self) -> List[Segment]:
        out = list(self.hydrants)
        if self.index.n_rows > 0:
            out.append(self.index.to_segment(self.ident.version,
                                             self.ident.partition))
        return out

    def merged_segment(self) -> Optional[Segment]:
        """Merge all hydrants into the final pushable segment
        (the IndexMergerV9.mergeQueryableIndex step)."""
        segs = self.query_segments()
        if not segs:
            return None
        if len(segs) == 1:
            s = segs[0]
            return Segment(SegmentId(self.ident.datasource,
                                     self.ident.interval, self.ident.version,
                                     self.ident.partition),
                           s.time_ms, s.dims, s.metrics)
        return merge_segments(segs, self.metric_specs,
                              datasource=self.ident.datasource,
                              interval=self.ident.interval,
                              version=self.ident.version,
                              partition=self.ident.partition,
                              query_granularity=self.query_granularity)


class Appenderator:
    """Manages sinks; add/persist/push; exposes in-flight data as ordinary
    segments for querying (SinkQuerySegmentWalker analog)."""

    def __init__(self, datasource: str,
                 metric_specs: Sequence[A.AggregatorSpec],
                 dimensions: Optional[Sequence[str]] = None,
                 query_granularity: str = "none",
                 max_rows_per_hydrant: int = 500_000):
        self.datasource = datasource
        self.metric_specs = list(metric_specs)
        self.dimensions = dimensions
        self.query_granularity = query_granularity
        self.max_rows_per_hydrant = max_rows_per_hydrant
        self._sinks: Dict[str, Sink] = {}
        self._lock = threading.RLock()
        # sink lifecycle listeners (cluster.realtime.RealtimeServer announces
        # created sinks into the broker's InventoryView — the
        # SinkQuerySegmentWalker announcement step)
        self._listeners: List[object] = []

    def add_listener(self, listener) -> None:
        """listener gets sink_created(ident) / sink_dropped(ident), and —
        when it defines them — sink_published(descriptor, segment) just
        before a publishing sink drops (the standing-query cutover hook,
        engine/standing.py)."""
        with self._lock:
            self._listeners.append(listener)
            existing = [s.ident for s in self._sinks.values()]
        for ident in existing:
            listener.sink_created(ident)

    def remove_listener(self, listener) -> None:
        with self._lock:
            try:
                self._listeners.remove(listener)
            except ValueError:
                pass

    def add(self, ident: SegmentIdWithShard, batch: RowBatch) -> None:
        created = False
        with self._lock:
            sink = self._sinks.get(ident.id)
            if sink is None:
                sink = self._sinks[ident.id] = Sink(
                    ident, self.metric_specs, self.dimensions,
                    self.query_granularity, self.max_rows_per_hydrant)
                created = True
            sink.add_batch(batch)
            if sink.needs_persist():
                sink.persist_hydrant()
            listeners = list(self._listeners) if created else ()
        for ln in listeners:
            ln.sink_created(ident)

    def persist_all(self) -> None:
        with self._lock:
            for sink in self._sinks.values():
                sink.persist_hydrant()

    def sink_ids(self) -> List[SegmentIdWithShard]:
        with self._lock:
            return [s.ident for s in self._sinks.values()]

    def rows_in(self, ident: SegmentIdWithShard) -> int:
        with self._lock:
            sink = self._sinks.get(ident.id)
            return sink.num_rows_added if sink else 0

    # ---- realtime querying (SinkQuerySegmentWalker) --------------------
    def query_segments(self) -> List[Segment]:
        with self._lock:
            out: List[Segment] = []
            for sink in self._sinks.values():
                out += sink.query_segments()
            return out

    def sink_segments(self, segment_id: str) -> Optional[List[Segment]]:
        """Queryable snapshots of ONE in-flight sink (hydrants + a snapshot
        of the live index), or None if no such sink."""
        with self._lock:
            sink = self._sinks.get(str(segment_id))
            return None if sink is None else sink.query_segments()

    def standing_states(self) -> List[Tuple]:
        """[(ident, immutable hydrant snapshots, live IncrementalIndex)]
        per sink — the standing-query fold surface (engine/standing.py):
        hydrants are append-only so the caller folds only the ones past
        its high-water mark, and the live index exposes change_marker()
        so an unchanged tick costs zero snapshots. Snapshot production
        (to_segment) is the caller's, OUTSIDE this lock."""
        with self._lock:
            return [(s.ident, tuple(s.hydrants), s.index)
                    for s in self._sinks.values()]

    def note_published(self, pairs) -> None:
        """Notify listeners that these sinks' merged historical segments
        now exist ((descriptor, segment) pairs, about to hand off). Fires
        BEFORE drop() so a standing listener can swap the contribution
        exactly-once at the publish boundary."""
        with self._lock:
            listeners = list(self._listeners)
        for desc, seg in pairs:
            for ln in listeners:
                fn = getattr(ln, "sink_published", None)
                if fn is not None:
                    fn(desc, seg)

    # ---- push -----------------------------------------------------------
    def push(self, idents: Sequence[SegmentIdWithShard]
             ) -> List[Tuple[SegmentDescriptor, Segment]]:
        """Merge each sink's hydrants into its final segment. Does NOT drop
        the sinks — data stays queryable until handoff (drop())."""
        out = []
        with self._lock:
            for ident in idents:
                sink = self._sinks.get(ident.id)
                if sink is None:
                    continue
                seg = sink.merged_segment()
                if seg is None:
                    continue
                spec = NumberedShardSpec(ident.partition, 0)
                desc = SegmentDescriptor(
                    ident.datasource, ident.interval, ident.version,
                    ident.partition, spec, num_rows=seg.n_rows)
                out.append((desc, seg))
        return out

    def drop(self, idents: Sequence[SegmentIdWithShard]) -> None:
        """Handoff complete: historicals serve these now."""
        dropped = []
        with self._lock:
            for ident in idents:
                if self._sinks.pop(ident.id, None) is not None:
                    dropped.append(ident)
            listeners = list(self._listeners)
        for ident in dropped:
            for ln in listeners:
                ln.sink_dropped(ident)


class StreamAppenderatorDriver:
    """The add → publish → handoff state machine with transactional
    (exactly-once) publish: segments and stream offsets commit in ONE
    metadata transaction (reference: StreamAppenderatorDriver +
    SegmentTransactionalInsertAction + §3.4)."""

    def __init__(self, appenderator: Appenderator,
                 allocator: SegmentAllocator,
                 metadata: MetadataStore,
                 handoff: Optional[Callable[
                     [List[Tuple[SegmentDescriptor, Segment]]], None]] = None,
                 deep_storage: Optional["DeepStorage"] = None):
        self.appenderator = appenderator
        self.allocator = allocator
        self.metadata = metadata
        self.handoff = handoff        # e.g. load onto a DataNode + announce
        self.deep_storage = deep_storage  # durable home before publish
        self._active: Dict[int, SegmentIdWithShard] = {}  # bucket start → id
        # serializes add_batch vs publish_all so a concurrently-allocated
        # sink can't be evicted from _active without being published
        self._lock = threading.Lock()

    def add_batch(self, batch: RowBatch) -> List[SegmentIdWithShard]:
        """Route rows to per-bucket allocated segments."""
        ts = np.asarray(batch.timestamps, dtype=np.int64)
        if len(ts) == 0:
            return []
        gran = self.allocator.granularity
        starts = gran.bucket_start_array(ts)
        touched = []
        with self._lock:
            for st in np.unique(starts):
                sel = starts == st
                ident = self._active.get(int(st))
                if ident is None:
                    ident = self.allocator.allocate(
                        self.appenderator.datasource, int(st))
                    self._active[int(st)] = ident
                sub = RowBatch(ts[sel],
                               {k: [v for v, m in zip(col, sel) if m]
                                if isinstance(col, list) else np.asarray(col)[sel]
                                for k, col in batch.columns.items()})
                self.appenderator.add(ident, sub)
                touched.append(ident)
        return touched

    def publish_all(self, start_metadata: Optional[dict],
                    end_metadata: dict) -> bool:
        """Transactionally publish every active segment with the stream
        offset CAS. On success, hand off and drop the sinks. On CAS
        failure nothing is committed (another task already advanced the
        offsets — the duplicate is discarded, preserving exactly-once)."""
        with self._lock:
            idents = list(self._active.values())
            pushed = self.appenderator.push(idents)
            if self.deep_storage is not None:
                # durable copy BEFORE the metadata commit, so the published
                # descriptors are loadable by the coordinator forever —
                # without this, the only copy dies with this process
                pushed = [(self.deep_storage.push(seg, d), seg)
                          for d, seg in pushed]
            ok = self.metadata.publish_segments(
                [d for d, _ in pushed],
                (self.appenderator.datasource, start_metadata, end_metadata))
            if ok:
                if self.handoff is not None and pushed:
                    self.handoff(pushed)
                # published segments exist (and are handed off) BEFORE the
                # sinks drop: standing listeners swap their incremental
                # partials for the published contribution exactly-once,
                # and the broker's ReplicaSet never has a serving gap
                self.appenderator.note_published(pushed)
                self.appenderator.drop(idents)
                for key in [k for k, v in self._active.items()
                            if v in idents]:
                    del self._active[key]
            # on CAS failure sinks stay intact so the caller may retry with
            # re-read metadata (or discard the task)
            return ok
