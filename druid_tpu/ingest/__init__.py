from druid_tpu.ingest.incremental import IncrementalIndex
from druid_tpu.ingest.input import (CombiningFirehose, DimensionsSpec,
                                    Firehose, InlineFirehose, InputRowParser,
                                    LocalFirehose, RowBatch, TimestampSpec,
                                    TransformSpec, firehose_from_json)
from druid_tpu.ingest.merger import merge_segments
from druid_tpu.ingest.receiver import EventReceiverFirehose
from druid_tpu.ingest.appenderator import (Appenderator, SegmentAllocator,
                                           Sink, StreamAppenderatorDriver)
from druid_tpu.ingest.streaming import (SimulatedStream, StreamIngestTask,
                                        StreamSource, StreamSupervisor,
                                        StreamSupervisorSpec,
                                        StreamTuningConfig)

__all__ = [
    "IncrementalIndex", "merge_segments", "EventReceiverFirehose", "InputRowParser", "TimestampSpec",
    "DimensionsSpec", "TransformSpec", "RowBatch", "Firehose",
    "InlineFirehose", "LocalFirehose", "CombiningFirehose",
    "firehose_from_json", "Appenderator", "SegmentAllocator", "Sink",
    "StreamAppenderatorDriver", "SimulatedStream", "StreamIngestTask",
    "StreamSource", "StreamSupervisor", "StreamSupervisorSpec",
    "StreamTuningConfig",
]
