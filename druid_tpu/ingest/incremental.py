"""IncrementalIndex: the in-memory mutable ingestion index with rollup.

Capability parity with the reference's IncrementalIndex
(processing/.../segment/incremental/IncrementalIndex.java:102,601 — facts map
keyed (truncated time, dims) with per-row Aggregator.aggregate calls;
OnheapIncrementalIndex). TPU-first inversion: there is no per-row facts map.
Rows buffer into columnar batches; a vectorized compaction pass
(factorize keys → np.unique → ufunc.at scatter aggregation) rolls the whole
batch up at once, then merges it with the accumulated grouped state. The
ingest hot loop is numpy, the same shape as the device kernels — ~100x the
reference's per-row HashMap path.

Dictionaries grow in arrival order during ingest (unsorted, exactly like the
reference's ingest-time dims) and are sorted + remapped only at snapshot
(the job IndexMergerV9 does at persist).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from druid_tpu.data.dictionary import Dictionary, NULL
from druid_tpu.data.segment import (ComplexColumn, NumericColumn, Segment,
                                    SegmentBuilder, SegmentId,
                                    StringDimColumn, ValueType)
from druid_tpu.engine import hll as hll_mod
from druid_tpu.ingest.input import RowBatch
from druid_tpu.query import aggregators as A
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval

_KEY_BITS_LIMIT = 62


def fuse_group_keys(t: np.ndarray, ids: Dict[str, np.ndarray],
                    cards: Dict[str, int],
                    dim_order: Sequence[str]) -> np.ndarray:
    """Fuse (time, dim ids...) into one int64 key per row, compacting via
    np.unique whenever the packed width would overflow 62 bits. Shared by
    IncrementalIndex rollup and segment merge (single source of truth for
    the key-packing semantics)."""
    _, key = np.unique(t, return_inverse=True)
    key = key.astype(np.int64)
    bits = max(int(key.max(initial=0)).bit_length(), 1)
    for d in dim_order:
        card = max(cards[d], 1)
        cbits = (card - 1).bit_length() or 1
        if bits + cbits > _KEY_BITS_LIMIT:
            _, key = np.unique(key, return_inverse=True)
            key = key.astype(np.int64)
            bits = max(int(key.max(initial=0)).bit_length(), 1)
        if cbits > _KEY_BITS_LIMIT:
            # a single dimension wider than the key space: compact its ids
            _, did = np.unique(ids[d], return_inverse=True)
            did = did.astype(np.int64)
            card = max(int(did.max(initial=0)) + 1, 1)
            cbits = (card - 1).bit_length() or 1
            key = key * card + did
        else:
            key = key * card + ids[d]
        bits += cbits
    return key


class GrowingDictionary:
    """Arrival-order value -> id map (unsorted during ingest)."""

    __slots__ = ("values", "index")

    def __init__(self):
        self.values: List[str] = []
        self.index: Dict[str, int] = {}

    def encode_list(self, vals: Sequence) -> np.ndarray:
        index = self.index
        values = self.values
        out = np.empty(len(vals), dtype=np.int32)
        for i, v in enumerate(vals):
            s = NULL if v is None else str(v)
            j = index.get(s)
            if j is None:
                j = len(values)
                index[s] = j
                values.append(s)
            out[i] = j
        return out

    @property
    def cardinality(self) -> int:
        return len(self.values)


class _MetricState:
    """Per-aggregator grouped state arrays + vectorized scatter update."""

    def __init__(self, spec: A.AggregatorSpec):
        self.spec = spec
        self.name = spec.name

    # hooks -------------------------------------------------------------
    def from_batch(self, gids: np.ndarray, n_groups: int,
                   batch_cols: Dict[str, list], t_raw: np.ndarray) -> dict:
        raise NotImplementedError

    def merge(self, a: dict, b: dict, map_a: np.ndarray, map_b: np.ndarray,
              n_groups: int) -> dict:
        raise NotImplementedError

    def final_column(self, state: dict):
        raise NotImplementedError

    def extra_columns(self, state: dict) -> Dict[str, NumericColumn]:
        """Auxiliary persisted columns (e.g. first/last pair times)."""
        return {}


def _numeric_field(batch_cols, field, t_raw, n, dtype):
    if field == "__time":
        return t_raw.astype(dtype)
    vals = batch_cols.get(field)
    if vals is None:
        return np.zeros(n, dtype=dtype)
    if isinstance(vals, np.ndarray) and vals.dtype != object:
        return vals.astype(dtype)  # merge path: already-numeric columns
    out = np.zeros(n, dtype=dtype)
    for i, v in enumerate(vals):
        if v is None:
            continue
        try:
            out[i] = v
        except (TypeError, ValueError):
            try:
                out[i] = float(v)
            except (TypeError, ValueError):
                pass
    return out


class _CountState(_MetricState):
    def from_batch(self, gids, n_groups, batch_cols, t_raw):
        out = np.zeros(n_groups, dtype=np.int64)
        np.add.at(out, gids, 1)
        return {"v": out}

    def merge(self, a, b, map_a, map_b, n_groups):
        out = np.zeros(n_groups, dtype=np.int64)
        np.add.at(out, map_a, a["v"])
        np.add.at(out, map_b, b["v"])
        return {"v": out}

    def final_column(self, state):
        return NumericColumn(state["v"], ValueType.LONG)


class _SumState(_MetricState):
    _DT = {ValueType.LONG: np.int64, ValueType.FLOAT: np.float32,
           ValueType.DOUBLE: np.float64}

    def __init__(self, spec, vtype: ValueType):
        super().__init__(spec)
        self.vtype = vtype
        self.dtype = self._DT[vtype]

    def from_batch(self, gids, n_groups, batch_cols, t_raw):
        v = _numeric_field(batch_cols, self.spec.field, t_raw, len(gids),
                           self.dtype)
        out = np.zeros(n_groups, dtype=self.dtype)
        np.add.at(out, gids, v)
        return {"v": out}

    def merge(self, a, b, map_a, map_b, n_groups):
        out = np.zeros(n_groups, dtype=self.dtype)
        np.add.at(out, map_a, a["v"])
        np.add.at(out, map_b, b["v"])
        return {"v": out}

    def final_column(self, state):
        return NumericColumn(state["v"], self.vtype)


class _MinMaxState(_MetricState):
    def __init__(self, spec, vtype: ValueType, is_max: bool):
        super().__init__(spec)
        self.vtype = vtype
        self.is_max = is_max
        self.dtype = _SumState._DT[vtype]
        if vtype == ValueType.LONG:
            self.ident = np.int64(-(2**63)) if is_max else np.int64(2**63 - 1)
        else:
            self.ident = self.dtype(-np.inf) if is_max else self.dtype(np.inf)

    def from_batch(self, gids, n_groups, batch_cols, t_raw):
        v = _numeric_field(batch_cols, self.spec.field, t_raw, len(gids),
                           self.dtype)
        out = np.full(n_groups, self.ident, dtype=self.dtype)
        (np.maximum if self.is_max else np.minimum).at(out, gids, v)
        return {"v": out}

    def merge(self, a, b, map_a, map_b, n_groups):
        out = np.full(n_groups, self.ident, dtype=self.dtype)
        op = np.maximum if self.is_max else np.minimum
        op.at(out, map_a, a["v"])
        op.at(out, map_b, b["v"])
        return {"v": out}

    def final_column(self, state):
        return NumericColumn(state["v"], self.vtype)


class _FirstLastState(_MetricState):
    """State = (event time, value) pairs per group. The event time persists
    as a hidden `__ft_<name>` LONG column so re-merges and queries over
    rolled-up segments order by TRUE event time, not the truncated group
    time (the reference stores SerializablePair(long, value) for this)."""

    def __init__(self, spec, vtype: ValueType, is_last: bool):
        super().__init__(spec)
        self.vtype = vtype
        self.is_last = is_last
        self.dtype = _SumState._DT[vtype]

    def from_batch(self, gids, n_groups, batch_cols, t_raw):
        v = _numeric_field(batch_cols, self.spec.field, t_raw, len(gids),
                           self.dtype)
        t_col = batch_cols.get(f"__ft_{self.spec.field}")
        if t_col is not None:  # merge path: restored pair times
            t_used = np.asarray(t_col, dtype=np.int64)
        else:
            t_used = t_raw
        # order rows so the winner (first by min time / last by max time)
        # lands LAST in the scatter, then plain assignment keeps it
        order = np.argsort(t_used, kind="stable")
        if not self.is_last:
            order = order[::-1]
        t_out = np.full(n_groups, -(2**63) if self.is_last else 2**63 - 1,
                        dtype=np.int64)
        v_out = np.zeros(n_groups, dtype=self.dtype)
        t_out[gids[order]] = t_used[order]
        v_out[gids[order]] = v[order]
        return {"t": t_out, "v": v_out}

    def merge(self, a, b, map_a, map_b, n_groups):
        better = (np.greater if self.is_last else np.less)
        t_out = np.full(n_groups, -(2**63) if self.is_last else 2**63 - 1,
                        dtype=np.int64)
        v_out = np.zeros(n_groups, dtype=self.dtype)
        for st, mp in ((a, map_a), (b, map_b)):
            take = better(st["t"], t_out[mp])
            idx = mp[take]
            t_out[idx] = st["t"][take]
            v_out[idx] = st["v"][take]
        return {"t": t_out, "v": v_out}

    def final_column(self, state):
        return NumericColumn(state["v"], self.vtype)

    def extra_columns(self, state):
        return {f"__ft_{self.name}": NumericColumn(state["t"],
                                                   ValueType.LONG)}


class _HllState(_MetricState):
    """hyperUnique ingest metric: per-group HLL register arrays
    (reference: HyperUniquesAggregatorFactory at ingest)."""

    def __init__(self, spec, log2m: int):
        super().__init__(spec)
        self.log2m = log2m
        self.m = 1 << log2m

    def from_batch(self, gids, n_groups, batch_cols, t_raw):
        vals = batch_cols.get(self.spec.field)
        regs = np.zeros((n_groups, self.m), dtype=np.int8)
        if vals is None or len(vals) == 0:
            return {"v": regs}
        first = vals[0]
        if isinstance(first, np.ndarray) and first.ndim == 1 \
                and first.shape[0] == self.m:
            # merge path: rows are already register arrays (complex column)
            arr = (vals if isinstance(vals, np.ndarray)
                   else np.stack(list(vals))).astype(np.int8)
            np.maximum.at(regs, gids, arr)
        else:
            h = hll_mod.hash_strings(["" if v is None else str(v)
                                      for v in vals])
            reg, rho = hll_mod.hash_to_register(h, self.log2m)
            np.maximum.at(regs, (gids, reg), rho.astype(np.int8))
        return {"v": regs}

    def merge(self, a, b, map_a, map_b, n_groups):
        out = np.zeros((n_groups, self.m), dtype=np.int8)
        np.maximum.at(out, map_a, a["v"])
        np.maximum.at(out, map_b, b["v"])
        return {"v": out}

    def final_column(self, state):
        return ComplexColumn(state["v"], "hyperUnique")


def make_metric_state(spec: A.AggregatorSpec) -> _MetricState:
    if isinstance(spec, A.CountAggregator):
        return _CountState(spec)
    if isinstance(spec, A.LongSumAggregator):
        return _SumState(spec, ValueType.LONG)
    if isinstance(spec, A.DoubleSumAggregator):
        return _SumState(spec, ValueType.DOUBLE)
    if isinstance(spec, A.FloatSumAggregator):
        return _SumState(spec, ValueType.FLOAT)
    if isinstance(spec, A.LongMinAggregator):
        return _MinMaxState(spec, ValueType.LONG, False)
    if isinstance(spec, A.LongMaxAggregator):
        return _MinMaxState(spec, ValueType.LONG, True)
    if isinstance(spec, A.DoubleMinAggregator):
        return _MinMaxState(spec, ValueType.DOUBLE, False)
    if isinstance(spec, A.DoubleMaxAggregator):
        return _MinMaxState(spec, ValueType.DOUBLE, True)
    if isinstance(spec, A.FloatMinAggregator):
        return _MinMaxState(spec, ValueType.FLOAT, False)
    if isinstance(spec, A.FloatMaxAggregator):
        return _MinMaxState(spec, ValueType.FLOAT, True)
    if isinstance(spec, A.FirstAggregator):
        return _FirstLastState(spec, ValueType(spec.kind), False)
    if isinstance(spec, A.LastAggregator):
        return _FirstLastState(spec, ValueType(spec.kind), True)
    if isinstance(spec, A.HyperUniqueAggregator):
        return _HllState(spec, spec.log2m)
    raise ValueError(
        f"aggregator {type(spec).__name__} unsupported at ingest")


class IncrementalIndex:
    """Mutable rollup index; thread-safe add; snapshot to immutable Segment."""

    def __init__(self, datasource: str, interval: Interval,
                 metric_specs: Sequence[A.AggregatorSpec],
                 dimensions: Optional[Sequence[str]] = None,
                 query_granularity: str | Granularity = "none",
                 rollup: bool = True,
                 max_rows_in_memory: int = 1_000_000,
                 flush_rows: int = 65536):
        self.datasource = datasource
        self.interval = interval
        self.metric_states = [make_metric_state(s) for s in metric_specs]
        self.metric_specs = list(metric_specs)
        self.explicit_dims = list(dimensions) if dimensions else None
        self.query_granularity = (query_granularity
                                  if isinstance(query_granularity, Granularity)
                                  else Granularity.of(query_granularity))
        self.rollup = rollup
        self.max_rows_in_memory = max_rows_in_memory
        self.flush_rows = flush_rows

        self._dicts: Dict[str, GrowingDictionary] = {}
        self._dim_order: List[str] = list(self.explicit_dims or [])
        for d in self._dim_order:
            self._dicts[d] = GrowingDictionary()
        # accumulated grouped state
        self._time = np.zeros(0, dtype=np.int64)
        self._dim_ids: Dict[str, np.ndarray] = {
            d: np.zeros(0, dtype=np.int32) for d in self._dim_order}
        self._states: List[dict] = [
            {k: np.zeros((0,) + v.shape[1:], dtype=v.dtype)
             for k, v in s.from_batch(np.zeros(0, dtype=np.int64), 0, {},
                                      np.zeros(0, dtype=np.int64)).items()}
            for s in self.metric_states]
        # pending raw rows
        self._pending_t: List[int] = []
        self._pending_cols: Dict[str, list] = {}
        self._lock = threading.Lock()
        self._generation = 0
        self._snapshot_cache: Optional[Tuple[int, Segment]] = None
        self.rows_out_of_interval = 0

    # -- ingestion ------------------------------------------------------
    def add(self, row: dict, timestamp: Optional[int] = None):
        """Add one row: {'timestamp': ms | via arg, dims..., metrics...}."""
        raw_ts = row.get("timestamp") if timestamp is None else timestamp
        if raw_ts is None:
            raise ValueError(
                "row has no 'timestamp' key and no timestamp argument")
        ts = int(raw_ts)
        cols = {k: v for k, v in row.items() if k != "timestamp"}
        self.add_batch(RowBatch([ts], {k: [v] for k, v in cols.items()}))

    def add_batch(self, batch: RowBatch):
        if not len(batch):
            return
        with self._lock:
            keep: Optional[np.ndarray] = None
            ts = np.asarray(batch.timestamps, dtype=np.int64)
            inside = (ts >= self.interval.start) & (ts < self.interval.end)
            if not inside.all():
                self.rows_out_of_interval += int((~inside).sum())
                keep = inside
            n_before = len(self._pending_t)
            for i, t in enumerate(batch.timestamps):
                if keep is not None and not keep[i]:
                    continue
                self._pending_t.append(int(t))
            for name, vals in batch.columns.items():
                col = self._pending_cols.get(name)
                if col is None:
                    col = self._pending_cols[name] = [None] * n_before
                if keep is None:
                    col.extend(vals)
                else:
                    col.extend(v for v, k in zip(vals, keep) if k)
            n_after = len(self._pending_t)
            for name, col in self._pending_cols.items():
                if len(col) < n_after:
                    col.extend([None] * (n_after - len(col)))
            if n_after >= self.flush_rows:
                self._compact_locked()

    def _metric_names(self) -> set:
        return {s.name for s in self.metric_states} | {
            s.spec.field for s in self.metric_states
            if getattr(s.spec, "field", None)}

    def _compact_locked(self):
        n = len(self._pending_t)
        if n == 0:
            return
        t_raw = np.asarray(self._pending_t, dtype=np.int64)
        # queryGranularity ALL collapses every row's time to the interval
        # start (one time bucket per segment, like the reference's rollup)
        if self.query_granularity.is_all:
            t_trunc = np.full(n, self.interval.start, dtype=np.int64)
        else:
            t_trunc = self.query_granularity.bucket_start_array(t_raw)

        # dims = declared order, else discovery order (non-metric columns)
        metric_fields = self._metric_names()
        for name in self._pending_cols:
            if self.explicit_dims is None and name not in metric_fields \
                    and name not in self._dicts:
                gd = GrowingDictionary()
                # register null FIRST so pre-existing rows backfill with the
                # null id, not whatever value happens to be seen first
                null_id = int(gd.encode_list([None])[0])
                self._dicts[name] = gd
                self._dim_order.append(name)
                self._dim_ids[name] = np.full(len(self._time), null_id,
                                              dtype=np.int32)

        ids: Dict[str, np.ndarray] = {}
        for d in self._dim_order:
            vals = self._pending_cols.get(d)
            if vals is None:
                ids[d] = np.full(n, self._dicts[d].encode_list([None])[0],
                                 dtype=np.int32)
            else:
                ids[d] = self._dicts[d].encode_list(vals)

        if self.rollup:
            key = self._fuse_keys(t_trunc, ids)
            uniq_keys, first_idx, gids = np.unique(
                key, return_index=True, return_inverse=True)
            n_groups = len(uniq_keys)
            g_time = t_trunc[first_idx]
            g_ids = {d: ids[d][first_idx] for d in self._dim_order}
            g_states = [s.from_batch(gids, n_groups, self._pending_cols,
                                     t_raw) for s in self.metric_states]
        else:
            g_time = t_trunc
            g_ids = ids
            gids = np.arange(n, dtype=np.int64)
            g_states = [s.from_batch(gids, n, self._pending_cols, t_raw)
                        for s in self.metric_states]

        self._merge_accumulated(g_time, g_ids, g_states)
        self._pending_t = []
        self._pending_cols = {}
        self._generation += 1

    def _fuse_keys(self, t: np.ndarray, ids: Dict[str, np.ndarray]) -> np.ndarray:
        return fuse_group_keys(
            t, ids, {d: self._dicts[d].cardinality for d in self._dim_order},
            self._dim_order)

    def _merge_accumulated(self, g_time, g_ids, g_states):
        if len(self._time) == 0:
            self._time = g_time
            self._dim_ids = dict(g_ids)
            self._states = g_states
            return
        # align dims (new discovered dims get null id for old rows — null is
        # whatever id the dictionary gave "")
        a_n, b_n = len(self._time), len(g_time)
        cat_t = np.concatenate([self._time, g_time])
        cat_ids = {}
        for d in self._dim_order:
            a = self._dim_ids.get(d)
            if a is None:
                a = np.full(a_n, self._dicts[d].encode_list([None])[0],
                            dtype=np.int32)
            cat_ids[d] = np.concatenate([a, g_ids[d]])
        if not self.rollup:
            self._time = cat_t
            self._dim_ids = cat_ids
            self._states = [
                {k: np.concatenate([a[k], b[k]]) for k in a}
                for a, b in zip(self._states, g_states)]
            return
        key = self._fuse_keys(cat_t, cat_ids)
        uniq_keys, first_idx, all_gids = np.unique(
            key, return_index=True, return_inverse=True)
        n_groups = len(uniq_keys)
        map_a, map_b = all_gids[:a_n], all_gids[a_n:]
        self._time = cat_t[first_idx]
        self._dim_ids = {d: cat_ids[d][first_idx] for d in self._dim_order}
        self._states = [
            s.merge(a, b, map_a, map_b, n_groups)
            for s, a, b in zip(self.metric_states, self._states, g_states)]

    # -- introspection ---------------------------------------------------
    @property
    def n_rows(self) -> int:
        with self._lock:
            return len(self._time) + len(self._pending_t)

    def change_marker(self) -> Tuple[int, int]:
        """(generation, pending rows): lexicographically advances on every
        content change — compaction bumps the generation, appends grow the
        pending tail. Standing queries (engine/standing.py) compare markers
        across ticks so an unchanged live hydrant costs zero snapshots."""
        with self._lock:
            return (self._generation, len(self._pending_t))

    def can_append(self) -> bool:
        return self.n_rows < self.max_rows_in_memory

    # -- snapshot --------------------------------------------------------
    def to_segment(self, version: str = "v0", partition: int = 0) -> Segment:
        """Immutable queryable snapshot: sort dictionaries, remap ids, build
        a Segment (the reference queries the live index through
        IncrementalIndexStorageAdapter; here realtime queries see cheap
        immutable snapshots, cached per generation)."""
        with self._lock:
            return self._to_segment_locked(version, partition)

    def snapshot_with_marker(self, version: str = "v0",
                             partition: int = 0
                             ) -> Tuple[Segment, Tuple[int, int]]:
        """(snapshot, change marker) where the marker describes EXACTLY the
        snapshot's content — taken under one lock hold, post-compaction,
        so standing queries (engine/standing.py) can store a high-water
        mark that neither re-folds an unchanged snapshot (the compaction
        bumped the generation the caller saw pre-snapshot) nor misses
        rows appended concurrently with snapshotting."""
        with self._lock:
            seg = self._to_segment_locked(version, partition)
            return seg, (self._generation, 0)

    def _to_segment_locked(self, version: str, partition: int) -> Segment:
        self._compact_locked()
        gen = self._generation
        if self._snapshot_cache is not None \
                and self._snapshot_cache[0] == gen:
            return self._snapshot_cache[1]
        dims: Dict[str, StringDimColumn] = {}
        for d in self._dim_order:
            gd = self._dicts[d]
            sorted_dict = Dictionary(sorted(gd.index))
            remap = np.asarray(
                [sorted_dict.id_of(v) for v in gd.values],
                dtype=np.int32) if gd.values else np.zeros(0, np.int32)
            null_id = sorted_dict.id_of(NULL)
            raw = self._dim_ids[d]
            if null_id < 0:
                sorted_dict = Dictionary(sorted(set(gd.index) | {NULL}))
                remap = np.asarray(
                    [sorted_dict.id_of(v) for v in gd.values],
                    dtype=np.int32)
            dims[d] = StringDimColumn(
                remap[raw] if len(raw) else raw.copy(), sorted_dict)
        metrics: Dict[str, object] = {}
        for s, st in zip(self.metric_states, self._states):
            metrics[s.name] = s.final_column(st)
            metrics.update(s.extra_columns(st))
        seg = Segment(
            SegmentId(self.datasource, self.interval, version, partition),
            self._time.copy(), dims, metrics, sorted_by_time=False)
        self._snapshot_cache = (gen, seg)
        return seg

    def persist(self, directory: str, version: str = "v0",
                partition: int = 0) -> Segment:
        # format V2 unless DRUID_TPU_SEGMENT_FORMAT=1: ingest pays the
        # cascade encodings once here, load/staging reuses them verbatim
        from druid_tpu.storage.format_v2 import persist_segment_auto
        seg = self.to_segment(version, partition)
        persist_segment_auto(seg, directory)
        return seg
