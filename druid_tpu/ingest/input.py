"""Input row model: parsers, timestamp/dimension specs, firehoses, transforms.

Capability parity with the reference's input layer
(api/.../data/input/InputRow.java, impl/ parsers — JSON/CSV/TSV/regex;
Firehose/FirehoseFactory SPI; segment/transform/TransformSpec.java).
TPU-first: parsers emit COLUMN BATCHES (numpy-backed dicts), not per-row
objects — the ingest hot loop is vectorized from the first byte.
"""
from __future__ import annotations

import csv
import glob as globlib
import gzip
import io
import json
import re
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import (Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence)

import numpy as np

from druid_tpu.query.filters import DimFilter, filter_from_json
from druid_tpu.utils.expression import parse_expression
from druid_tpu.utils.intervals import parse_ts


@dataclass(frozen=True)
class TimestampSpec:
    """Reference analog: api/.../data/input/impl/TimestampSpec.java."""
    column: str = "timestamp"
    format: str = "auto"        # auto | iso | millis | posix | nano | <strptime>
    missing_value: Optional[int] = None

    def parse(self, value) -> int:
        if value is None:
            if self.missing_value is not None:
                return self.missing_value
            raise ValueError(f"null timestamp in column {self.column!r}")
        f = self.format
        if f == "millis":
            return int(value)
        if f == "posix":
            return int(float(value) * 1000)
        if f == "nano":
            return int(value) // 1_000_000
        if f in ("auto", "iso"):
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                return int(value)
            s = str(value)
            if f == "auto" and s.lstrip("-").isdigit():
                return int(s)
            return parse_ts(s)
        dt = datetime.strptime(str(value), f)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        return int(dt.timestamp() * 1000)

    @staticmethod
    def from_json(j: Optional[dict]) -> "TimestampSpec":
        j = j or {}
        return TimestampSpec(j.get("column", "timestamp"),
                             j.get("format", "auto"),
                             j.get("missingValue"))

    def to_json(self) -> dict:
        return {"column": self.column, "format": self.format,
                "missingValue": self.missing_value}


@dataclass(frozen=True)
class DimensionsSpec:
    """Reference analog: api/.../data/input/impl/DimensionsSpec.java.
    Empty `dimensions` means schemaless discovery (all non-excluded,
    non-timestamp, non-metric fields become string dims).
    spatial_dimensions: ((dimName, (coord fields...)), ...) — the parser
    joins the coordinate fields into one 'x,y' string dim that
    SpatialFilter understands (SpatialDimensionSchema)."""
    dimensions: tuple = ()
    exclusions: tuple = ()
    spatial_dimensions: tuple = ()

    @staticmethod
    def from_json(j: Optional[dict]) -> "DimensionsSpec":
        j = j or {}
        dims = []
        for d in j.get("dimensions", []):
            dims.append(d if isinstance(d, str) else d["name"])
        spatial = tuple(
            (s["dimName"], tuple(s["dims"]))
            for s in j.get("spatialDimensions", []))
        return DimensionsSpec(tuple(dims),
                              tuple(j.get("dimensionExclusions", [])),
                              spatial)

    def to_json(self) -> dict:
        return {"dimensions": list(self.dimensions),
                "dimensionExclusions": list(self.exclusions),
                "spatialDimensions": [{"dimName": n, "dims": list(d)}
                                      for n, d in self.spatial_dimensions]}


class RowBatch:
    """A parsed batch: timestamps + per-column python-object lists.

    Columns hold raw parsed values (str for dims, numbers for metrics);
    the IncrementalIndex vectorizes from here.
    """

    def __init__(self, timestamps: List[int], columns: Dict[str, list]):
        self.timestamps = timestamps
        self.columns = columns

    def __len__(self):
        return len(self.timestamps)


class InputRowParser:
    """Parse raw records (dicts or lines) into RowBatches.

    Reference analog: api/.../data/input/impl/InputRowParser + ParseSpec
    (JSONParseSpec, CSVParseSpec, DelimitedParseSpec, RegexParseSpec).
    """

    def __init__(self, timestamp_spec: TimestampSpec,
                 dimensions_spec: DimensionsSpec,
                 fmt: str = "json",
                 columns: Optional[Sequence[str]] = None,
                 delimiter: str = "\t",
                 list_delimiter: str = "\x01",
                 pattern: Optional[str] = None):
        self.timestamp_spec = timestamp_spec
        self.dimensions_spec = dimensions_spec
        self.fmt = fmt
        self.columns = list(columns) if columns else None
        self.delimiter = delimiter
        self.list_delimiter = list_delimiter
        self.pattern = re.compile(pattern) if pattern else None

    #: extension parser types: "type" → constructor(json) (the reference's
    #: InputRowParser @JsonSubTypes registry, extended by DruidModules)
    _PARSER_TYPES: Dict[str, "Callable[[dict], InputRowParser]"] = {}

    @classmethod
    def register_type(cls, name: str, ctor) -> None:
        cls._PARSER_TYPES[name] = ctor

    @staticmethod
    def from_json(j: dict) -> "InputRowParser":
        t = j.get("type")
        if t and t not in ("string", "map", "hadoopyString"):
            ctor = InputRowParser._PARSER_TYPES.get(t)
            if ctor is None:
                # a forked peon deserializing a task spec may not have
                # imported the extension modules yet — registering them
                # here beats silently JSON-parsing binary records
                import druid_tpu.ext  # noqa: F401
                ctor = InputRowParser._PARSER_TYPES.get(t)
            if ctor is None:
                raise ValueError(f"unknown parser type {t!r}")
            return ctor(j)
        ps = j.get("parseSpec", j)
        fmt = ps.get("format", "json")
        return InputRowParser(
            TimestampSpec.from_json(ps.get("timestampSpec")),
            DimensionsSpec.from_json(ps.get("dimensionsSpec")),
            fmt=("csv" if fmt == "csv" else "tsv" if fmt in ("tsv", "delimited")
                 else "regex" if fmt == "regex" else "json"),
            columns=ps.get("columns"),
            delimiter=ps.get("delimiter", "\t"),
            pattern=ps.get("pattern"))

    def to_json(self) -> dict:
        ps = {"format": self.fmt,
              "timestampSpec": self.timestamp_spec.to_json(),
              "dimensionsSpec": self.dimensions_spec.to_json()}
        if self.columns is not None:
            ps["columns"] = list(self.columns)
        if self.fmt == "tsv":
            ps["delimiter"] = self.delimiter
        if self.pattern is not None:
            ps["pattern"] = self.pattern.pattern
        return {"parseSpec": ps}

    # -- record-level decode --------------------------------------------
    def _decode(self, record) -> Optional[dict]:
        if isinstance(record, dict):
            return record
        line = record.decode("utf-8") if isinstance(record, bytes) else record
        line = line.rstrip("\n\r")
        if not line:
            return None
        if self.fmt == "json":
            return json.loads(line)
        if self.fmt in ("csv", "tsv"):
            delim = "," if self.fmt == "csv" else self.delimiter
            vals = next(csv.reader([line], delimiter=delim))
            if self.columns is None:
                raise ValueError(f"{self.fmt} parser requires explicit columns")
            return dict(zip(self.columns, vals))
        if self.fmt == "regex":
            m = self.pattern.match(line)
            if m is None:
                raise ValueError(f"regex did not match line: {line[:80]!r}")
            groups = m.groups()
            cols = self.columns or [f"column_{i + 1}"
                                    for i in range(len(groups))]
            return dict(zip(cols, groups))
        raise ValueError(f"unknown format {self.fmt}")

    def parse_batch(self, records: Iterable) -> RowBatch:
        """Parse an iterable of raw records into one columnar batch;
        malformed records raise (callers may count+skip per task config)."""
        ts_col = self.timestamp_spec.column
        explicit_dims = self.dimensions_spec.dimensions
        spatial_specs = self.dimensions_spec.spatial_dimensions
        spatial_fields = {f for _, fields in spatial_specs for f in fields}
        # spatial sources are read from the RAW record (pre-exclusion) and
        # consumed by the join — excluding them must not empty the joined
        # dim, and they don't become discovered dims of their own
        # (SpatialDimensionRowTransformer consumes them from the row)
        exclusions = (set(self.dimensions_spec.exclusions) | {ts_col}
                      | spatial_fields) - set(explicit_dims)
        timestamps: List[int] = []
        columns: Dict[str, list] = {d: [] for d in explicit_dims}
        spatial_src: Dict[str, list] = {f: [] for f in spatial_fields}
        n = 0
        for record in records:
            d = self._decode(record)
            if d is None:
                continue
            timestamps.append(self.timestamp_spec.parse(d.get(ts_col)))
            for f in spatial_src:
                spatial_src[f].append(d.get(f))
            # keep ALL non-timestamp fields: the dimensions spec decides what
            # becomes a dim downstream, but metric inputs must survive parse
            keys = [k for k in d.keys() if k not in exclusions]
            for k in keys:
                col = columns.get(k)
                if col is None:
                    col = columns[k] = [None] * n
                col.append(d.get(k))
            for k, col in columns.items():
                if len(col) < len(timestamps):
                    col.append(None)
            n += 1
        # join spatial coordinate fields into 'x,y' dims
        # (SpatialDimensionRowTransformer)
        for dim_name, fields in spatial_specs:
            src = [spatial_src[f] for f in fields]
            columns[dim_name] = [
                ",".join("" if c[i] is None else str(c[i]) for c in src)
                for i in range(n)]
        return RowBatch(timestamps, columns)


# ---------------------------------------------------------------------------
# Transforms (reference: segment/transform/TransformSpec.java,
# ExpressionTransform.java) — expression-computed columns + a pre-rollup
# row filter, applied on the columnar batch.
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ExpressionTransform:
    name: str
    expression: str

    @staticmethod
    def from_json(j: dict) -> "ExpressionTransform":
        return ExpressionTransform(j["name"], j["expression"])

    def to_json(self) -> dict:
        return {"type": "expression", "name": self.name,
                "expression": self.expression}


@dataclass(frozen=True)
class TransformSpec:
    transforms: tuple = ()
    filter: Optional[DimFilter] = None

    @staticmethod
    def from_json(j: Optional[dict]) -> "TransformSpec":
        if not j:
            return TransformSpec()
        return TransformSpec(
            tuple(ExpressionTransform.from_json(t)
                  for t in j.get("transforms", [])),
            filter_from_json(j.get("filter")))

    def to_json(self) -> dict:
        return {"transforms": [t.to_json() for t in self.transforms],
                "filter": self.filter.to_json() if self.filter else None}

    def apply(self, batch: RowBatch) -> RowBatch:
        if not self.transforms and self.filter is None:
            return batch
        cols = dict(batch.columns)
        n = len(batch)
        if self.transforms:
            # bind only the columns the transform expressions reference
            exprs = [(t, parse_expression(t.expression))
                     for t in self.transforms]
            referenced = set()
            for _, e in exprs:
                referenced |= e.required_columns()
            bindings: Dict[str, object] = {"__time": np.asarray(
                batch.timestamps, dtype=np.int64)}
            for k in referenced:
                if k == "__time" or k not in cols:
                    continue
                v = cols[k]
                num = [x if isinstance(x, (int, float))
                       and not isinstance(x, bool) else _maybe_num(x)
                       for x in v]
                if all(isinstance(x, (int, float)) for x in num):
                    bindings[k] = np.asarray([float(x) for x in num])
                else:
                    bindings[k] = np.asarray(v, dtype=object)
            for t, e in exprs:
                val = np.asarray(e.evaluate(bindings))
                if val.ndim == 0:
                    val = np.full(n, val[()])
                cols[t.name] = list(val)
                bindings[t.name] = val
        if self.filter is not None:
            keep = _filter_rows(self.filter, batch.timestamps, cols, n)
            ts = [t for t, k in zip(batch.timestamps, keep) if k]
            cols = {name: [v for v, k in zip(vals, keep) if k]
                    for name, vals in cols.items()}
            return RowBatch(ts, cols)
        return RowBatch(batch.timestamps, cols)


def _maybe_num(x):
    try:
        return float(x)
    except (TypeError, ValueError):
        return x


def _filter_rows(flt: DimFilter, timestamps, cols: Dict[str, list],
                 n: int) -> np.ndarray:
    """Row-level filter on raw values (ingest-time; pre-dictionary)."""
    from druid_tpu.engine.filters import make_row_matcher
    matcher = make_row_matcher(flt)
    rows_match = np.ones(n, dtype=bool)
    for i in range(n):
        row = {k: v[i] for k, v in cols.items()}
        row["__time"] = timestamps[i]
        rows_match[i] = matcher(row)
    return rows_match


# ---------------------------------------------------------------------------
# Firehoses (reference: api/.../data/input/FirehoseFactory.java,
# server/.../realtime/firehose/LocalFirehoseFactory.java) — batch iterators
# of raw records.
# ---------------------------------------------------------------------------

class Firehose:
    """Iterator of raw-record batches."""

    def batches(self, batch_size: int = 65536) -> Iterator[List]:
        raise NotImplementedError

    def to_json(self) -> dict:
        """Wire form consumed by firehose_from_json — required so tasks can
        ship to forked peons (ForkingTaskRunner)."""
        raise NotImplementedError(f"{type(self).__name__} is not serializable")

    def splits(self, n: int) -> List["Firehose"]:
        """Partition into ≤ n independent firehoses for parallel ingest
        (reference: SplittableInputSource.createSplits). Default:
        unsplittable → one split."""
        return [self]


class InlineFirehose(Firehose):
    def __init__(self, records: Sequence):
        self.records = list(records)

    def batches(self, batch_size: int = 65536):
        for i in range(0, len(self.records), batch_size):
            yield self.records[i:i + batch_size]

    def to_json(self) -> dict:
        return {"type": "inline", "data": list(self.records)}

    def splits(self, n: int) -> List["Firehose"]:
        if not self.records:
            return [self]
        n = max(1, min(n, len(self.records)))
        per = -(-len(self.records) // n)
        return [InlineFirehose(self.records[i:i + per])
                for i in range(0, len(self.records), per)]


class LocalFirehose(Firehose):
    """Reads newline-delimited files matching a glob (gzip-aware)."""

    def __init__(self, base_dir: str, glob: str = "*"):
        self.base_dir = base_dir
        self.glob = glob
        self.paths = sorted(globlib.glob(f"{base_dir}/{glob}"))

    def batches(self, batch_size: int = 65536):
        buf: List[str] = []
        for path in self.paths:
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rt") as f:
                for line in f:
                    buf.append(line)
                    if len(buf) >= batch_size:
                        yield buf
                        buf = []
        if buf:
            yield buf


    def to_json(self) -> dict:
        # explicit paths so SPLIT instances round-trip exactly (a split
        # shipped to a peon must not re-glob the whole directory)
        return {"type": "local", "baseDir": self.base_dir,
                "filter": self.glob, "paths": list(self.paths)}

    @classmethod
    def _from_paths(cls, base_dir: str, glob: str,
                    paths: Sequence[str]) -> "LocalFirehose":
        fh = cls.__new__(cls)
        fh.base_dir = base_dir
        fh.glob = glob
        fh.paths = list(paths)
        return fh

    def splits(self, n: int) -> List["Firehose"]:
        if len(self.paths) <= 1:
            return [self]
        n = max(1, min(n, len(self.paths)))
        return [LocalFirehose._from_paths(self.base_dir, self.glob,
                                          self.paths[i::n])
                for i in range(n)]


class CombiningFirehose(Firehose):
    def __init__(self, delegates: Sequence[Firehose]):
        self.delegates = list(delegates)

    def batches(self, batch_size: int = 65536):
        for d in self.delegates:
            yield from d.batches(batch_size)

    def to_json(self) -> dict:
        return {"type": "combining",
                "delegates": [d.to_json() for d in self.delegates]}


def firehose_from_json(j: dict) -> Firehose:
    t = j.get("type")
    if t == "local":
        if "paths" in j:
            # explicit split: do NOT re-glob the directory
            return LocalFirehose._from_paths(j["baseDir"],
                                             j.get("filter", "*"),
                                             j["paths"])
        return LocalFirehose(j["baseDir"], j.get("filter", "*"))
    if t == "inline":
        return InlineFirehose(j.get("data", "").splitlines()
                              if isinstance(j.get("data"), str)
                              else j["data"])
    if t == "combining":
        return CombiningFirehose([firehose_from_json(d)
                                  for d in j["delegates"]])
    if t == "receiver":
        from druid_tpu.ingest.receiver import EventReceiverFirehose
        return EventReceiverFirehose(j["serviceName"],
                                     port=int(j.get("port", 0)))
    raise ValueError(f"unknown firehose type {t!r}")
