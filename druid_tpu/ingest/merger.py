"""Segment merging: n-way merge with dictionary reconciliation + re-rollup.

Capability parity with the reference's IndexMergerV9.mergeQueryableIndex
(processing/.../segment/IndexMergerV9.java:801 — n-way sorted dictionary
merge via DimensionMergerV9, row merge with rollup re-aggregation). TPU-first:
merge is a vectorized concat + remap + grouped re-aggregation (the same
np.unique/ufunc.at pass the IncrementalIndex uses), not a per-row iterator
merge.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from druid_tpu.data.dictionary import Dictionary, NULL, merge_dictionaries
from druid_tpu.data.segment import (ComplexColumn, NumericColumn, Segment,
                                    SegmentId, StringDimColumn, ValueType)
from druid_tpu.query import aggregators as A
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval


def merge_segments(segments: Sequence[Segment],
                   metric_specs: Sequence[A.AggregatorSpec],
                   datasource: Optional[str] = None,
                   interval: Optional[Interval] = None,
                   version: str = "merged",
                   partition: int = 0,
                   rollup: bool = True,
                   query_granularity: str | Granularity = "none") -> Segment:
    """Merge segments into one. `metric_specs` are the ORIGINAL ingest specs;
    re-aggregation uses their combining form (count re-merges as longSum —
    reference AggregatorFactory.getCombiningFactory)."""
    from druid_tpu.ingest.incremental import make_metric_state

    assert segments
    datasource = datasource or segments[0].id.datasource
    if interval is None:
        interval = Interval(min(s.interval.start for s in segments),
                            max(s.interval.end for s in segments))
    gran = (query_granularity if isinstance(query_granularity, Granularity)
            else Granularity.of(query_granularity))

    # 1. unified dim set (order: first-seen across segments)
    dim_order: List[str] = []
    for s in segments:
        for d in s.dims:
            if d not in dim_order:
                dim_order.append(d)

    # 2. merged dictionaries + per-segment remaps (DimensionMergerV9 analog)
    merged_dicts: Dict[str, Dictionary] = {}
    remaps: Dict[str, List[Optional[np.ndarray]]] = {}
    for d in dim_order:
        per_seg = []
        for s in segments:
            col = s.dims.get(d)
            per_seg.append(col.dictionary if col else Dictionary([NULL]))
        # ensure NULL present for segments lacking the dim
        if any(d not in s.dims for s in segments):
            per_seg.append(Dictionary([NULL]))
            md, rm = merge_dictionaries(per_seg)
            rm = rm[:-1]
        else:
            md, rm = merge_dictionaries(per_seg)
        merged_dicts[d] = md
        remaps[d] = rm

    # 3. concat columns (remapped)
    n_total = sum(s.n_rows for s in segments)
    time_cat = np.concatenate([s.time_ms for s in segments]) if n_total \
        else np.zeros(0, dtype=np.int64)
    ids_cat: Dict[str, np.ndarray] = {}
    for d in dim_order:
        parts = []
        for s, rm in zip(segments, remaps[d]):
            col = s.dims.get(d)
            if col is None:
                null_id = merged_dicts[d].id_of(NULL)
                parts.append(np.full(s.n_rows, null_id, dtype=np.int32))
            else:
                parts.append(rm[col.ids])
        ids_cat[d] = np.concatenate(parts) if parts else np.zeros(0, np.int32)

    # 4. metric columns concat (as combining inputs); hidden pair-time
    # columns (__ft_<name>) ride along when every segment has them, so
    # first/last re-merge by true event time
    states = [make_metric_state(spec.combining()) for spec in metric_specs]
    metric_cols: Dict[str, np.ndarray] = {}
    names = [spec.name for spec in metric_specs]
    names += [h for spec in metric_specs
              for h in (f"__ft_{spec.name}",)
              if all(h in s.metrics for s in segments)]
    for name in names:
        parts = []
        for s in segments:
            col = s.metrics.get(name)
            if col is None:
                raise ValueError(
                    f"segment {s.id} lacks metric {name!r} for merge")
            parts.append(col.values)
        metric_cols[name] = (np.concatenate(parts) if parts
                             else np.zeros(0, dtype=np.float64))

    if gran.is_all:
        t_trunc = np.full(n_total, interval.start, dtype=np.int64)
    else:
        t_trunc = gran.bucket_start_array(time_cat)
    if rollup and n_total:
        from druid_tpu.ingest.incremental import fuse_group_keys
        key = fuse_group_keys(
            t_trunc, ids_cat,
            {d: merged_dicts[d].cardinality for d in dim_order}, dim_order)
        uniq, first_idx, gids = np.unique(key, return_index=True,
                                          return_inverse=True)
        n_groups = len(uniq)
        g_time = t_trunc[first_idx]
        g_ids = {d: ids_cat[d][first_idx] for d in dim_order}
        g_states = [st.from_batch(gids, n_groups, metric_cols, time_cat)
                    for st in states]
    else:
        order = np.argsort(t_trunc, kind="stable")
        g_time = t_trunc[order]
        g_ids = {d: ids_cat[d][order] for d in dim_order}
        gids = np.arange(n_total, dtype=np.int64)
        g_states = [st.from_batch(gids, n_total,
                                  {k: v[order]
                                   for k, v in metric_cols.items()},
                                  time_cat[order]) for st in states]

    dims = {d: StringDimColumn(g_ids[d].astype(np.int32), merged_dicts[d])
            for d in dim_order}
    metrics: Dict[str, object] = {}
    for st, s in zip(states, g_states):
        metrics[st.name] = st.final_column(s)
        metrics.update(st.extra_columns(s))
    return Segment(SegmentId(datasource, interval, version, partition),
                   g_time, dims, metrics, sorted_by_time=False)


