"""Push-based ingestion: the HTTP event-receiver firehose.

Reference analog: server/src/main/java/org/apache/druid/segment/realtime/
firehose/EventReceiverFirehoseFactory.java — clients POST batches of JSON
events to /druid/worker/v1/chat/{serviceName}/push-events; the firehose
buffers them (bounded) until the producer closes the stream, and an index
task drains it like any other firehose.
"""
from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterator, List, Optional

from druid_tpu.ingest.input import Firehose


class EventReceiverFirehose(Firehose):
    """Bounded-buffer push firehose with an HTTP front.

    batches() blocks on the buffer and ends when close() is called (or the
    producer POSTs to /shutdown) and the buffer drains — exactly the
    EventReceiverFirehose lifecycle."""

    def __init__(self, service_name: str, host: str = "127.0.0.1",
                 port: int = 0, max_buffered: int = 100_000):
        self.service_name = service_name
        self.max_buffered = max_buffered
        self._q: "queue.Queue[object]" = queue.Queue()
        self._closed = threading.Event()
        self.events_received = 0
        self._recv_lock = threading.Lock()
        outer = self
        base = f"/druid/worker/v1/chat/{service_name}"

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                try:
                    self.wfile.write(data)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                if self.path == f"{base}/push-events":
                    if outer._closed.is_set():
                        self._reply(409, {"error": "firehose closed"})
                        return
                    try:
                        events = json.loads(self.rfile.read(n) or b"[]")
                    except ValueError as e:
                        self._reply(400, {"error": str(e)})
                        return
                    if not isinstance(events, list):
                        events = [events]
                    # all-or-nothing admission: a partially-enqueued batch
                    # answered 503 would be retried by the client and its
                    # accepted prefix ingested twice
                    with outer._recv_lock:
                        if outer._q.qsize() + len(events) > \
                                outer.max_buffered:
                            self._reply(503, {"error": "buffer full"})
                            return
                        for e in events:
                            outer._q.put(e)
                        outer.events_received += len(events)
                    self._reply(200, {"eventCount": len(events)})
                elif self.path == f"{base}/shutdown":
                    outer.close()
                    self._reply(200, {"shutdown": True})
                else:
                    self._reply(404, {"error": "unknown path"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return (f"http://127.0.0.1:{self.port}"
                f"/druid/worker/v1/chat/{self.service_name}")

    def to_json(self) -> dict:
        """Factory form (EventReceiverFirehoseFactory): a task carrying
        this spec OPENS the endpoint where it runs — a forked peon hosts
        its own chat handler, exactly like the reference."""
        return {"type": "receiver", "serviceName": self.service_name}

    def close(self) -> None:
        self._closed.set()

    def stop(self) -> None:
        self.close()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # ---- Firehose ------------------------------------------------------
    def batches(self, batch_size: int = 65536) -> Iterator[List]:
        buf: List = []
        while True:
            try:
                buf.append(self._q.get(timeout=0.05))
                if len(buf) >= batch_size:
                    yield buf
                    buf = []
            except queue.Empty:
                if buf:
                    yield buf
                    buf = []
                if self._closed.is_set() and self._q.empty():
                    return
