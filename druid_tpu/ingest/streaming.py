"""Streaming ingestion with exactly-once semantics.

Reference analogs (extensions-core/kafka-indexing-service/):
  KafkaSupervisor.java — run loop: partitions → task groups, spawns
    replicated index tasks, checkpoint coordination (:523), reconciliation
    of failed tasks from last committed offsets
  KafkaIndexTask / IncrementalPublishingKafkaIndexTaskRunner.java:229 —
    poll → parse → appenderator add → transactional publish where
    (startOffsets → endOffsets) CAS against datasource metadata commits
    atomically with the segments = exactly-once (§3.4)

The stream source is an SPI (`StreamSource`) with an in-process
`SimulatedStream` implementation (the role Kafka's consumer plays; a real
deployment implements StreamSource over a network consumer).
Tasks here are pollable objects driven by the supervisor's run loop —
deterministic for tests, threadable in deployment.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from druid_tpu.cluster.metadata import MetadataStore
from druid_tpu.ingest.appenderator import (Appenderator, SegmentAllocator,
                                           StreamAppenderatorDriver)
from druid_tpu.ingest.input import InputRowParser, RowBatch, TransformSpec
from druid_tpu.query import aggregators as A


# ---------------------------------------------------------------------------
# Stream source SPI + simulated implementation
# ---------------------------------------------------------------------------

class StreamSource:
    """Partitioned, offset-addressable record stream (Kafka consumer SPI)."""

    def partitions(self) -> List[int]:
        raise NotImplementedError

    def read(self, partition: int, offset: int, max_records: int
             ) -> List[Tuple[int, dict]]:
        """Records [(offset, record)] starting at `offset`."""
        raise NotImplementedError

    def latest_offset(self, partition: int) -> int:
        """One past the last available offset."""
        raise NotImplementedError


class SimulatedStream(StreamSource):
    """In-memory partitioned log for tests/local runs."""

    def __init__(self, n_partitions: int = 2):
        self._logs: Dict[int, List[dict]] = {i: [] for i in range(n_partitions)}
        self._lock = threading.Lock()

    def append(self, partition: int, records: Sequence[dict]) -> None:
        with self._lock:
            self._logs[partition].extend(records)

    def partitions(self) -> List[int]:
        with self._lock:
            return sorted(self._logs)

    def read(self, partition: int, offset: int, max_records: int):
        with self._lock:
            log = self._logs[partition]
            end = min(len(log), offset + max_records)
            return [(i, log[i]) for i in range(offset, end)]

    def latest_offset(self, partition: int) -> int:
        with self._lock:
            return len(self._logs[partition])


# ---------------------------------------------------------------------------
# Streaming task
# ---------------------------------------------------------------------------

@dataclass
class StreamTuningConfig:
    max_rows_per_hydrant: int = 500_000
    max_records_per_poll: int = 10_000
    segment_granularity: str = "hour"
    query_granularity: str = "none"


class StreamIngestTask:
    """One exactly-once ingestion task over a set of partitions
    (KafkaIndexTask analog). Drive with poll_once(); checkpoint() publishes
    everything read so far atomically with the new offsets."""

    def __init__(self, task_id: str, datasource: str,
                 source: StreamSource, partitions: Sequence[int],
                 start_offsets: Dict[int, int],
                 metric_specs: Sequence[A.AggregatorSpec],
                 metadata: MetadataStore,
                 parser: Optional[InputRowParser] = None,
                 transform: Optional[TransformSpec] = None,
                 dimensions: Optional[Sequence[str]] = None,
                 tuning: Optional[StreamTuningConfig] = None,
                 handoff: Optional[Callable] = None,
                 deep_storage=None, realtime=None):
        self.task_id = task_id
        self.datasource = datasource
        self.source = source
        self.partitions = list(partitions)
        self.start_offsets = dict(start_offsets)   # committed base
        self.current_offsets = dict(start_offsets)
        self.metadata = metadata
        self.parser = parser
        self.transform = transform
        self.tuning = tuning or StreamTuningConfig()
        appender = Appenderator(
            datasource, metric_specs, dimensions=dimensions,
            query_granularity=self.tuning.query_granularity,
            max_rows_per_hydrant=self.tuning.max_rows_per_hydrant)
        if realtime is not None:
            # announce in-flight sinks into the broker view
            # (cluster.realtime.RealtimeServer — SinkQuerySegmentWalker)
            realtime.attach(appender)
        allocator = SegmentAllocator(metadata,
                                     self.tuning.segment_granularity)
        self.driver = StreamAppenderatorDriver(appender, allocator, metadata,
                                               handoff, deep_storage)
        self.paused = False
        self.status = "READING"
        self.rows_read = 0

    # ---- the ingest loop body (★ §3.4) ---------------------------------
    def poll_once(self) -> int:
        """consumer.poll → parse → driver.add. Returns records consumed."""
        if self.paused or self.status != "READING":
            return 0
        n = 0
        for p in self.partitions:
            records = self.source.read(p, self.current_offsets[p],
                                       self.tuning.max_records_per_poll)
            if not records:
                continue
            rows = [r for _, r in records]
            batch = self._parse(rows)
            if len(batch):
                self.driver.add_batch(batch)
                self.rows_read += len(batch)
            self.current_offsets[p] = records[-1][0] + 1
            n += len(records)
        return n

    def _parse(self, rows: List[dict]) -> RowBatch:
        if self.parser is not None:
            batch = self.parser.parse_batch(rows)
        else:
            # rows are already {"timestamp": ms, **columns}
            ts = [r["timestamp"] for r in rows]
            cols: Dict[str, list] = {}
            keys = {k for r in rows for k in r if k != "timestamp"}
            for k in sorted(keys):
                cols[k] = [r.get(k) for r in rows]
            batch = RowBatch(ts, cols)
        if self.transform is not None:
            batch = self.transform.apply(batch)
        return batch

    # ---- pause/resume protocol (chat handler analog) -------------------
    def pause(self):
        self.paused = True

    def resume(self):
        self.paused = False

    # ---- transactional checkpoint --------------------------------------
    def checkpoint(self, cas_attempts: int = 3) -> bool:
        """Publish all in-flight segments + advance committed offsets in one
        metadata transaction. The task owns a SUBSET of partitions, so the
        comparison/merge is per-partition (KafkaDataSourceMetadata.matches /
        .plus): our partitions must be exactly at our start offsets in the
        committed map; other task groups' partitions pass through untouched.
        False = offsets conflict (another replica already committed past us)
        — our work is discarded, no duplicates."""
        for _ in range(cas_attempts):
            current = self.metadata.datasource_metadata(self.datasource)
            cur_parts = dict(current["partitions"]) if current else {}
            for p in self.partitions:
                if int(cur_parts.get(str(p), 0)) != self.start_offsets[p]:
                    self.status = "FAILED"   # stale replica: genuinely lost
                    return False
            merged = dict(cur_parts)
            for p in self.partitions:
                merged[str(p)] = self.current_offsets[p]
            ok = self.driver.publish_all(current, {"partitions": merged})
            if ok:
                self.start_offsets = dict(self.current_offsets)
                return True
            # CAS raced with a concurrent commit on OTHER partitions:
            # re-read and retry; a conflict on OUR partitions exits above
        self.status = "FAILED"
        return False

    def finish(self) -> bool:
        ok = self.checkpoint()
        if ok:
            self.status = "SUCCESS"
        return ok


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------

@dataclass
class StreamSupervisorSpec:
    datasource: str
    metric_specs: Sequence[A.AggregatorSpec]
    dimensions: Optional[Sequence[str]] = None
    task_count: int = 1
    max_rows_per_task: int = 1_000_000
    tuning: StreamTuningConfig = field(default_factory=StreamTuningConfig)


class StreamSupervisor:
    """Assigns stream partitions to task groups, rolls tasks over at
    checkpoints, and recreates failed tasks from the last committed offsets
    (KafkaSupervisor's reconciliation loop)."""

    def __init__(self, spec: StreamSupervisorSpec, source: StreamSource,
                 metadata: MetadataStore,
                 parser: Optional[InputRowParser] = None,
                 transform: Optional[TransformSpec] = None,
                 handoff: Optional[Callable] = None,
                 deep_storage=None, realtime=None):
        self.spec = spec
        self.source = source
        self.metadata = metadata
        self.parser = parser
        self.transform = transform
        self.handoff = handoff
        self.deep_storage = deep_storage
        self.realtime = realtime
        self.tasks: Dict[int, StreamIngestTask] = {}   # group → task
        self._task_seq = 0
        self.metadata.set_supervisor(
            spec.datasource, {"datasource": spec.datasource,
                              "taskCount": spec.task_count})

    # ---- offset recovery (the exactly-once resume point) ----------------
    def committed_offsets(self) -> Dict[int, int]:
        meta = self.metadata.datasource_metadata(self.spec.datasource)
        if meta is None:
            return {p: 0 for p in self.source.partitions()}
        parts = {int(k): v for k, v in meta["partitions"].items()}
        for p in self.source.partitions():
            parts.setdefault(p, 0)
        return parts

    def _groups(self) -> Dict[int, List[int]]:
        groups: Dict[int, List[int]] = {i: [] for i in
                                        range(self.spec.task_count)}
        for p in self.source.partitions():
            groups[p % self.spec.task_count].append(p)
        return groups

    def run_once(self) -> None:
        """One supervisor period: ensure a healthy task per group (recreate
        failed/missing ones from committed offsets), drive polls, roll over
        tasks that exceeded max_rows_per_task."""
        committed = self.committed_offsets()
        for group, partitions in self._groups().items():
            if not partitions:
                continue
            task = self.tasks.get(group)
            if task is None or task.status == "FAILED":
                self._task_seq += 1
                task = StreamIngestTask(
                    f"index_stream_{self.spec.datasource}_{self._task_seq}",
                    self.spec.datasource, self.source, partitions,
                    {p: committed[p] for p in partitions},
                    list(self.spec.metric_specs), self.metadata,
                    parser=self.parser, transform=self.transform,
                    dimensions=self.spec.dimensions, tuning=self.spec.tuning,
                    handoff=self.handoff, deep_storage=self.deep_storage,
                    realtime=self.realtime)
                self.tasks[group] = task
                self.metadata.insert_task(task.task_id, self.spec.datasource,
                                          "RUNNING", {"group": group})
            task.poll_once()
            if task.rows_read >= self.spec.max_rows_per_task:
                self._complete(group, task)

    def _complete(self, group: int, task: StreamIngestTask) -> None:
        ok = task.finish()
        self.metadata.update_task_status(
            task.task_id, "SUCCESS" if ok else "FAILED")
        del self.tasks[group]

    def checkpoint_all(self) -> bool:
        """Force-publish every running task (supervisor checkpoint notice)."""
        ok = True
        for group, task in list(self.tasks.items()):
            if not task.checkpoint():
                ok = False
                self.metadata.update_task_status(task.task_id, "FAILED")
                del self.tasks[group]
        return ok

    def stop(self, publish: bool = True) -> bool:
        ok = True
        for group, task in list(self.tasks.items()):
            if publish:
                ok = task.finish() and ok
            elif task.status == "READING":
                task.status = "FAILED"   # discarded without publishing
            self.metadata.update_task_status(task.task_id, task.status)
            del self.tasks[group]
        return ok

    # ---- realtime query surface ----------------------------------------
    def query_segments(self):
        """In-flight (unpublished) segments across tasks — what
        SinkQuerySegmentWalker announces to the broker."""
        out = []
        for task in self.tasks.values():
            out += task.driver.appenderator.query_segments()
        return out
