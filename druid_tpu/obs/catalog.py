"""The single-source metrics catalog: every metric name the tree emits.

The druidlint `metric-name` rule parses THIS file's METRICS dict literal and
fails the build when any `emitter.metric("...")` literal is not declared
here — metric-name drift (a renamed metric silently orphaning its dashboard)
becomes a gate failure, the same discipline contracts.py applies to engine
shape constants. Keep the dict a PLAIN LITERAL: the rule reads it with ast,
no imports.

Each entry: unit, the per-site dims (service/host are stamped on everything
by ServiceEmitter and not repeated), the emitting site, and a help string
(also the Prometheus # HELP text). `render_table()` produces the README's
markdown table from the same data.
"""
from __future__ import annotations

from typing import Dict, List

METRICS = {
    # ---- query lifecycle (server/lifecycle.py) -------------------------
    "query/time": {
        "unit": "ms", "dims": ("dataSource", "type", "id", "priority",
                               "success"),
        "site": "server/lifecycle.py, cluster/dataserver.py",
        "help": "end-to-end query wall time"},
    "query/wait/time": {
        "unit": "ms", "dims": ("dataSource", "type", "id"),
        "site": "server/lifecycle.py",
        "help": "time queued for a scheduler slot before execution"},
    "query/node/time": {
        "unit": "ms", "dims": ("dataSource", "type", "id", "server"),
        "site": "server/lifecycle.py (from broker/node trace spans)",
        "help": "broker wait on one data node's response"},
    "query/compile/time": {
        "unit": "ms", "dims": ("dataSource", "type", "id"),
        "site": "server/lifecycle.py (from engine/compile trace spans)",
        "help": "jit-cache-miss compile time inside the query (absent on "
                "cache-hit runs)"},
    "query/stage/h2d/time": {
        "unit": "ms", "dims": ("dataSource", "type", "id"),
        "site": "server/lifecycle.py (from pool/h2d trace spans)",
        "help": "device-pool cold-miss host-to-device staging time"},
    # ---- per-segment serving (cluster/view.py) -------------------------
    "query/segment/time": {
        "unit": "ms", "dims": ("dataSource", "type", "id", "segment",
                               "server"),
        "site": "cluster/view.py",
        "help": "uncached per-segment (or fused-set) execution wall time"},
    "query/segmentAndCache/time": {
        "unit": "ms", "dims": ("dataSource", "type", "id", "segment",
                               "server"),
        "site": "cluster/view.py",
        "help": "per-segment serving time including cache hits"},
    "query/cpu/time": {
        "unit": "ms", "dims": ("dataSource", "type", "id", "segment",
                               "server"),
        "site": "cluster/view.py",
        "help": "per-segment host CPU (thread) time"},
    # ---- query counts (utils/emitter.py QueryCountStatsMonitor) --------
    "query/count": {
        "unit": "count", "dims": (),
        "site": "utils/emitter.py",
        "help": "cumulative queries served"},
    "query/success/count": {
        "unit": "count", "dims": (),
        "site": "utils/emitter.py",
        "help": "cumulative successful queries"},
    "query/failed/count": {
        "unit": "count", "dims": (),
        "site": "utils/emitter.py",
        "help": "cumulative failed queries"},
    "query/count/delta": {
        "unit": "count/period", "dims": (),
        "site": "utils/emitter.py",
        "help": "queries served since the last monitor tick"},
    "query/success/count/delta": {
        "unit": "count/period", "dims": (),
        "site": "utils/emitter.py",
        "help": "successes since the last monitor tick"},
    "query/failed/count/delta": {
        "unit": "count/period", "dims": (),
        "site": "utils/emitter.py",
        "help": "failures since the last monitor tick"},
    # ---- result/segment cache (utils/emitter.py CacheMonitor) ----------
    "query/cache/total/hits": {
        "unit": "count", "dims": (),
        "site": "utils/emitter.py",
        "help": "cumulative cache hits"},
    "query/cache/total/misses": {
        "unit": "count", "dims": (),
        "site": "utils/emitter.py",
        "help": "cumulative cache misses"},
    "query/cache/total/evictions": {
        "unit": "count", "dims": (),
        "site": "utils/emitter.py",
        "help": "cumulative cache evictions"},
    "query/cache/total/entries": {
        "unit": "count", "dims": (),
        "site": "utils/emitter.py",
        "help": "current cache entry count"},
    # ---- data-node scheduler (server/scheduler.py) ---------------------
    "query/queue/depth": {
        "unit": "count", "dims": (),
        "site": "server/scheduler.py (SchedulerMetricsMonitor)",
        "help": "queries queued at the data-node scheduler at tick time"},
    "query/queue/wait": {
        "unit": "ms", "dims": ("dataSource", "type", "id", "lane"),
        "site": "server/scheduler.py",
        "help": "time a query was held in the scheduler queue before its "
                "flush started (emitted per query, tracing on or off)"},
    "query/shed/count": {
        "unit": "count/period", "dims": (),
        "site": "server/scheduler.py (SchedulerMetricsMonitor)",
        "help": "queries shed with 429 at admission since the last tick"},
    "query/crossBatch/queries": {
        "unit": "count", "dims": (),
        "site": "server/scheduler.py (SchedulerMetricsMonitor)",
        "help": "distinct queries fused into one cross-query dispatch"},
    "query/crossBatch/segments": {
        "unit": "count", "dims": (),
        "site": "server/scheduler.py (SchedulerMetricsMonitor)",
        "help": "segments stacked into one cross-query dispatch"},
    "query/crossBatch/fillRatio": {
        "unit": "ratio", "dims": (),
        "site": "server/scheduler.py (SchedulerMetricsMonitor)",
        "help": "real rows / padded slots of a cross-query dispatch"},
    "query/crossBatch/droppedEvents": {
        "unit": "count", "dims": (),
        "site": "server/scheduler.py (SchedulerMetricsMonitor)",
        "help": "per-dispatch events lost to the bounded event queue "
                "(the crossBatch series undercounts by this many)"},
    # ---- broker fault tolerance (cluster/resilience.py) ----------------
    "broker/circuit/open": {
        "unit": "count", "dims": (),
        "site": "cluster/resilience.py (ResilienceMetricsMonitor)",
        "help": "per-server circuit breakers currently open or half-open "
                "(replica selection is skipping these servers)"},
    "broker/circuit/trips": {
        "unit": "count/period", "dims": (),
        "site": "cluster/resilience.py (ResilienceMetricsMonitor)",
        "help": "circuits tripped open since the last tick (consecutive "
                "failures/sheds/timeouts crossed the threshold)"},
    "broker/circuit/probes": {
        "unit": "count/period", "dims": (),
        "site": "cluster/resilience.py (ResilienceMetricsMonitor)",
        "help": "half-open probe queries routed through an open circuit "
                "since the last tick"},
    "query/hedge/issued": {
        "unit": "count/period", "dims": (),
        "site": "cluster/resilience.py (ResilienceMetricsMonitor)",
        "help": "speculative straggler re-issues sent since the last "
                "tick (hedged requests)"},
    "query/hedge/won": {
        "unit": "count/period", "dims": (),
        "site": "cluster/resilience.py (ResilienceMetricsMonitor)",
        "help": "hedged requests that claimed their segments first since "
                "the last tick"},
    "query/hedge/cancelled": {
        "unit": "count/period", "dims": (),
        "site": "cluster/resilience.py (ResilienceMetricsMonitor)",
        "help": "in-flight rivals remote-cancelled after losing a hedge "
                "race since the last tick"},
    "query/partial/missingSegments": {
        "unit": "count/period", "dims": (),
        "site": "cluster/resilience.py (ResilienceMetricsMonitor)",
        "help": "segments reported missing in typed partial results "
                "(allowPartialResults degradations) since the last tick"},
    # ---- device dispatches (obs/dispatch.py) ---------------------------
    "query/dispatch/count": {
        "unit": "count/period", "dims": (),
        "site": "obs/dispatch.py (DispatchMonitor)",
        "help": "device-callable invocations on the query path since the "
                "last tick (per-segment, batched, sharded, and "
                "bitmap-fill programs; the megakernel's one-dispatch "
                "contract is asserted on deltas of this counter)"},
    # ---- fused megakernel (engine/megakernel.py) -----------------------
    "query/megakernel/hits": {
        "unit": "count/period", "dims": (),
        "site": "engine/megakernel.py (MegakernelMonitor)",
        "help": "bitmap filter subtrees fused inline into the one-dispatch "
                "megakernel program since the last tick"},
    "query/megakernel/fallbacks": {
        "unit": "count/period", "dims": (),
        "site": "engine/megakernel.py (MegakernelMonitor)",
        "help": "bitmap filter subtrees that stayed on the staged "
                "fill-wave path since the last tick (megakernel disabled, "
                "or resident combined words already serve them)"},
    "query/megakernel/donatedBytes": {
        "unit": "bytes/period", "dims": (),
        "site": "engine/megakernel.py (MegakernelMonitor)",
        "help": "per-group partial-buffer bytes handed back DONATED across "
                "repeated executions since the last tick (standing-query "
                "ticks update partials in place, zero per-tick HBM churn)"},
    # ---- standing queries (engine/standing.py) -------------------------
    "query/standing/ticks": {
        "unit": "count/period", "dims": (),
        "site": "engine/standing.py (StandingMetricsMonitor)",
        "help": "standing-query ticks executed since the last monitor "
                "tick (each folds only data appended past the per-sink "
                "high-water marks)"},
    "query/standing/folds": {
        "unit": "count/period", "dims": (),
        "site": "engine/standing.py (StandingMetricsMonitor)",
        "help": "incremental segment folds (device work actually paid) "
                "since the last tick — a quiet datasource ticks for free"},
    "query/standing/rows": {
        "unit": "count/period", "dims": (),
        "site": "engine/standing.py (StandingMetricsMonitor)",
        "help": "newly appended rows folded into standing partials since "
                "the last tick (the incremental win vs re-scanning every "
                "sink)"},
    "query/standing/cutovers": {
        "unit": "count/period", "dims": (),
        "site": "engine/standing.py (StandingMetricsMonitor)",
        "help": "publish cutovers since the last tick (a sink's "
                "incremental partials swapped exactly-once for its "
                "published segment's contribution)"},
    # ---- subscription fan-out (server/subscriptions.py) ----------------
    "subscription/active": {
        "unit": "count", "dims": (),
        "site": "server/subscriptions.py (SubscriptionMetricsMonitor)",
        "help": "live subscriptions at tick time (N structurally "
                "identical ones share ONE standing program)"},
    "subscription/fanout": {
        "unit": "count/period", "dims": (),
        "site": "server/subscriptions.py (SubscriptionMetricsMonitor)",
        "help": "changed-result long-poll deliveries since the last tick"},
    "subscription/ticks": {
        "unit": "count/period", "dims": (),
        "site": "server/subscriptions.py (SubscriptionMetricsMonitor)",
        "help": "subscription-hub ticks since the last monitor tick "
                "(each advances every standing program once)"},
    # ---- sharded mesh execution (parallel/distributed.py) --------------
    "query/sharded/mergeDevice": {
        "unit": "count/period", "dims": (),
        "site": "parallel/distributed.py (ShardedMonitor)",
        "help": "sharded dispatches whose partial grids were merged "
                "IN-PROGRAM by the mesh collectives (psum/pmin/pmax/"
                "all_gather+fold) since the last tick — every sharded "
                "dispatch, now that the broker-side host merge is gone"},
    "query/sharded/stackBytes": {
        "unit": "bytes", "dims": (),
        "site": "parallel/distributed.py (ShardedMonitor)",
        "help": "HBM resident in stacked sharded blocks (gauge; the "
                "device pool's stacked_* accounting — counted against "
                "DEVICE_POOL_BUDGET_BYTES like every other entry)"},
    "query/sharded/packedRatio": {
        "unit": "ratio", "dims": (),
        "site": "parallel/distributed.py (ShardedMonitor)",
        "help": "decoded-equivalent / actual bytes over the stacked "
                "sharded blocks (gauge; 1.0 when nothing is stacked) — "
                "the HBM multiplier the compressed-resident stacking "
                "(packed words, cascade run tables, bitmap slots) buys "
                "a pod"},
    # ---- code-domain aggregation (data/cascade.py) ---------------------
    "query/codeDomain/hits": {
        "unit": "count/period", "dims": (),
        "site": "data/cascade.py (CodeDomainMonitor)",
        "help": "segment executions served fully over run metadata since "
                "the last tick (no row-width column staged or decoded — "
                "count/sum/min-max computed from run values × lengths)"},
    "query/codeDomain/rows": {
        "unit": "count/period", "dims": (),
        "site": "data/cascade.py (CodeDomainMonitor)",
        "help": "logical rows covered by code-domain (run-space) "
                "executions since the last tick"},
    # ---- device filter-bitmap cache (engine/filters.py) ----------------
    "query/filter/deviceBitmapHits": {
        "unit": "count/period", "dims": (),
        "site": "engine/filters.py (FilterBitmapMonitor)",
        "help": "filter-result device bitmaps served from resident pool "
                "words since the last tick (no leaf staging, no algebra "
                "dispatch)"},
    "query/filter/deviceBitmapMisses": {
        "unit": "count/period", "dims": (),
        "site": "engine/filters.py (FilterBitmapMonitor)",
        "help": "filter-result device bitmaps built cold since the last "
                "tick"},
    "query/filter/bytes": {
        "unit": "bytes/period", "dims": (),
        "site": "engine/filters.py (FilterBitmapMonitor)",
        "help": "device filter-bitmap bytes materialized on cold misses "
                "since the last tick (1 bit per padded row per filter)"},
    # ---- batched execution (engine/batching.py) ------------------------
    "query/batch/segments": {
        "unit": "count", "dims": (),
        "site": "engine/batching.py",
        "help": "segments fused into one batched dispatch"},
    "query/batch/fillRatio": {
        "unit": "ratio", "dims": (),
        "site": "engine/batching.py",
        "help": "real rows / padded slots of a batched dispatch"},
    "query/batch/droppedEvents": {
        "unit": "count", "dims": (),
        "site": "engine/batching.py",
        "help": "per-dispatch events lost to the bounded queue"},
    # ---- device segment pool (data/devicepool.py) ----------------------
    "segment/devicePool/hitRate": {
        "unit": "ratio", "dims": (),
        "site": "data/devicepool.py",
        "help": "pool hit rate over the monitor tick window"},
    "segment/devicePool/hits": {
        "unit": "count/period", "dims": (),
        "site": "data/devicepool.py",
        "help": "pool hits since the last tick"},
    "segment/devicePool/misses": {
        "unit": "count/period", "dims": (),
        "site": "data/devicepool.py",
        "help": "pool misses since the last tick"},
    "segment/devicePool/evictedBytes": {
        "unit": "bytes/period", "dims": (),
        "site": "data/devicepool.py",
        "help": "HBM bytes evicted since the last tick"},
    "segment/devicePool/residentBytes": {
        "unit": "bytes", "dims": (),
        "site": "data/devicepool.py",
        "help": "HBM bytes currently pinned by pool entries"},
    "segment/devicePool/entries": {
        "unit": "count", "dims": (),
        "site": "data/devicepool.py",
        "help": "current pool entry count"},
    "segment/devicePool/packedRatio": {
        "unit": "ratio", "dims": (),
        "site": "data/devicepool.py",
        "help": "decoded-equivalent bytes / actual resident bytes of "
                "compressed-domain pool entries (1.0 = nothing packed); "
                "the pool/h2d trace span's bytes attr is likewise the "
                "COMPRESSED bus transfer, logicalBytes the decoded size"},
    "segment/devicePool/cascadeRatio": {
        "unit": "ratio", "dims": (),
        "site": "data/devicepool.py",
        "help": "decoded-equivalent bytes / actual resident bytes over "
                "CASCADE-encoded pool entries only (RLE/delta/FOR/LZ4 — "
                "data/cascade.py; 1.0 when nothing cascade-encoded is "
                "resident)"},
    # ---- segment load (storage/format_v2.py) ---------------------------
    "segment/load/time": {
        "unit": "ms/period", "dims": (),
        "site": "storage/format_v2.py",
        "help": "wall time spent loading segments from disk since the "
                "last tick (format V2: mmap + descriptor reconstruction, "
                "no column decode)"},
    "segment/load/bytes": {
        "unit": "bytes/period", "dims": (),
        "site": "storage/format_v2.py",
        "help": "logical (decoded-equivalent) bytes of segments loaded "
                "since the last tick"},
    "segment/load/compressedBytes": {
        "unit": "bytes/period", "dims": (),
        "site": "storage/format_v2.py",
        "help": "on-disk bytes of segments loaded since the last tick "
                "(ratio to segment/load/bytes = storage compression)"},
    # ---- broker <-> data node wire (cluster/wire.py) -------------------
    "query/wire/bytes": {
        "unit": "bytes/period", "dims": (),
        "site": "cluster/wire.py",
        "help": "logical (raw little-endian) tensor bytes of partials "
                "payloads serialized since the last tick"},
    "query/wire/compressedBytes": {
        "unit": "bytes/period", "dims": (),
        "site": "cluster/wire.py",
        "help": "tensor bytes actually emitted after per-tensor wire "
                "compression (equals query/wire/bytes when peers do not "
                "advertise wireCompress)"},
    # ---- coordination (coordination/latch.py) --------------------------
    "coordination/leader/transitions": {
        "unit": "count", "dims": ("service", "node", "event", "term",
                                  "leader"),
        "site": "coordination/latch.py",
        "help": "cumulative leadership transitions"},
    "coordination/lease/ageMs": {
        "unit": "ms", "dims": ("service", "node", "leader"),
        "site": "coordination/latch.py",
        "help": "age of the current leader lease"},
    # ---- host/process (utils/emitter.py Sys/ProcessMonitor) ------------
    "sys/cpu": {
        "unit": "percent", "dims": (),
        "site": "utils/emitter.py",
        "help": "host CPU utilization over the tick window"},
    "sys/mem/used": {
        "unit": "bytes", "dims": (),
        "site": "utils/emitter.py",
        "help": "host memory in use"},
    "sys/mem/max": {
        "unit": "bytes", "dims": (),
        "site": "utils/emitter.py",
        "help": "host memory total"},
    "proc/rss": {
        "unit": "bytes", "dims": (),
        "site": "utils/emitter.py",
        "help": "this process's resident set size"},
    "proc/cpu": {
        "unit": "seconds", "dims": (),
        "site": "utils/emitter.py",
        "help": "this process's cumulative CPU time"},
}


def declared_names() -> List[str]:
    return sorted(METRICS)


def help_for(name: str) -> str:
    m = METRICS.get(name)
    if m is None:
        return "(undeclared metric)"
    return f"{m['help']} ({m['unit']})"


def render_table() -> str:
    """The catalog as a markdown table (README's Observability section)."""
    lines = ["| metric | unit | dims | emitting site |",
             "|---|---|---|---|"]
    for name in sorted(METRICS):
        m = METRICS[name]
        dims = ", ".join(m["dims"]) if m["dims"] else "—"
        lines.append(f"| `{name}` | {m['unit']} | {dims} | {m['site']} |")
    return "\n".join(lines)


def validate_emitted(names) -> List[str]:
    """Names in `names` missing from the catalog (test helper)."""
    return sorted(set(names) - set(METRICS))
