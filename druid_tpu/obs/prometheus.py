"""MetricRegistry: a Prometheus text-exposition sink for the emitter chain.

Reference analog: the statsd/prometheus emitter extensions — a sink that
turns the event stream into a scrapeable surface, so any node type answers
GET /metrics without new plumbing (cluster/dataserver.py and
server/http.py serve `exposition()`).

Model: last-value gauges keyed by (metric, label set). High-cardinality
labels (the per-query `id`) are dropped before keying so series stay
bounded; `max_series` hard-caps the table and counts what it refused.
Exposition follows the text format v0.0.4: HELP/TYPE per metric (help text
from obs/catalog.py), one sample line per label set, deterministic order.
"""
from __future__ import annotations

import re
import threading
from typing import Dict, Tuple

from druid_tpu.obs import catalog
from druid_tpu.utils.emitter import Emitter

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: per-query/per-segment dims whose values are unbounded — dropped from
#: series keys so a query storm cannot blow the registry
DEFAULT_DROP_LABELS = frozenset({"id", "segment"})

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD = re.compile(r"[^a-zA-Z0-9_]")


def metric_name(name: str) -> str:
    """'query/batch/fillRatio' -> 'druid_query_batch_fillRatio'."""
    return "druid_" + _NAME_BAD.sub("_", name)


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _fmt(v: float) -> str:
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def compose_sink(emitter, registry: "MetricRegistry"):
    """Chain `registry` onto a caller-owned emitter's sink IN PLACE and
    return a restore() undoing it. The restore is identity-guarded: it
    only un-wraps if the sink is still the one installed here, so server
    generations sharing one emitter can stop() in any order without
    clobbering each other's chains."""
    from druid_tpu.utils.emitter import ComposingEmitter
    prev = emitter.sink
    emitter.sink = ComposingEmitter([prev, registry])
    installed = emitter.sink

    def restore() -> None:
        if emitter.sink is installed:
            emitter.sink = prev
    return restore


class MetricRegistry(Emitter):
    """Emitter sink exposing the latest value per (metric, labels)."""

    def __init__(self, max_series: int = 4096,
                 drop_labels=DEFAULT_DROP_LABELS):
        self.max_series = max_series
        self.drop_labels = frozenset(drop_labels)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] \
            = {}
        self._dropped_series = 0

    def emit(self, event) -> None:
        if event.kind != "metric":
            return
        try:
            value = float(event.value)
        except (TypeError, ValueError):
            return
        labels = tuple(sorted(
            (_LABEL_BAD.sub("_", str(k)), str(v))
            for k, v in event.dims.items() if k not in self.drop_labels))
        key = (event.metric, labels)
        with self._lock:
            if key not in self._series \
                    and len(self._series) >= self.max_series:
                self._dropped_series += 1
                return
            # gauge semantics: the latest value per series wins by
            # design — the miss check above only enforces the cap
            self._series[key] = value  # druidlint: disable=unkeyed-trace-input

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def exposition(self) -> str:
        """Prometheus text format, deterministically ordered."""
        with self._lock:
            items = sorted(self._series.items())
            dropped = self._dropped_series
        out = []
        last_metric = None
        for (metric, labels), value in items:
            if metric != last_metric:
                pname = metric_name(metric)
                out.append(f"# HELP {pname} {catalog.help_for(metric)}")
                out.append(f"# TYPE {pname} gauge")
                last_metric = metric
            else:
                pname = metric_name(metric)
            if labels:
                lbl = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
                out.append(f"{pname}{{{lbl}}} {_fmt(value)}")
            else:
                out.append(f"{pname} {_fmt(value)}")
        if dropped:
            out.append("# HELP druid_metric_registry_dropped_series series "
                       "refused by the max_series cap")
            out.append("# TYPE druid_metric_registry_dropped_series gauge")
            out.append(f"druid_metric_registry_dropped_series {dropped}")
        return "\n".join(out) + "\n"
