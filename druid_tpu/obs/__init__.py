"""Observability: distributed query tracing (qtrace), the metrics catalog,
and the Prometheus exposition sink. See trace.py for the span model and
propagation contract, catalog.py for the declared metric names the
druidlint `metric-name` rule enforces, prometheus.py for /metrics."""
from druid_tpu.obs.catalog import METRICS, render_table
from druid_tpu.obs.prometheus import MetricRegistry
from druid_tpu.obs.trace import (COMPILE_SPAN, H2D_SPAN, NODE_SPAN, Span,
                                 TraceStore, attach, current_span,
                                 emit_trace_metrics, phase_breakdown,
                                 root_span, span, trace_enabled, trace_store,
                                 with_traceparent)

__all__ = [
    "METRICS", "render_table", "MetricRegistry",
    "COMPILE_SPAN", "H2D_SPAN", "NODE_SPAN", "Span", "TraceStore",
    "attach", "current_span", "emit_trace_metrics", "phase_breakdown",
    "root_span", "span", "trace_enabled", "trace_store", "with_traceparent",
]
