"""qtrace: end-to-end distributed query tracing.

Reference analogs:
  processing/.../query/QueryMetrics.java + MetricsEmittingQueryRunner — the
    per-phase timing dims the reference sprinkles through its runner stack
  opentelemetry-emitter (druid extensions) — span-per-phase query tracing

One trace per query: the trace id IS the queryId (a fresh id when the query
carries none), spans are (name, service, start, duration, attrs) nodes in a
parent tree. Spans cost two monotonic clock reads and a dict — no device
syncs, no locks on the hot path (the store append takes the store lock once
per finished span) — and the whole subsystem no-ops unless a ROOT span is
open on the current thread, so untraced paths pay one thread-local read.

Propagation:
  * thread-local span stack: `span(name)` children nest under the current
    span; `attach(s)` re-activates a span on a worker thread (the broker's
    scatter pool).
  * wire: `with_traceparent(query, span)` stamps "traceId:spanId" into the
    query context the broker POSTs; the data node's `root_span` re-roots its
    spans under that remote parent; the node's finished spans travel back in
    the partials/rows response and the broker ingests them into its store —
    ONE assembled trace per query.
  * opt-out: context {"trace": false} disables tracing for the query
    everywhere (the stamp is simply never created).

Storage: a bounded per-process ring buffer (TraceStore) serves
GET /druid/v2/trace/<queryId> on any node type.
"""
from __future__ import annotations

import collections
import threading
import time
import uuid
from typing import Dict, List, Optional

#: context key carrying the remote parent ("traceId:spanId"); the span id is
#: always our own hex (no ":"), so rsplit from the right survives arbitrary
#: user queryIds as trace ids
TRACEPARENT_KEY = "traceparent"
#: context key opting a query out of tracing ({"trace": false})
TRACE_KEY = "trace"

#: well-known span names (phase attribution keys — see obs/catalog.py for
#: the metrics derived from them)
COMPILE_SPAN = "engine/compile"
H2D_SPAN = "pool/h2d"
NODE_SPAN = "broker/node"


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One timed phase. Mutated only by the thread that opened it; finished
    spans are immutable JSON dicts in the store/collector."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "service",
                 "start_ms", "duration_ms", "attrs", "_t0", "_store",
                 "_collector")

    def __init__(self, trace_id: str, span_id: str, parent_id: Optional[str],
                 name: str, service: str, attrs: Optional[dict] = None,
                 store: Optional["TraceStore"] = None, collector=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.service = service
        self.start_ms = time.time() * 1000.0
        self.duration_ms: Optional[float] = None
        self.attrs = dict(attrs or {})
        self._t0 = time.monotonic()
        self._store = store
        self._collector = collector

    def to_json(self) -> dict:
        return {"traceId": self.trace_id, "spanId": self.span_id,
                "parentId": self.parent_id, "name": self.name,
                "service": self.service,
                "startMs": round(self.start_ms, 3),
                "durationMs": None if self.duration_ms is None
                else round(self.duration_ms, 3),
                "attrs": self.attrs}

    def finish(self) -> None:
        if self.duration_ms is not None:
            return                       # idempotent (double __exit__)
        self.duration_ms = (time.monotonic() - self._t0) * 1000.0
        j = self.to_json()
        if self._store is not None:
            self._store.add_json(j)
        if self._collector is not None:
            self._collector.append(j)

    def collected(self) -> List[dict]:
        """Finished spans of this span's request-local collector (the data
        node's response payload); empty unless opened with collect=True."""
        return list(self._collector) if self._collector is not None else []


# ---------------------------------------------------------------------------
# Thread-local current-span stack
# ---------------------------------------------------------------------------

_TLS = threading.local()


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def current_span() -> Optional[Span]:
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


class _NullCtx:
    """Inactive span context — tracing off / no root open."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class _SpanCtx:
    __slots__ = ("_span",)

    def __init__(self, s: Span):
        self._span = s

    def __enter__(self) -> Span:
        _stack().append(self._span)
        return self._span

    def __exit__(self, et, ev, tb):
        st = _stack()
        if st and st[-1] is self._span:
            st.pop()
        elif self._span in st:       # unbalanced exit: still unwind
            st.remove(self._span)
        if et is not None:
            self._span.attrs.setdefault("error", f"{et.__name__}: {ev}")
        self._span.finish()
        return False


class _AttachCtx:
    """Re-activate an EXISTING span on this thread (no finish on exit) —
    the broker's scatter workers parent their per-node spans this way."""
    __slots__ = ("_span",)

    def __init__(self, s: Span):
        self._span = s

    def __enter__(self) -> Span:
        _stack().append(self._span)
        return self._span

    def __exit__(self, *exc):
        st = _stack()
        if st and st[-1] is self._span:
            st.pop()
        elif self._span in st:
            st.remove(self._span)
        return False


def attach(s: Optional[Span]):
    return _AttachCtx(s) if s is not None else _NULL_CTX


def span(name: str, **attrs):
    """Child span under the current span; a no-op context when no trace is
    active on this thread (the one thread-local read untraced paths pay)."""
    parent = current_span()
    if parent is None:
        return _NULL_CTX
    return _SpanCtx(Span(
        trace_id=parent.trace_id, span_id=_new_id(),
        parent_id=parent.span_id, name=name, service=parent.service,
        attrs=attrs, store=parent._store, collector=parent._collector))


def span_when(cond: bool, name: str, **attrs):
    """`span(name)` when `cond`, else the inactive context — the jit-cache
    sites wrap their dispatch in this so the builder-idiom miss (the
    compile event) gets its span without duplicating the call in an
    if/else."""
    return span(name, **attrs) if cond else _NULL_CTX


def trace_enabled(query) -> bool:
    v = query.context_map.get(TRACE_KEY, True)
    return str(v).strip().lower() not in ("0", "false", "no")


def root_span(name: str, query=None, service: str = "", store=None,
              collect: bool = False, **attrs):
    """Open a trace root for a query (trace id = queryId), re-rooting under
    a remote parent when the query context carries a traceparent stamp.
    When a trace is ALREADY active on this thread (the lifecycle opened the
    root and the broker re-enters), this degrades to a plain child span.
    Inactive (_NULL_CTX) when the query opts out via {"trace": false}."""
    if query is not None and not trace_enabled(query):
        return _NULL_CTX
    if current_span() is not None:
        return span(name, **attrs)
    ctxm = query.context_map if query is not None else {}
    parent_id = None
    tp = ctxm.get(TRACEPARENT_KEY)
    if isinstance(tp, str) and ":" in tp:
        trace_id, parent_id = tp.rsplit(":", 1)
    else:
        qid = ctxm.get("queryId")
        trace_id = str(qid) if qid else _new_id()
    if query is not None:
        attrs.setdefault("queryType", getattr(query, "query_type", ""))
        attrs.setdefault("dataSource", getattr(query, "datasource", ""))
    st = store if store is not None else trace_store()
    # the collector rides back in the response payload — bound it like the
    # store bounds a trace, or a span-heavy query bloats every reply
    return _SpanCtx(Span(
        trace_id=trace_id, span_id=_new_id(), parent_id=parent_id,
        name=name, service=service, attrs=attrs, store=st,
        collector=collections.deque(maxlen=st.max_spans_per_trace)
        if collect else None))


def with_traceparent(query, s: Span):
    """Copy of `query` whose context carries this span as the remote
    parent — what the broker POSTs to a data node."""
    from dataclasses import replace
    ctx = dict(query.context_map)
    ctx[TRACEPARENT_KEY] = f"{s.trace_id}:{s.span_id}"
    return replace(query, context=tuple(sorted(ctx.items())))


# ---------------------------------------------------------------------------
# TraceStore: bounded per-process ring buffer of assembled traces
# ---------------------------------------------------------------------------

class TraceStore:
    """trace id -> span list, LRU-by-creation ring: the oldest trace is
    evicted when `max_traces` is exceeded; spans beyond
    `max_spans_per_trace` are counted, not kept (a runaway span producer
    must not eat the process). Span ids dedupe — a data node sharing this
    process with the broker (in-process tests) records spans locally AND
    ships them back in the response; both paths land once."""

    def __init__(self, max_traces: int = 256,
                 max_spans_per_trace: int = 2048):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()

    def add(self, s: Span) -> None:
        self.add_json(s.to_json())

    def add_json(self, j: dict) -> None:
        tid = j.get("traceId")
        sid = j.get("spanId")
        if not tid or not sid:
            return
        with self._lock:
            t = self._traces.get(tid)
            if t is None:
                t = self._traces[tid] = {"spans": [], "ids": set(),
                                         "dropped": 0}
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            if sid in t["ids"]:
                return
            if len(t["spans"]) >= self.max_spans_per_trace:
                t["dropped"] += 1
                return
            t["ids"].add(sid)
            t["spans"].append(j)

    def ingest(self, spans) -> None:
        """Add remote span dicts (a data node's response payload)."""
        for j in spans or ():
            if isinstance(j, dict):
                self.add_json(j)

    def get(self, trace_id: str) -> Optional[dict]:
        """The assembled trace, spans sorted by start time; None when the
        id is unknown (or already evicted)."""
        with self._lock:
            t = self._traces.get(trace_id)
            if t is None:
                return None
            spans = sorted(t["spans"],
                           key=lambda s: (s.get("startMs") or 0.0))
            return {"traceId": trace_id, "spanCount": len(spans),
                    "droppedSpans": t["dropped"], "spans": spans}

    def spans(self, trace_id: str) -> List[dict]:
        got = self.get(trace_id)
        return got["spans"] if got else []

    def trace_ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


_STORE = TraceStore()


def trace_store() -> TraceStore:
    """The process-wide default store (every node type in this process)."""
    return _STORE


# ---------------------------------------------------------------------------
# Phase attribution -> per-query metrics
# ---------------------------------------------------------------------------

def spans_under(spans, root_span_id: Optional[str]) -> List[dict]:
    """The spans of ONE run: the root plus everything reachable from it by
    parentage. A client may legally reuse a queryId, landing several runs'
    spans in one store entry — per-run metrics must not sum across runs."""
    if root_span_id is None:
        return list(spans)
    children: Dict[Optional[str], List[dict]] = {}
    for s in spans:
        children.setdefault(s.get("parentId"), []).append(s)
    out = [s for s in spans if s.get("spanId") == root_span_id]
    stack = [root_span_id]
    while stack:
        for s in children.get(stack.pop(), ()):
            out.append(s)
            stack.append(s.get("spanId"))
    return out


def phase_breakdown(spans) -> Dict[str, float]:
    """Total duration per span name — the slow-query log's payload.
    Wire-ingested span dicts are unvalidated: nameless ones are skipped."""
    out: Dict[str, float] = {}
    for s in spans:
        d = s.get("durationMs")
        name = s.get("name")
        if d is not None and name:
            out[name] = round(out.get(name, 0.0) + d, 3)
    return out


def emit_trace_metrics(emitter, query, qid: str, spans) -> None:
    """Druid-authentic per-query phase metrics derived from the assembled
    trace: query/compile/time (jit-cache misses), query/stage/h2d/time
    (device-pool cold staging), query/node/time (per remote node wait).
    Emitted once per query by the lifecycle — phases that did not occur
    (cache-hit runs) emit nothing, which is itself the signal."""
    base = dict(dataSource=query.datasource, type=query.query_type, id=qid)
    compile_ms = sum(s["durationMs"] for s in spans
                     if s.get("name") == COMPILE_SPAN
                     and s.get("durationMs") is not None)
    if compile_ms:
        emitter.metric("query/compile/time", compile_ms, **base)
    h2d_ms = sum(s["durationMs"] for s in spans
                 if s.get("name") == H2D_SPAN
                 and s.get("durationMs") is not None)
    if h2d_ms:
        emitter.metric("query/stage/h2d/time", h2d_ms, **base)
    for s in spans:
        if s.get("name") == NODE_SPAN and s.get("durationMs") is not None:
            emitter.metric("query/node/time", s["durationMs"],
                           server=str(s.get("attrs", {}).get("server", "")),
                           **base)
