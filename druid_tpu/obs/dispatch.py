"""Device-dispatch accounting: one counter per device-callable invocation.

A "dispatch" is one invocation of a jitted device callable on the query
path — a per-segment grouped-aggregate program, a batched multi-segment
program, a bitmap-algebra fill program, a sharded mesh program. The count
is the engine's dispatch-amortization scoreboard: the megakernel's
contract (a cold query in exactly ONE dispatch — engine/megakernel.py) is
asserted against deltas of this counter, and `query/dispatch/count` makes
the same number a tick-window metric so a planner regression that
reintroduces a fill wave or splits a fused program shows up on dashboards,
not just in tests.

Deliberately NOT derived from qtrace spans: spans are off for
{"trace": false} queries and the witness must count every dispatch.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from druid_tpu.utils.emitter import Monitor


class DispatchStats:
    """Thread-safe per-kind dispatch counters (BatchStats discipline)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0
        self._by_kind: Dict[str, int] = {}

    def record(self, kind: str) -> None:
        with self._lock:
            self._total += 1
            self._by_kind[kind] = self._by_kind.get(kind, 0) + 1

    def count(self) -> int:
        with self._lock:
            return self._total

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._by_kind)
            out["total"] = self._total
            return out


_STATS = DispatchStats()


def record(kind: str) -> None:
    """Count one device dispatch of `kind` ("segment", "batched",
    "filterFill", "sharded") — called at the exact callable-invocation
    sites, never speculatively."""
    _STATS.record(kind)


def count() -> int:
    """Total dispatches this process has issued (test/bench delta basis)."""
    return _STATS.count()


def stats() -> DispatchStats:
    return _STATS


class DispatchMonitor(Monitor):
    """Emits `query/dispatch/count` per tick: dispatches since the last
    tick (delta, the FilterBitmapMonitor discipline)."""

    def __init__(self, source: Optional[DispatchStats] = None):
        self.source = source or _STATS
        self._last = self.source.count()

    def do_monitor(self, emitter):
        now = self.source.count()
        last, self._last = self._last, now
        emitter.metric("query/dispatch/count", now - last)
