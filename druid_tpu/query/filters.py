"""Dimension filter model (the query-layer JSON filter tree).

Capability parity with the reference's DimFilter hierarchy
(processing/src/main/java/org/apache/druid/query/filter/DimFilter.java and the
19 impls under segment/filter/). The *planning* of a filter (bitmap path vs
device-predicate path, CNF conversion, dictionary LUT construction) lives in
druid_tpu/engine/filters.py; this module is the pure data model + JSON serde.
"""
from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from druid_tpu.utils.intervals import Interval, normalize_intervals


class DimFilter:
    """Base filter node."""

    def to_json(self) -> dict:
        raise NotImplementedError

    # -- tree utilities ------------------------------------------------
    def required_columns(self) -> set:
        return set()

    def optimize(self) -> "DimFilter":
        return self


@dataclass(frozen=True)
class TrueFilter(DimFilter):
    def to_json(self):
        return {"type": "true"}


@dataclass(frozen=True)
class FalseFilter(DimFilter):
    def to_json(self):
        return {"type": "false"}


def _with_exfn(j: dict, fn) -> dict:
    if fn is not None:
        j["extractionFn"] = fn.to_json()
    return j


@dataclass(frozen=True)
class SelectorFilter(DimFilter):
    """dimension == value (reference: query/filter/SelectorDimFilter.java).
    An optional extraction_fn transforms each dictionary value BEFORE the
    comparison — the dimension-extraction filter surface every leaf string
    filter shares in the reference."""
    dimension: str
    value: Optional[str]
    extraction_fn: Optional[object] = None

    def to_json(self):
        return _with_exfn({"type": "selector", "dimension": self.dimension,
                           "value": self.value}, self.extraction_fn)

    def required_columns(self):
        return {self.dimension}


@dataclass(frozen=True)
class InFilter(DimFilter):
    """dimension IN (values) (reference: query/filter/InDimFilter.java)."""
    dimension: str
    values: Tuple[Optional[str], ...]
    extraction_fn: Optional[object] = None

    def to_json(self):
        return _with_exfn({"type": "in", "dimension": self.dimension,
                           "values": list(self.values)}, self.extraction_fn)

    def required_columns(self):
        return {self.dimension}

    def optimize(self):
        if len(self.values) == 1:
            return SelectorFilter(self.dimension, self.values[0],
                                  self.extraction_fn)
        return self


@dataclass(frozen=True)
class BoundFilter(DimFilter):
    """Range filter, lexicographic or numeric ordering
    (reference: query/filter/BoundDimFilter.java)."""
    dimension: str
    lower: Optional[str] = None
    upper: Optional[str] = None
    lower_strict: bool = False
    upper_strict: bool = False
    ordering: str = "lexicographic"  # or "numeric"
    extraction_fn: Optional[object] = None

    def to_json(self):
        return _with_exfn(
            {"type": "bound", "dimension": self.dimension,
             "lower": self.lower, "upper": self.upper,
             "lowerStrict": self.lower_strict,
             "upperStrict": self.upper_strict,
             "ordering": self.ordering}, self.extraction_fn)

    def required_columns(self):
        return {self.dimension}


@dataclass(frozen=True)
class LikeFilter(DimFilter):
    """SQL LIKE (reference: query/filter/LikeDimFilter.java)."""
    dimension: str
    pattern: str
    escape: Optional[str] = None
    extraction_fn: Optional[object] = None

    def regex(self) -> str:
        out, i = [], 0
        esc = self.escape
        p = self.pattern
        while i < len(p):
            c = p[i]
            if esc and c == esc and i + 1 < len(p):
                out.append(re.escape(p[i + 1])); i += 2; continue
            if c == "%":
                out.append(".*")
            elif c == "_":
                out.append(".")
            else:
                out.append(re.escape(c))
            i += 1
        return "^" + "".join(out) + "$"

    def to_json(self):
        return _with_exfn({"type": "like", "dimension": self.dimension,
                           "pattern": self.pattern, "escape": self.escape},
                          self.extraction_fn)

    def required_columns(self):
        return {self.dimension}


@dataclass(frozen=True)
class RegexFilter(DimFilter):
    dimension: str
    pattern: str
    extraction_fn: Optional[object] = None

    def to_json(self):
        return _with_exfn({"type": "regex", "dimension": self.dimension,
                           "pattern": self.pattern}, self.extraction_fn)

    def required_columns(self):
        return {self.dimension}


@dataclass(frozen=True)
class SearchFilter(DimFilter):
    """contains/insensitive_contains/fragment search on dim values
    (reference: query/filter/SearchQueryDimFilter.java)."""
    dimension: str
    value: str
    case_sensitive: bool = False
    extraction_fn: Optional[object] = None

    def to_json(self):
        return _with_exfn(
            {"type": "search", "dimension": self.dimension,
             "query": {"type": "contains", "value": self.value,
                       "caseSensitive": self.case_sensitive}},
            self.extraction_fn)

    def required_columns(self):
        return {self.dimension}


@dataclass(frozen=True)
class IntervalFilter(DimFilter):
    """__time (or numeric dim) within intervals
    (reference: query/filter/IntervalDimFilter.java)."""
    dimension: str
    intervals: Tuple[Interval, ...]

    def to_json(self):
        return {"type": "interval", "dimension": self.dimension,
                "intervals": [str(iv) for iv in self.intervals]}

    def required_columns(self):
        return {self.dimension}


@dataclass(frozen=True)
class ColumnComparisonFilter(DimFilter):
    """dimA == dimB row-wise (reference: query/filter/ColumnComparisonDimFilter.java)."""
    dimensions: Tuple[str, ...]

    def to_json(self):
        return {"type": "columnComparison", "dimensions": list(self.dimensions)}

    def required_columns(self):
        return set(self.dimensions)


@dataclass(frozen=True)
class ExpressionFilter(DimFilter):
    """Expression-language predicate (reference: query/filter/ExpressionDimFilter.java)."""
    expression: str

    def to_json(self):
        return {"type": "expression", "expression": self.expression}

    def required_columns(self):
        from druid_tpu.utils.expression import parse_expression
        return set(parse_expression(self.expression).required_columns())


@dataclass(frozen=True)
class JavaScriptFilter(DimFilter):
    """Reference has a Rhino JS filter (query/filter/JavaScriptDimFilter.java).
    The TPU framework has no embedded JS engine; accepts a python callable
    evaluated host-side over dictionary values instead (gated, like the
    reference's JavaScriptConfig enable flag)."""
    dimension: str
    predicate: object  # Callable[[str], bool]

    def to_json(self):
        return {"type": "javascript", "dimension": self.dimension,
                "function": "<python-callable>"}

    def required_columns(self):
        return {self.dimension}


@dataclass(frozen=True)
class AndFilter(DimFilter):
    fields: Tuple[DimFilter, ...]

    def to_json(self):
        return {"type": "and", "fields": [f.to_json() for f in self.fields]}

    def required_columns(self):
        out = set()
        for f in self.fields:
            out |= f.required_columns()
        return out

    def optimize(self):
        flat: List[DimFilter] = []
        for f in self.fields:
            f = f.optimize()
            if isinstance(f, AndFilter):
                flat.extend(f.fields)
            elif isinstance(f, TrueFilter):
                continue
            elif isinstance(f, FalseFilter):
                return FalseFilter()
            else:
                flat.append(f)
        if not flat:
            return TrueFilter()
        if len(flat) == 1:
            return flat[0]
        return AndFilter(tuple(flat))


@dataclass(frozen=True)
class OrFilter(DimFilter):
    fields: Tuple[DimFilter, ...]

    def to_json(self):
        return {"type": "or", "fields": [f.to_json() for f in self.fields]}

    def required_columns(self):
        out = set()
        for f in self.fields:
            out |= f.required_columns()
        return out

    def optimize(self):
        flat: List[DimFilter] = []
        for f in self.fields:
            f = f.optimize()
            if isinstance(f, OrFilter):
                flat.extend(f.fields)
            elif isinstance(f, FalseFilter):
                continue
            elif isinstance(f, TrueFilter):
                return TrueFilter()
            else:
                flat.append(f)
        if not flat:
            return FalseFilter()
        if len(flat) == 1:
            return flat[0]
        return OrFilter(tuple(flat))


@dataclass(frozen=True)
class NotFilter(DimFilter):
    field: DimFilter

    def to_json(self):
        return {"type": "not", "field": self.field.to_json()}

    def required_columns(self):
        return self.field.required_columns()

    def optimize(self):
        f = self.field.optimize()
        if isinstance(f, NotFilter):
            return f.field
        if isinstance(f, TrueFilter):
            return FalseFilter()
        if isinstance(f, FalseFilter):
            return TrueFilter()
        return NotFilter(f)


# convenience constructors mirroring Druids builders
def and_(*fs: DimFilter) -> DimFilter:
    return AndFilter(tuple(fs)).optimize()


def or_(*fs: DimFilter) -> DimFilter:
    return OrFilter(tuple(fs)).optimize()


def not_(f: DimFilter) -> DimFilter:
    return NotFilter(f).optimize()


class SpatialBound:
    """Geometric region for spatial filters (reference:
    collections/spatial/search/Bound.java)."""

    @staticmethod
    def from_json(j: dict) -> "SpatialBound":
        t = j["type"]
        if t == "rectangular":
            return RectangularBound(tuple(j["minCoords"]),
                                    tuple(j["maxCoords"]))
        if t == "radius":
            return RadiusBound(tuple(j["coords"]), float(j["radius"]))
        if t == "polygon":
            return PolygonBound(tuple(j["abscissa"]), tuple(j["ordinate"]))
        raise ValueError(f"unknown spatial bound type {t!r}")

    def to_json(self) -> dict:
        raise NotImplementedError

    def contains(self, coords) -> bool:
        raise NotImplementedError


@dataclass(frozen=True)
class RectangularBound(SpatialBound):
    """Axis-aligned box in any dimensionality
    (collections/spatial/search/RectangularBound.java)."""
    min_coords: tuple
    max_coords: tuple

    def to_json(self):
        return {"type": "rectangular", "minCoords": list(self.min_coords),
                "maxCoords": list(self.max_coords)}

    def contains(self, coords):
        if len(coords) != len(self.min_coords):
            return False
        return all(lo <= c <= hi for c, lo, hi in
                   zip(coords, self.min_coords, self.max_coords))


@dataclass(frozen=True)
class RadiusBound(SpatialBound):
    """Euclidean ball (collections/spatial/search/RadiusBound.java)."""
    coords: tuple
    radius: float

    def to_json(self):
        return {"type": "radius", "coords": list(self.coords),
                "radius": self.radius}

    def contains(self, coords):
        if len(coords) != len(self.coords):
            return False
        return sum((c - o) ** 2 for c, o in
                   zip(coords, self.coords)) <= self.radius ** 2


@dataclass(frozen=True)
class PolygonBound(SpatialBound):
    """2-D polygon via even-odd ray casting
    (collections/spatial/search/PolygonBound.java)."""
    abscissa: tuple    # x of each vertex
    ordinate: tuple    # y of each vertex

    def to_json(self):
        return {"type": "polygon", "abscissa": list(self.abscissa),
                "ordinate": list(self.ordinate)}

    def contains(self, coords):
        if len(coords) != 2:
            return False
        x, y = coords
        n = len(self.abscissa)
        inside = False
        j = n - 1
        for i in range(n):
            xi, yi = self.abscissa[i], self.ordinate[i]
            xj, yj = self.abscissa[j], self.ordinate[j]
            if (yi > y) != (yj > y) and \
                    x < (xj - xi) * (y - yi) / (yj - yi) + xi:
                inside = not inside
            j = i
        return inside


@dataclass(frozen=True)
class SpatialFilter(DimFilter):
    """Spatial dimension filter (reference: query/filter/SpatialDimFilter
    .java over an ImmutableRTree index). The spatial dimension stores
    joined 'x,y[,z...]' coordinate strings; evaluation is a per-dictionary-
    VALUE bound test — O(cardinality), the same index-not-rows cost profile
    as the reference's r-tree search — flowing through the standard LUT /
    bitmap machinery."""
    dimension: str
    bound: SpatialBound

    def to_json(self):
        return {"type": "spatial", "dimension": self.dimension,
                "bound": self.bound.to_json()}

    def required_columns(self):
        return {self.dimension}

    def value_predicate(self):
        bound = self.bound

        def pred(v) -> bool:
            try:
                coords = tuple(float(p) for p in str(v).split(","))
            except (TypeError, ValueError):
                return False
            return bound.contains(coords)
        return pred


# extension-registered filter types (druid_tpu/ext/)
_EXTENSION_FILTERS: dict = {}


def register_filter(type_name: str, from_json) -> None:
    _EXTENSION_FILTERS[type_name] = from_json


def filter_from_json(j: Optional[dict]) -> Optional[DimFilter]:
    """JSON-polymorphic deserialization, mirroring the reference's Jackson
    @JsonSubTypes registration on DimFilter."""
    if j is None:
        return None
    t = j["type"]
    if t in _EXTENSION_FILTERS:
        return _EXTENSION_FILTERS[t](j)
    if t == "spatial":
        return SpatialFilter(j["dimension"],
                             SpatialBound.from_json(j["bound"]))
    exfn = None
    if j.get("extractionFn") is not None:
        # lazy: extraction fns live in query.model, which imports this module
        from druid_tpu.query.model import extractionfn_from_json
        exfn = extractionfn_from_json(j["extractionFn"])
        if t not in ("selector", "in", "bound", "like", "regex", "search"):
            # silently dropping the fn would return wrong rows
            raise ValueError(f"extractionFn unsupported on filter type {t!r}")
    if t == "selector":
        return SelectorFilter(j["dimension"], j.get("value"), exfn)
    if t == "in":
        return InFilter(j["dimension"], tuple(j["values"]), exfn)
    if t == "bound":
        return BoundFilter(j["dimension"], j.get("lower"), j.get("upper"),
                           j.get("lowerStrict", False), j.get("upperStrict", False),
                           j.get("ordering", "lexicographic"), exfn)
    if t == "like":
        return LikeFilter(j["dimension"], j["pattern"], j.get("escape"),
                          exfn)
    if t == "regex":
        return RegexFilter(j["dimension"], j["pattern"], exfn)
    if t == "search":
        q = j.get("query", {})
        return SearchFilter(j["dimension"], q.get("value", ""),
                            q.get("caseSensitive", False), exfn)
    if t == "interval":
        return IntervalFilter(j["dimension"],
                              tuple(normalize_intervals(j["intervals"])))
    if t == "columnComparison":
        return ColumnComparisonFilter(tuple(j["dimensions"]))
    if t == "expression":
        return ExpressionFilter(j["expression"])
    if t == "and":
        return AndFilter(tuple(filter_from_json(f) for f in j["fields"]))
    if t == "or":
        return OrFilter(tuple(filter_from_json(f) for f in j["fields"]))
    if t == "not":
        return NotFilter(filter_from_json(j["field"]))
    if t == "true":
        return TrueFilter()
    if t == "false":
        return FalseFilter()
    raise ValueError(f"unknown filter type {t!r}")
