"""The polymorphic query model: 9 query types.

Capability parity with the reference's Query registration
(processing/src/main/java/org/apache/druid/query/Query.java:61-69):
timeseries, search, timeBoundary, groupBy, scan, segmentMetadata, select,
topN, dataSourceMetadata. Queries are frozen dataclasses; JSON serde mirrors
the reference's Jackson wire format so native-query payloads translate 1:1.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from druid_tpu.query import lookup as _lookup_mod
from druid_tpu.query.aggregators import AggregatorSpec, agg_from_json
from druid_tpu.query.filters import DimFilter, filter_from_json
from druid_tpu.query.postaggs import PostAggregator, postagg_from_json
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval, normalize_intervals


# ---------------------------------------------------------------------------
# Dimension specs + extraction fns (reference: query/dimension/, query/extraction/)
# ---------------------------------------------------------------------------

class ExtractionFn:
    """Host-side value transform applied to dictionary values at plan time
    (reference: query/extraction/ExtractionFn.java). Because dictionaries are
    small relative to rows, extraction is O(cardinality) host work producing
    an id remap table — never a per-row device op."""

    def apply(self, value: Optional[str]) -> Optional[str]:
        raise NotImplementedError

    def to_json(self) -> dict:
        raise NotImplementedError

    def cache_key(self) -> dict:
        """Key for per-segment id-remap caches. Defaults to the wire form;
        fns whose output depends on external state (registered lookups) must
        mix that state's version in so stale remaps are not served."""
        return self.to_json()

    def apply_all(self, values):
        """Batch apply over a dictionary's values (the engine's remap loop).
        Override where per-call setup (registry resolution) would otherwise
        repeat O(cardinality) times."""
        return [self.apply(v) for v in values]


@dataclass(frozen=True)
class SubstringExtractionFn(ExtractionFn):
    index: int
    length: Optional[int] = None

    def apply(self, value):
        if value is None or value == "":
            return None
        if self.index >= len(value):
            return None
        end = None if self.length is None else self.index + self.length
        return value[self.index:end]

    def to_json(self):
        return {"type": "substring", "index": self.index, "length": self.length}


@dataclass(frozen=True)
class RegexExtractionFn(ExtractionFn):
    expr: str
    index: int = 1
    replace_missing: bool = False
    replacement: Optional[str] = None

    def apply(self, value):
        m = re.search(self.expr, value or "")
        if m and m.groups():
            return m.group(self.index)
        if m and self.index == 0:
            return m.group(0)
        return self.replacement if self.replace_missing else value

    def to_json(self):
        return {"type": "regex", "expr": self.expr, "index": self.index,
                "replaceMissingValue": self.replace_missing,
                "replaceMissingValueWith": self.replacement}


@dataclass(frozen=True)
class UpperExtractionFn(ExtractionFn):
    def apply(self, value):
        return value.upper() if value else value

    def to_json(self):
        return {"type": "upper"}


@dataclass(frozen=True)
class LowerExtractionFn(ExtractionFn):
    def apply(self, value):
        return value.lower() if value else value

    def to_json(self):
        return {"type": "lower"}


@dataclass(frozen=True)
class LookupExtractionFn(ExtractionFn):
    """key→value map extraction (reference: query/lookup/LookupExtractionFn.java)."""
    lookup: Tuple[Tuple[str, str], ...]
    retain_missing: bool = True
    replace_missing: Optional[str] = None

    def apply(self, value):
        m = dict(self.lookup)
        if value in m:
            return m[value]
        return value if self.retain_missing else self.replace_missing

    def to_json(self):
        return {"type": "lookup", "lookup": {"type": "map", "map": dict(self.lookup)},
                "retainMissingValue": self.retain_missing,
                "replaceMissingValueWith": self.replace_missing}


@dataclass(frozen=True)
class StrlenExtractionFn(ExtractionFn):
    """reference: query/extraction/StrlenExtractionFn.java"""
    def apply(self, value):
        return str(len(value)) if value is not None else "0"

    def to_json(self):
        return {"type": "strlen"}


@dataclass(frozen=True)
class StringFormatExtractionFn(ExtractionFn):
    """reference: query/extraction/StringFormatExtractionFn.java — %-style
    format applied to the dim value; nullHandling returnNull|emptyString."""
    format: str
    null_handling: str = "nullString"

    def apply(self, value):
        if value is None:
            if self.null_handling == "returnNull":
                return None
            # nullString renders as Java's "null", emptyString as ""
            value = "" if self.null_handling == "emptyString" else "null"
        return self.format % (value,)

    def to_json(self):
        return {"type": "stringFormat", "format": self.format,
                "nullHandling": self.null_handling}


@dataclass(frozen=True)
class TimeFormatExtractionFn(ExtractionFn):
    """reference: query/extraction/TimeFormatExtractionFn.java. Parses the
    value as an ISO timestamp (or epoch millis) and reformats via strftime;
    optional granularity truncation first. Joda patterns are mapped to the
    common strftime subset (yyyy, MM, dd, HH, mm, ss, EEEE, MMMM)."""
    format: Optional[str] = None
    granularity: Optional[str] = None

    # longest-pattern-first so e.g. MMMM is not consumed by MM
    _JODA = (("yyyy", "%Y"), ("MMMM", "%B"), ("MMM", "%b"), ("MM", "%m"),
             ("dd", "%d"), ("HH", "%H"), ("mm", "%M"), ("ss", "%S"),
             ("EEEE", "%A"), ("EEE", "%a"))

    def apply(self, value):
        import datetime as _dt

        from druid_tpu.utils.intervals import parse_ts, ts_to_iso
        if value is None:
            return None
        try:
            ms = parse_ts(value)
        except (ValueError, TypeError):
            # epoch-millis strings (dictionary values are always str)
            try:
                ms = int(value)
            except (ValueError, TypeError):
                return None
        if self.granularity:
            ms = Granularity.of(self.granularity).bucket_start(ms)
        if self.format is None:
            return ts_to_iso(ms)
        dt = _dt.datetime.fromtimestamp(ms / 1000.0, _dt.timezone.utc)
        fmt = self.format
        for joda, std in self._JODA:
            fmt = fmt.replace(joda, std)
        return dt.strftime(fmt)

    def to_json(self):
        return {"type": "timeFormat", "format": self.format,
                "granularity": self.granularity}


@dataclass(frozen=True)
class CascadeExtractionFn(ExtractionFn):
    """reference: query/extraction/CascadeExtractionFn.java — chain."""
    fns: Tuple[ExtractionFn, ...] = ()

    def apply(self, value):
        for fn in self.fns:
            value = fn.apply(value)
        return value

    def apply_all(self, values):
        for fn in self.fns:
            values = fn.apply_all(values)
        return list(values)

    def to_json(self):
        return {"type": "cascade",
                "extractionFns": [f.to_json() for f in self.fns]}

    def cache_key(self):
        return {"type": "cascade",
                "extractionFns": [f.cache_key() for f in self.fns]}


@dataclass(frozen=True)
class RegisteredLookupExtractionFn(ExtractionFn):
    """Named lookup resolved against the process-wide lookup registry
    (reference: query/lookup/RegisteredLookupExtractionFn.java +
    LookupReferencesManager)."""
    lookup: str
    retain_missing: bool = True
    replace_missing: Optional[str] = None

    def apply(self, value):
        return self._apply_with(_lookup_mod.get_lookup(self.lookup), value)

    def _apply_with(self, m, value):
        if value in m:
            return m[value]
        return value if self.retain_missing else self.replace_missing

    def apply_all(self, values):
        m = _lookup_mod.get_lookup(self.lookup)  # resolve registry once
        return [self._apply_with(m, v) for v in values]

    def to_json(self):
        return {"type": "registeredLookup", "lookup": self.lookup,
                "retainMissingValue": self.retain_missing,
                "replaceMissingValueWith": self.replace_missing}

    def cache_key(self):
        c = _lookup_mod.lookup_manager().get(self.lookup)
        j = self.to_json()
        j["_lookupVersion"] = c.version if c is not None else None
        return j


class DimensionSpec:
    dimension: str
    output_name: str

    @property
    def extraction_fn(self) -> Optional[ExtractionFn]:
        return None


@dataclass(frozen=True)
class DefaultDimensionSpec(DimensionSpec):
    dimension: str
    output_name: str = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.output_name is None:
            object.__setattr__(self, "output_name", self.dimension)

    def to_json(self):
        return {"type": "default", "dimension": self.dimension,
                "outputName": self.output_name}


@dataclass(frozen=True)
class ExtractionDimensionSpec(DimensionSpec):
    dimension: str
    output_name: str
    fn: ExtractionFn = None

    @property
    def extraction_fn(self):
        return self.fn

    def to_json(self):
        return {"type": "extraction", "dimension": self.dimension,
                "outputName": self.output_name, "extractionFn": self.fn.to_json()}


@dataclass(frozen=True)
class ListFilteredDimensionSpec(DimensionSpec):
    """reference: query/dimension/ListFilteredDimensionSpec.java"""
    delegate: DimensionSpec = None
    values: Tuple[str, ...] = ()
    is_whitelist: bool = True

    @property
    def dimension(self):
        return self.delegate.dimension

    @property
    def output_name(self):
        return self.delegate.output_name

    @property
    def extraction_fn(self):
        return self.delegate.extraction_fn

    def to_json(self):
        return {"type": "listFiltered", "delegate": self.delegate.to_json(),
                "values": list(self.values), "isWhitelist": self.is_whitelist}


@dataclass(frozen=True)
class ExpressionDimensionSpec(DimensionSpec):
    """Group by a computed expression (the capability of the reference's
    virtualColumn-as-dimension path). Evaluated HOST-side per segment into
    a query-time value dictionary — the device then groups by compact ids
    exactly like any other dimension (engines._keydim_for)."""
    expression: str = ""
    output_name: str = ""
    output_type: str = "long"     # long | double | string

    @property
    def dimension(self):
        return self.output_name

    def to_json(self):
        return {"type": "expression", "expression": self.expression,
                "outputName": self.output_name,
                "outputType": self.output_type}


def dimspec_from_json(j) -> DimensionSpec:
    if isinstance(j, str):
        return DefaultDimensionSpec(j, j)
    t = j.get("type", "default")
    if t == "default":
        return DefaultDimensionSpec(j["dimension"], j.get("outputName") or j["dimension"])
    if t == "expression":
        return ExpressionDimensionSpec(j["expression"],
                                       j.get("outputName") or "expr",
                                       j.get("outputType", "long"))
    if t == "extraction":
        return ExtractionDimensionSpec(j["dimension"],
                                       j.get("outputName") or j["dimension"],
                                       extractionfn_from_json(j["extractionFn"]))
    if t == "listFiltered":
        return ListFilteredDimensionSpec(dimspec_from_json(j["delegate"]),
                                         tuple(j["values"]),
                                         j.get("isWhitelist", True))
    raise ValueError(f"unknown dimension spec {t!r}")


def extractionfn_from_json(j) -> ExtractionFn:
    t = j["type"]
    if t == "substring":
        return SubstringExtractionFn(j["index"], j.get("length"))
    if t == "regex":
        return RegexExtractionFn(j["expr"], j.get("index", 1),
                                 j.get("replaceMissingValue", False),
                                 j.get("replaceMissingValueWith"))
    if t == "upper":
        return UpperExtractionFn()
    if t == "lower":
        return LowerExtractionFn()
    if t == "lookup":
        return LookupExtractionFn(tuple(j["lookup"]["map"].items()),
                                  j.get("retainMissingValue", True),
                                  j.get("replaceMissingValueWith"))
    if t == "strlen":
        return StrlenExtractionFn()
    if t == "stringFormat":
        return StringFormatExtractionFn(j["format"],
                                        j.get("nullHandling", "nullString"))
    if t == "timeFormat":
        return TimeFormatExtractionFn(j.get("format"), j.get("granularity"))
    if t == "cascade":
        return CascadeExtractionFn(
            tuple(extractionfn_from_json(f) for f in j["extractionFns"]))
    if t == "registeredLookup":
        return RegisteredLookupExtractionFn(j["lookup"],
                                            j.get("retainMissingValue", True),
                                            j.get("replaceMissingValueWith"))
    raise ValueError(f"unknown extraction fn {t!r}")


# ---------------------------------------------------------------------------
# Limit / having specs (reference: query/groupby/orderby/, query/groupby/having/)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class OrderByColumnSpec:
    dimension: str
    direction: str = "ascending"   # ascending | descending
    dimension_order: str = "lexicographic"  # lexicographic | numeric

    def to_json(self):
        return {"dimension": self.dimension, "direction": self.direction,
                "dimensionOrder": self.dimension_order}


@dataclass(frozen=True)
class DefaultLimitSpec:
    columns: Tuple[OrderByColumnSpec, ...] = ()
    limit: Optional[int] = None
    offset: int = 0

    def to_json(self):
        return {"type": "default",
                "columns": [c.to_json() for c in self.columns],
                "limit": self.limit, "offset": self.offset}


class HavingSpec:
    def evaluate(self, row: Dict[str, object]) -> bool:
        raise NotImplementedError

    def to_json(self) -> dict:
        raise NotImplementedError


@dataclass(frozen=True)
class GreaterThanHaving(HavingSpec):
    aggregation: str
    value: float

    def evaluate(self, row):
        return float(row.get(self.aggregation, 0)) > self.value

    def to_json(self):
        return {"type": "greaterThan", "aggregation": self.aggregation,
                "value": self.value}


@dataclass(frozen=True)
class LessThanHaving(HavingSpec):
    aggregation: str
    value: float

    def evaluate(self, row):
        return float(row.get(self.aggregation, 0)) < self.value

    def to_json(self):
        return {"type": "lessThan", "aggregation": self.aggregation,
                "value": self.value}


@dataclass(frozen=True)
class EqualToHaving(HavingSpec):
    aggregation: str
    value: float

    def evaluate(self, row):
        return float(row.get(self.aggregation, 0)) == self.value

    def to_json(self):
        return {"type": "equalTo", "aggregation": self.aggregation,
                "value": self.value}


@dataclass(frozen=True)
class AndHaving(HavingSpec):
    specs: Tuple[HavingSpec, ...]

    def evaluate(self, row):
        return all(s.evaluate(row) for s in self.specs)

    def to_json(self):
        return {"type": "and", "havingSpecs": [s.to_json() for s in self.specs]}


@dataclass(frozen=True)
class OrHaving(HavingSpec):
    specs: Tuple[HavingSpec, ...]

    def evaluate(self, row):
        return any(s.evaluate(row) for s in self.specs)

    def to_json(self):
        return {"type": "or", "havingSpecs": [s.to_json() for s in self.specs]}


@dataclass(frozen=True)
class NotHaving(HavingSpec):
    spec: HavingSpec

    def evaluate(self, row):
        return not self.spec.evaluate(row)

    def to_json(self):
        return {"type": "not", "havingSpec": self.spec.to_json()}


@dataclass(frozen=True)
class DimSelectorHaving(HavingSpec):
    dimension: str
    value: Optional[str]

    def evaluate(self, row):
        return row.get(self.dimension) == self.value

    def to_json(self):
        return {"type": "dimSelector", "dimension": self.dimension,
                "value": self.value}


@dataclass(frozen=True)
class FilterHaving(HavingSpec):
    """reference: query/groupby/having/DimFilterHavingSpec.java — evaluated
    host-side over result rows."""
    filter: DimFilter

    def evaluate(self, row):
        from druid_tpu.engine.filters import evaluate_filter_on_row
        return evaluate_filter_on_row(self.filter, row)

    def to_json(self):
        return {"type": "filter", "filter": self.filter.to_json()}


def having_from_json(j) -> Optional[HavingSpec]:
    if j is None:
        return None
    t = j["type"]
    if t == "greaterThan":
        return GreaterThanHaving(j["aggregation"], j["value"])
    if t == "lessThan":
        return LessThanHaving(j["aggregation"], j["value"])
    if t == "equalTo":
        return EqualToHaving(j["aggregation"], j["value"])
    if t == "and":
        return AndHaving(tuple(having_from_json(s) for s in j["havingSpecs"]))
    if t == "or":
        return OrHaving(tuple(having_from_json(s) for s in j["havingSpecs"]))
    if t == "not":
        return NotHaving(having_from_json(j["havingSpec"]))
    if t == "dimSelector":
        return DimSelectorHaving(j["dimension"], j.get("value"))
    if t == "filter":
        return FilterHaving(filter_from_json(j["filter"]))
    raise ValueError(f"unknown having spec {t!r}")


# ---------------------------------------------------------------------------
# Virtual columns (reference: segment/VirtualColumns.java, ExpressionVirtualColumn)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ExpressionVirtualColumn:
    name: str
    expression: str
    output_type: str = "double"  # long | double | float | string

    def to_json(self):
        return {"type": "expression", "name": self.name,
                "expression": self.expression, "outputType": self.output_type}


def virtualcolumn_from_json(j) -> ExpressionVirtualColumn:
    if j["type"] != "expression":
        raise ValueError(f"unknown virtual column {j['type']!r}")
    return ExpressionVirtualColumn(j["name"], j["expression"],
                                   j.get("outputType", "double"))


# ---------------------------------------------------------------------------
# Queries
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Query:
    datasource: str = ""
    intervals: Tuple[Interval, ...] = ()
    filter: Optional[DimFilter] = None
    granularity: Granularity = Granularity.ALL
    virtual_columns: Tuple[ExpressionVirtualColumn, ...] = ()
    context: Tuple[Tuple[str, object], ...] = ()
    # polymorphic data sources (reference: query/TableDataSource /
    # UnionDataSource / QueryDataSource): a non-None inner_query makes this
    # a subquery (executor materializes inner groupBy results as a segment,
    # mirroring GroupByStrategyV2.processSubqueryResult); a non-empty
    # union_datasources unions several tables' segments
    inner_query: Optional["Query"] = None
    union_datasources: Tuple[str, ...] = ()

    query_type: str = "base"

    @property
    def context_map(self) -> Dict[str, object]:
        return dict(self.context)

    def required_columns(self) -> set:
        out = set()
        if self.filter is not None:
            out |= self.filter.required_columns()
        return out

    def _datasource_json(self):
        if self.inner_query is not None:
            return {"type": "query", "query": self.inner_query.to_json()}
        if self.union_datasources:
            return {"type": "union",
                    "dataSources": list(self.union_datasources)}
        return self.datasource

    def base_json(self) -> dict:
        return {
            "queryType": self.query_type,
            "dataSource": self._datasource_json(),
            "intervals": [str(iv) for iv in self.intervals],
            "filter": self.filter.to_json() if self.filter else None,
            "granularity": str(self.granularity),
            "virtualColumns": [v.to_json() for v in self.virtual_columns],
            "context": dict(self.context),
        }

    def to_json(self) -> dict:
        return self.base_json()


def _mk(datasource, intervals, flt, granularity, virtual_columns, context):
    return dict(
        datasource=datasource,
        intervals=tuple(normalize_intervals(intervals)),
        filter=flt,
        granularity=Granularity.of(granularity),
        virtual_columns=tuple(virtual_columns or ()),
        context=tuple(sorted((context or {}).items())),
    )


@dataclass(frozen=True)
class TimeseriesQuery(Query):
    """reference: query/timeseries/TimeseriesQuery.java"""
    aggregations: Tuple[AggregatorSpec, ...] = ()
    post_aggregations: Tuple[PostAggregator, ...] = ()
    descending: bool = False
    skip_empty_buckets: bool = False
    query_type: str = "timeseries"

    @staticmethod
    def of(datasource, intervals, aggregations, granularity="all", filter=None,
           post_aggregations=(), descending=False, skip_empty_buckets=False,
           virtual_columns=(), context=None) -> "TimeseriesQuery":
        return TimeseriesQuery(
            aggregations=tuple(aggregations),
            post_aggregations=tuple(post_aggregations),
            descending=descending, skip_empty_buckets=skip_empty_buckets,
            **_mk(datasource, intervals, filter, granularity, virtual_columns,
                  context))

    def required_columns(self):
        out = super().required_columns()
        for a in self.aggregations:
            out |= a.required_columns()
        return out

    def to_json(self):
        j = self.base_json()
        j.update(aggregations=[a.to_json() for a in self.aggregations],
                 postAggregations=[p.to_json() for p in self.post_aggregations],
                 descending=self.descending)
        return j


@dataclass(frozen=True)
class TopNQuery(Query):
    """reference: query/topn/TopNQuery.java"""
    dimension: DimensionSpec = None
    metric: str = ""               # ordering metric name (agg or postagg)
    metric_ordering: str = "numeric"  # numeric | lexicographic | inverted(...)
    threshold: int = 10
    aggregations: Tuple[AggregatorSpec, ...] = ()
    post_aggregations: Tuple[PostAggregator, ...] = ()
    query_type: str = "topN"

    @staticmethod
    def of(datasource, intervals, dimension, metric, threshold, aggregations,
           granularity="all", filter=None, post_aggregations=(),
           metric_ordering="numeric", virtual_columns=(), context=None) -> "TopNQuery":
        dim = dimension if isinstance(dimension, DimensionSpec) \
            else DefaultDimensionSpec(dimension, dimension)
        return TopNQuery(
            dimension=dim, metric=metric, metric_ordering=metric_ordering,
            threshold=threshold, aggregations=tuple(aggregations),
            post_aggregations=tuple(post_aggregations),
            **_mk(datasource, intervals, filter, granularity, virtual_columns,
                  context))

    def required_columns(self):
        out = super().required_columns() | {self.dimension.dimension}
        for a in self.aggregations:
            out |= a.required_columns()
        return out

    def to_json(self):
        j = self.base_json()
        j.update(dimension=self.dimension.to_json(), metric=self.metric,
                 threshold=self.threshold,
                 aggregations=[a.to_json() for a in self.aggregations],
                 postAggregations=[p.to_json() for p in self.post_aggregations])
        return j


@dataclass(frozen=True)
class GroupByQuery(Query):
    """reference: query/groupby/GroupByQuery.java"""
    dimensions: Tuple[DimensionSpec, ...] = ()
    aggregations: Tuple[AggregatorSpec, ...] = ()
    post_aggregations: Tuple[PostAggregator, ...] = ()
    having: Optional[HavingSpec] = None
    limit_spec: Optional[DefaultLimitSpec] = None
    subtotals: Tuple[Tuple[str, ...], ...] = ()
    query_type: str = "groupBy"

    @staticmethod
    def of(datasource, intervals, dimensions, aggregations, granularity="all",
           filter=None, post_aggregations=(), having=None, limit_spec=None,
           subtotals=(), virtual_columns=(), context=None) -> "GroupByQuery":
        dims = tuple(d if isinstance(d, DimensionSpec)
                     else DefaultDimensionSpec(d, d) for d in dimensions)
        return GroupByQuery(
            dimensions=dims, aggregations=tuple(aggregations),
            post_aggregations=tuple(post_aggregations), having=having,
            limit_spec=limit_spec,
            subtotals=tuple(tuple(s) for s in subtotals),
            **_mk(datasource, intervals, filter, granularity, virtual_columns,
                  context))

    def required_columns(self):
        out = super().required_columns()
        out |= {d.dimension for d in self.dimensions}
        for a in self.aggregations:
            out |= a.required_columns()
        return out

    def to_json(self):
        j = self.base_json()
        j.update(dimensions=[d.to_json() for d in self.dimensions],
                 aggregations=[a.to_json() for a in self.aggregations],
                 postAggregations=[p.to_json() for p in self.post_aggregations],
                 having=self.having.to_json() if self.having else None,
                 limitSpec=self.limit_spec.to_json() if self.limit_spec else None,
                 subtotalsSpec=[list(s) for s in self.subtotals] or None)
        return j


@dataclass(frozen=True)
class ScanQuery(Query):
    """reference: query/scan/ScanQuery.java — streaming raw-row export."""
    columns: Tuple[str, ...] = ()
    limit: Optional[int] = None
    offset: int = 0
    order: str = "none"  # none | ascending | descending (by __time)
    batch_size: int = 20480
    query_type: str = "scan"

    @staticmethod
    def of(datasource, intervals, columns=(), limit=None, offset=0, order="none",
           filter=None, virtual_columns=(), context=None) -> "ScanQuery":
        return ScanQuery(
            columns=tuple(columns), limit=limit, offset=offset, order=order,
            **_mk(datasource, intervals, filter, "all", virtual_columns, context))

    def required_columns(self):
        return super().required_columns() | set(self.columns)

    def to_json(self):
        j = self.base_json()
        j.update(columns=list(self.columns), limit=self.limit,
                 offset=self.offset, order=self.order,
                 batchSize=self.batch_size)
        return j


@dataclass(frozen=True)
class SelectQuery(Query):
    """reference: query/select/SelectQuery.java — legacy paged scan."""
    dimensions: Tuple[str, ...] = ()
    metrics: Tuple[str, ...] = ()
    paging_spec: Tuple[Tuple[str, int], ...] = ()
    threshold: int = 100
    descending: bool = False
    query_type: str = "select"

    @staticmethod
    def of(datasource, intervals, dimensions=(), metrics=(), threshold=100,
           paging_spec=None, descending=False, filter=None, granularity="all",
           context=None) -> "SelectQuery":
        return SelectQuery(
            dimensions=tuple(dimensions), metrics=tuple(metrics),
            paging_spec=tuple(sorted((paging_spec or {}).items())),
            threshold=threshold, descending=descending,
            **_mk(datasource, intervals, filter, granularity, (), context))

    def to_json(self):
        j = self.base_json()
        j.update(dimensions=list(self.dimensions), metrics=list(self.metrics),
                 pagingSpec={"pagingIdentifiers": dict(self.paging_spec),
                             "threshold": self.threshold},
                 descending=self.descending)
        return j


@dataclass(frozen=True)
class SearchQuery(Query):
    """reference: query/search/SearchQuery.java — find dim values matching."""
    search_dimensions: Tuple[str, ...] = ()   # empty = all dims
    value: str = ""
    case_sensitive: bool = False
    limit: int = 1000
    sort: str = "lexicographic"  # lexicographic | alphanumeric | strlen
    query_type: str = "search"

    @staticmethod
    def of(datasource, intervals, value, search_dimensions=(), limit=1000,
           case_sensitive=False, filter=None, granularity="all", sort="lexicographic",
           context=None) -> "SearchQuery":
        return SearchQuery(
            search_dimensions=tuple(search_dimensions), value=value,
            case_sensitive=case_sensitive, limit=limit, sort=sort,
            **_mk(datasource, intervals, filter, granularity, (), context))

    def to_json(self):
        j = self.base_json()
        j.update(searchDimensions=list(self.search_dimensions),
                 query={"type": "contains", "value": self.value,
                        "caseSensitive": self.case_sensitive},
                 limit=self.limit, sort={"type": self.sort})
        return j


@dataclass(frozen=True)
class TimeBoundaryQuery(Query):
    """reference: query/timeboundary/TimeBoundaryQuery.java"""
    bound: Optional[str] = None  # None | minTime | maxTime
    query_type: str = "timeBoundary"

    @staticmethod
    def of(datasource, intervals=None, bound=None, filter=None,
           context=None) -> "TimeBoundaryQuery":
        return TimeBoundaryQuery(
            bound=bound,
            **_mk(datasource, intervals, filter, "all", (), context))

    def to_json(self):
        j = self.base_json()
        j.update(bound=self.bound)
        return j


@dataclass(frozen=True)
class SegmentMetadataQuery(Query):
    """reference: query/metadata/SegmentMetadataQuery.java"""
    to_include: Tuple[str, ...] = ()  # empty = all columns
    analysis_types: Tuple[str, ...] = ("cardinality", "size", "interval", "minmax")
    merge: bool = False
    query_type: str = "segmentMetadata"

    @staticmethod
    def of(datasource, intervals=None, to_include=(), merge=False,
           analysis_types=("cardinality", "size", "interval", "minmax"),
           context=None) -> "SegmentMetadataQuery":
        return SegmentMetadataQuery(
            to_include=tuple(to_include), merge=merge,
            analysis_types=tuple(analysis_types),
            **_mk(datasource, intervals, None, "all", (), context))

    def to_json(self):
        j = self.base_json()
        j.update(toInclude={"type": "list", "columns": list(self.to_include)}
                 if self.to_include else {"type": "all"},
                 analysisTypes=list(self.analysis_types), merge=self.merge)
        return j


@dataclass(frozen=True)
class DataSourceMetadataQuery(Query):
    """reference: query/datasourcemetadata/DataSourceMetadataQuery.java —
    max ingested event time."""
    query_type: str = "dataSourceMetadata"

    @staticmethod
    def of(datasource, context=None) -> "DataSourceMetadataQuery":
        return DataSourceMetadataQuery(
            **_mk(datasource, None, None, "all", (), context))


def query_from_json(j: dict) -> Query:
    """Wire-format deserialization (reference: Jackson polymorphic Query),
    including polymorphic dataSources (table | union | query)."""
    ds_j = j.get("dataSource", "")
    inner_q = None
    union: Tuple[str, ...] = ()
    if isinstance(ds_j, dict):
        dtype = ds_j.get("type", "table")
        if dtype == "table":
            ds = ds_j["name"]
        elif dtype == "union":
            union = tuple(ds_j["dataSources"])
            ds = union[0] if union else ""
        elif dtype == "query":
            inner_q = query_from_json(ds_j["query"])
            ds = inner_q.datasource
        else:
            raise ValueError(f"unknown dataSource type {dtype!r}")
    else:
        ds = ds_j
    q = _query_body_from_json(j, ds)
    if inner_q is not None or union:
        from dataclasses import replace as _replace
        q = _replace(q, inner_query=inner_q, union_datasources=union)
    return q


def _query_body_from_json(j: dict, ds: str) -> Query:
    t = j["queryType"]
    ivs = j.get("intervals")
    if isinstance(ivs, dict):  # {"type": "intervals", "intervals": [...]}
        ivs = ivs.get("intervals")
    common = dict(
        intervals=ivs,
        filter=filter_from_json(j.get("filter")),
        granularity=j.get("granularity", "all"),
        context=j.get("context"),
    )
    vcs = tuple(virtualcolumn_from_json(v) for v in j.get("virtualColumns", []))
    if t == "timeseries":
        ctx = j.get("context") or {}
        return TimeseriesQuery.of(
            ds, aggregations=[agg_from_json(a) for a in j.get("aggregations", [])],
            post_aggregations=[postagg_from_json(p)
                               for p in j.get("postAggregations", [])],
            descending=j.get("descending", False),
            skip_empty_buckets=bool(ctx.get("skipEmptyBuckets", False)),
            virtual_columns=vcs, **common)
    if t == "topN":
        m = j["metric"]
        if isinstance(m, str):
            metric, ordering = m, "numeric"
        else:
            mt = m.get("type", "numeric")
            if mt == "numeric":
                metric, ordering = m.get("metric", ""), "numeric"
            elif mt == "inverted":
                inner = m.get("metric", "")
                if isinstance(inner, dict):
                    metric = inner.get("metric", "")
                    ordering = ("inverted_lexicographic"
                                if inner.get("type") in ("dimension", "lexicographic")
                                else "inverted")
                else:
                    metric, ordering = inner, "inverted"
            elif mt in ("dimension", "lexicographic", "alphaNumeric"):
                metric, ordering = "", "lexicographic"
            else:
                raise ValueError(f"unknown topN metric spec type {mt!r}")
        return TopNQuery.of(
            ds, dimension=dimspec_from_json(j["dimension"]),
            metric=metric, metric_ordering=ordering,
            threshold=j["threshold"],
            aggregations=[agg_from_json(a) for a in j.get("aggregations", [])],
            post_aggregations=[postagg_from_json(p)
                               for p in j.get("postAggregations", [])],
            virtual_columns=vcs, **common)
    if t == "groupBy":
        ls = j.get("limitSpec")
        limit_spec = None
        if ls:
            limit_spec = DefaultLimitSpec(
                tuple(OrderByColumnSpec(c["dimension"], c.get("direction", "ascending"),
                                        c.get("dimensionOrder", "lexicographic"))
                      if isinstance(c, dict) else OrderByColumnSpec(c)
                      for c in ls.get("columns", [])),
                ls.get("limit"), ls.get("offset", 0))
        return GroupByQuery.of(
            ds, dimensions=[dimspec_from_json(d) for d in j.get("dimensions", [])],
            aggregations=[agg_from_json(a) for a in j.get("aggregations", [])],
            post_aggregations=[postagg_from_json(p)
                               for p in j.get("postAggregations", [])],
            having=having_from_json(j.get("having")),
            limit_spec=limit_spec,
            subtotals=j.get("subtotalsSpec") or (), virtual_columns=vcs, **common)
    if t == "scan":
        common.pop("granularity")
        q = ScanQuery.of(ds, columns=j.get("columns", ()),
                         limit=j.get("limit"), offset=j.get("offset", 0),
                         order=j.get("order", "none"), virtual_columns=vcs,
                         **common)
        if j.get("batchSize"):
            from dataclasses import replace
            q = replace(q, batch_size=int(j["batchSize"]))
        return q
    if t == "select":
        ps = j.get("pagingSpec", {})
        return SelectQuery.of(ds, dimensions=j.get("dimensions", ()),
                              metrics=j.get("metrics", ()),
                              threshold=ps.get("threshold", 100),
                              paging_spec=ps.get("pagingIdentifiers"),
                              descending=j.get("descending", False), **common)
    if t == "search":
        q = j.get("query", {})
        return SearchQuery.of(ds, value=q.get("value", ""),
                              search_dimensions=j.get("searchDimensions", ()),
                              limit=j.get("limit", 1000),
                              case_sensitive=q.get("caseSensitive", False),
                              sort=(j.get("sort") or {}).get("type", "lexicographic"),
                              **common)
    if t == "timeBoundary":
        common.pop("granularity")
        return TimeBoundaryQuery.of(ds, bound=j.get("bound"), **common)
    if t == "segmentMetadata":
        inc = j.get("toInclude") or {}
        return SegmentMetadataQuery.of(
            ds, intervals=common["intervals"],
            to_include=inc.get("columns", ()) if inc.get("type") == "list" else (),
            merge=j.get("merge", False),
            analysis_types=tuple(j.get("analysisTypes",
                                       ("cardinality", "size", "interval", "minmax"))),
            context=j.get("context"))
    if t == "dataSourceMetadata":
        return DataSourceMetadataQuery.of(ds, context=j.get("context"))
    raise ValueError(f"unknown query type {t!r}")
