"""Post-aggregators: arithmetic over finalized aggregate values.

Capability parity with the reference's PostAggregator hierarchy
(processing/src/main/java/org/apache/druid/query/aggregation/post/ —
arithmetic, fieldAccess, constant, greatest/least, hyperUniqueCardinality,
finalizingFieldAccess). Evaluated host-side over result rows (result sets are
small; device work is done by then).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


class PostAggregator:
    name: str

    def compute(self, row: Dict[str, object]) -> object:
        raise NotImplementedError

    def to_json(self) -> dict:
        raise NotImplementedError


@dataclass(frozen=True)
class FieldAccessPostAgg(PostAggregator):
    name: str
    field: str

    def compute(self, row):
        return row.get(self.field)

    def to_json(self):
        return {"type": "fieldAccess", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class FinalizingFieldAccessPostAgg(PostAggregator):
    name: str
    field: str

    def compute(self, row):
        return row.get(self.field)

    def to_json(self):
        return {"type": "finalizingFieldAccess", "name": self.name,
                "fieldName": self.field}


@dataclass(frozen=True)
class ConstantPostAgg(PostAggregator):
    name: str
    value: float

    def compute(self, row):
        return self.value

    def to_json(self):
        return {"type": "constant", "name": self.name, "value": self.value}


def _safe_div(a, b, zero):
    """Array-safe division (reference: division by zero -> 0)."""
    import numpy as np
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        b_arr = np.asarray(b, dtype=np.float64)
        return np.where(b_arr != 0, np.asarray(a, dtype=np.float64)
                        / np.where(b_arr != 0, b_arr, 1.0), zero)
    return (a / b) if b else zero


_OPS = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: _safe_div(a, b, 0.0),
    "quotient": lambda a, b: _safe_div(a, b, math.nan),
}


@dataclass(frozen=True)
class ArithmeticPostAgg(PostAggregator):
    name: str
    fn: str
    fields: Tuple[PostAggregator, ...]

    def compute(self, row):
        # works both per-row (scalars) and vectorized (numpy arrays)
        op = _OPS[self.fn]
        vals = [f.compute(row) for f in self.fields]
        vals = [0.0 if v is None else v for v in vals]
        import numpy as np
        vals = [v if isinstance(v, np.ndarray) else float(v) for v in vals]
        out = vals[0]
        for v in vals[1:]:
            out = op(out, v)
        return out

    def to_json(self):
        return {"type": "arithmetic", "name": self.name, "fn": self.fn,
                "fields": [f.to_json() for f in self.fields]}


@dataclass(frozen=True)
class GreatestPostAgg(PostAggregator):
    name: str
    fields: Tuple[PostAggregator, ...]
    kind: str = "double"

    def compute(self, row):
        return max(float(f.compute(row) or 0.0) for f in self.fields)

    def to_json(self):
        return {"type": f"{self.kind}Greatest", "name": self.name,
                "fields": [f.to_json() for f in self.fields]}


@dataclass(frozen=True)
class LeastPostAgg(PostAggregator):
    name: str
    fields: Tuple[PostAggregator, ...]
    kind: str = "double"

    def compute(self, row):
        return min(float(f.compute(row) or 0.0) for f in self.fields)

    def to_json(self):
        return {"type": f"{self.kind}Least", "name": self.name,
                "fields": [f.to_json() for f in self.fields]}


@dataclass(frozen=True)
class HyperUniqueFinalizingPostAgg(PostAggregator):
    """Reference: hyperloglog/HyperUniqueFinalizingPostAggregator.java —
    in this framework HLL states are finalized by their AggregatorSpec before
    post-agg evaluation, so this is a pass-through field access."""
    name: str
    field: str

    def compute(self, row):
        return row.get(self.field)

    def to_json(self):
        return {"type": "hyperUniqueCardinality", "name": self.name,
                "fieldName": self.field}


# extension-registered post-aggregator types (druid_tpu/ext/)
_EXTENSION_POSTAGGS: dict = {}


def register_postagg(type_name: str, from_json) -> None:
    _EXTENSION_POSTAGGS[type_name] = from_json


def postagg_from_json(j: dict) -> PostAggregator:
    t = j["type"]
    if t in _EXTENSION_POSTAGGS:
        return _EXTENSION_POSTAGGS[t](j)
    # "name" is optional on nested fields of arithmetic/greatest/least
    # (reference: ArithmeticPostAggregator's field list carries unnamed
    # fieldAccess entries in wire JSON)
    if t == "fieldAccess":
        return FieldAccessPostAgg(j.get("name", j["fieldName"]), j["fieldName"])
    if t == "finalizingFieldAccess":
        return FinalizingFieldAccessPostAgg(j.get("name", j["fieldName"]),
                                            j["fieldName"])
    if t == "constant":
        return ConstantPostAgg(j.get("name", "const"), j["value"])
    if t == "arithmetic":
        return ArithmeticPostAgg(j["name"], j["fn"],
                                 tuple(postagg_from_json(f) for f in j["fields"]))
    if t == "hyperUniqueCardinality":
        return HyperUniqueFinalizingPostAgg(j["name"], j["fieldName"])
    for kind in ("double", "long"):
        if t == f"{kind}Greatest":
            return GreatestPostAgg(j["name"],
                                   tuple(postagg_from_json(f) for f in j["fields"]), kind)
        if t == f"{kind}Least":
            return LeastPostAgg(j["name"],
                                tuple(postagg_from_json(f) for f in j["fields"]), kind)
    raise ValueError(f"unknown post-aggregator type {t!r}")


def compute_postaggs(postaggs, row: Dict[str, object]) -> Dict[str, object]:
    out = dict(row)
    for pa in postaggs:
        out[pa.name] = pa.compute(out)
    return out
