"""Aggregator specs (the query-model side of aggregation).

Capability parity with the reference's AggregatorFactory SPI
(processing/src/main/java/org/apache/druid/query/aggregation/AggregatorFactory.java:44-161
— factorize / combine / getCombiningFactory / finalizeComputation).

TPU-first split: an AggregatorSpec here is pure metadata; the device
implementation is a *vectorized segmented reduction* chosen in
druid_tpu/engine/kernels.py — (update over a masked block → per-bucket
partial state) + (host/device combine) + (finalize). There is no per-row
Aggregator object: the whole block aggregates in one XLA op, which is the
replacement for BufferAggregator's per-row ByteBuffer updates
(query/aggregation/BufferAggregator.java:54-144).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np


class AggregatorSpec:
    name: str

    @property
    def field_name(self) -> Optional[str]:
        return getattr(self, "field", None)

    def required_columns(self) -> set:
        f = self.field_name
        return {f} if f else set()

    # combining factory: the agg used to merge partial results
    # (reference AggregatorFactory.getCombiningFactory)
    def combining(self) -> "AggregatorSpec":
        cls = type(self)
        try:
            return cls(self.name, self.name)  # type: ignore[call-arg]
        except TypeError:
            return self

    def finalize(self, value):
        return value

    def to_json(self) -> dict:
        raise NotImplementedError


@dataclass(frozen=True)
class CountAggregator(AggregatorSpec):
    name: str = "count"

    def combining(self):
        return LongSumAggregator(self.name, self.name)

    def to_json(self):
        return {"type": "count", "name": self.name}


@dataclass(frozen=True)
class LongSumAggregator(AggregatorSpec):
    name: str
    field: str

    def to_json(self):
        return {"type": "longSum", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class DoubleSumAggregator(AggregatorSpec):
    name: str
    field: str

    def to_json(self):
        return {"type": "doubleSum", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class FloatSumAggregator(AggregatorSpec):
    name: str
    field: str

    def to_json(self):
        return {"type": "floatSum", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class LongMinAggregator(AggregatorSpec):
    name: str
    field: str

    def to_json(self):
        return {"type": "longMin", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class LongMaxAggregator(AggregatorSpec):
    name: str
    field: str

    def to_json(self):
        return {"type": "longMax", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class DoubleMinAggregator(AggregatorSpec):
    name: str
    field: str

    def to_json(self):
        return {"type": "doubleMin", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class DoubleMaxAggregator(AggregatorSpec):
    name: str
    field: str

    def to_json(self):
        return {"type": "doubleMax", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class FloatMinAggregator(AggregatorSpec):
    name: str
    field: str

    def to_json(self):
        return {"type": "floatMin", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class FloatMaxAggregator(AggregatorSpec):
    name: str
    field: str

    def to_json(self):
        return {"type": "floatMax", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class FirstAggregator(AggregatorSpec):
    """Value at min __time (reference: query/aggregation/first/)."""
    name: str
    field: str
    kind: str = "double"  # long|double|float

    def combining(self):
        return FirstAggregator(self.name, self.name, self.kind)

    def required_columns(self):
        # the rollup pair-time column, when present, restores true event-time
        # ordering over rolled-up segments (reference stores
        # SerializablePair(long time, value) for exactly this)
        return {self.field, f"__ft_{self.field}"}

    def to_json(self):
        return {"type": f"{self.kind}First", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class LastAggregator(AggregatorSpec):
    """Value at max __time (reference: query/aggregation/last/)."""
    name: str
    field: str
    kind: str = "double"

    def combining(self):
        return LastAggregator(self.name, self.name, self.kind)

    def required_columns(self):
        return {self.field, f"__ft_{self.field}"}

    def to_json(self):
        return {"type": f"{self.kind}Last", "name": self.name, "fieldName": self.field}


@dataclass(frozen=True)
class FilteredAggregator(AggregatorSpec):
    """Delegate aggregator gated by a filter
    (reference: query/aggregation/FilteredAggregatorFactory.java)."""
    name: str
    delegate: AggregatorSpec = None
    filter: object = None  # DimFilter

    def required_columns(self):
        return self.delegate.required_columns() | self.filter.required_columns()

    def combining(self):
        return self.delegate.combining()

    def finalize(self, value):
        return self.delegate.finalize(value)

    def to_json(self):
        return {"type": "filtered", "name": self.name,
                "aggregator": self.delegate.to_json(),
                "filter": self.filter.to_json()}


@dataclass(frozen=True)
class HyperUniqueAggregator(AggregatorSpec):
    """HLL cardinality over a precomputed HLL metric column or a dimension
    (reference: query/aggregation/hyperloglog/HyperUniquesAggregatorFactory.java:51).
    State = int8 register array (2^log2m buckets); merge = elementwise max;
    see druid_tpu/engine/hll.py for the device kernel."""
    name: str
    field: str
    log2m: int = 11
    round: bool = False

    def finalize(self, value):
        from druid_tpu.engine.hll import estimate
        est = estimate(value, self.log2m)
        return int(round(est)) if self.round else est

    def to_json(self):
        return {"type": "hyperUnique", "name": self.name, "fieldName": self.field,
                "log2m": self.log2m, "round": self.round}


@dataclass(frozen=True)
class CardinalityAggregator(AggregatorSpec):
    """HLL over dimension values at query time
    (reference: query/aggregation/cardinality/CardinalityAggregator.java)."""
    name: str
    fields: Tuple[str, ...] = ()
    by_row: bool = False
    log2m: int = 11
    round: bool = False

    def required_columns(self):
        return set(self.fields)

    def combining(self):
        return HyperUniqueAggregator(self.name, self.name, self.log2m, self.round)

    def finalize(self, value):
        from druid_tpu.engine.hll import estimate
        est = estimate(value, self.log2m)
        return int(round(est)) if self.round else est

    def to_json(self):
        return {"type": "cardinality", "name": self.name,
                "fields": list(self.fields), "byRow": self.by_row,
                "log2m": self.log2m, "round": self.round}


_SIMPLE = {
    "count": lambda j: CountAggregator(j["name"]),
    "longSum": lambda j: LongSumAggregator(j["name"], j["fieldName"]),
    "doubleSum": lambda j: DoubleSumAggregator(j["name"], j["fieldName"]),
    "floatSum": lambda j: FloatSumAggregator(j["name"], j["fieldName"]),
    "longMin": lambda j: LongMinAggregator(j["name"], j["fieldName"]),
    "longMax": lambda j: LongMaxAggregator(j["name"], j["fieldName"]),
    "doubleMin": lambda j: DoubleMinAggregator(j["name"], j["fieldName"]),
    "doubleMax": lambda j: DoubleMaxAggregator(j["name"], j["fieldName"]),
    "floatMin": lambda j: FloatMinAggregator(j["name"], j["fieldName"]),
    "floatMax": lambda j: FloatMaxAggregator(j["name"], j["fieldName"]),
    "hyperUnique": lambda j: HyperUniqueAggregator(
        j["name"], j["fieldName"], log2m=j.get("log2m", 11),
        round=j.get("round", False)),
    "cardinality": lambda j: CardinalityAggregator(
        j["name"], tuple(j["fields"]), j.get("byRow", False),
        log2m=j.get("log2m", 11), round=j.get("round", False)),
}


# extension-registered aggregator types (the DruidModule Jackson-module
# registration analog — see druid_tpu/ext/)
_EXTENSION_AGGS: dict = {}


def register_aggregator(type_name: str, from_json) -> None:
    _EXTENSION_AGGS[type_name] = from_json


def agg_from_json(j: dict) -> AggregatorSpec:
    t = j["type"]
    if t in _EXTENSION_AGGS:
        return _EXTENSION_AGGS[t](j)
    if t in _SIMPLE:
        return _SIMPLE[t](j)
    for kind in ("long", "double", "float"):
        if t == f"{kind}First":
            return FirstAggregator(j["name"], j["fieldName"], kind)
        if t == f"{kind}Last":
            return LastAggregator(j["name"], j["fieldName"], kind)
    if t == "filtered":
        from druid_tpu.query.filters import filter_from_json
        return FilteredAggregator(j.get("name") or j["aggregator"]["name"],
                                  agg_from_json(j["aggregator"]),
                                  filter_from_json(j["filter"]))
    raise ValueError(f"unknown aggregator type {t!r}")
