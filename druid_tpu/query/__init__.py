from druid_tpu.query.filters import (
    DimFilter, SelectorFilter, InFilter, BoundFilter, LikeFilter, RegexFilter,
    AndFilter, OrFilter, NotFilter, IntervalFilter, SearchFilter,
    ColumnComparisonFilter, TrueFilter, FalseFilter, JavaScriptFilter,
    ExpressionFilter, SpatialFilter, SpatialBound, RectangularBound,
    RadiusBound, PolygonBound, filter_from_json,
)
from druid_tpu.query.aggregators import (
    AggregatorSpec, CountAggregator, LongSumAggregator, DoubleSumAggregator,
    FloatSumAggregator, LongMinAggregator, LongMaxAggregator,
    DoubleMinAggregator, DoubleMaxAggregator, FloatMinAggregator,
    FloatMaxAggregator, FirstAggregator, LastAggregator, FilteredAggregator,
    HyperUniqueAggregator, CardinalityAggregator, agg_from_json,
)
from druid_tpu.query.postaggs import (
    PostAggregator, ArithmeticPostAgg, FieldAccessPostAgg, ConstantPostAgg,
    FinalizingFieldAccessPostAgg, GreatestPostAgg, LeastPostAgg,
    HyperUniqueFinalizingPostAgg,
)
from druid_tpu.query.model import (
    Query, TimeseriesQuery, TopNQuery, GroupByQuery, ScanQuery,
    TimeBoundaryQuery, SegmentMetadataQuery, SearchQuery, SelectQuery,
    DataSourceMetadataQuery, DefaultDimensionSpec, ExtractionDimensionSpec,
    DefaultLimitSpec, OrderByColumnSpec, HavingSpec, query_from_json,
)
