"""Process-wide named lookup registry.

Reference analog: query/lookup/LookupExtractorFactoryContainerProvider +
LookupReferencesManager (server-side registry of named key→value maps,
versioned, distributed by the coordinator — server/lookup/cache/
LookupCoordinatorManager.java). Here: an in-process versioned registry; the
cluster layer distributes lookup definitions to nodes the same way the
coordinator pushes them over HTTP.

Lookups are applied host-side over dictionaries (O(cardinality)), never on
device — see ExtractionFn in druid_tpu/query/model.py.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class LookupContainer:
    """A named lookup version (reference: LookupExtractorFactoryContainer).
    `owner` records which manager wrote it (None = process-local
    register_lookup; "lookup-sync:<tier>" = cluster sync) — deletion and
    replacement authority follow ownership, never version-string shape."""
    name: str
    mapping: Dict[str, str]
    version: str = "v0"
    owner: object = None


class LookupReferencesManager:
    """Thread-safe registry of named lookups with versioned replace."""

    def __init__(self):
        self._lock = threading.Lock()
        self._lookups: Dict[str, LookupContainer] = {}

    @staticmethod
    def _version_key(v: str):
        # length-then-lexicographic: numeric suffixes compare naturally
        # ("v9" < "v10"), equal-length versions compare lexicographically
        return (len(v), v)

    def add(self, name: str, mapping: Dict[str, str],
            version: str = "v0", owner: object = None) -> bool:
        """Register/replace; a replace with a version <= current is a no-op
        (mirrors LookupReferencesManager version-gated updates). A write
        from a DIFFERENT owner than the current entry's never applies —
        first writer wins on a name collision; the other party must
        remove() first (which only the owning sync does)."""
        with self._lock:
            cur = self._lookups.get(name)
            if cur is not None and cur.owner != owner:
                return False
            if cur is not None and \
                    self._version_key(version) <= self._version_key(cur.version):
                return False
            # version-gated replace registry: later versions overwrite
            # by design — the name is the identity, not a build key
            self._lookups[name] = LookupContainer(name, dict(mapping),  # druidlint: disable=unkeyed-trace-input
                                                  version, owner)
            return True

    def force_replace(self, name: str, mapping: Dict[str, str],
                      version: str = "v0", owner: object = None) -> bool:
        """Atomic ownership-checked replace with NO version gate — the
        owning sync swapping its own entry across version-scheme changes
        (namespace stamp → plain spec version). One lock acquisition, so
        concurrent get_lookup() never observes the name missing."""
        with self._lock:
            cur = self._lookups.get(name)
            if cur is not None and cur.owner != owner:
                return False
            # ownership-checked replace registry (see add() above)
            self._lookups[name] = LookupContainer(name, dict(mapping),  # druidlint: disable=unkeyed-trace-input
                                                  version, owner)
            return True

    def remove(self, name: str) -> bool:
        with self._lock:
            return self._lookups.pop(name, None) is not None

    def get(self, name: str) -> Optional[LookupContainer]:
        with self._lock:
            return self._lookups.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._lookups)

    def snapshot(self) -> List[dict]:
        """Introspection/persistence snapshot (LookupSnapshotTaker analog)."""
        with self._lock:
            return [{"name": c.name, "version": c.version, "map": dict(c.mapping)}
                    for c in self._lookups.values()]


_MANAGER = LookupReferencesManager()


def lookup_manager() -> LookupReferencesManager:
    return _MANAGER


def register_lookup(name: str, mapping: Dict[str, str],
                    version: str = "v0") -> bool:
    return _MANAGER.add(name, mapping, version)


def get_lookup(name: str) -> Dict[str, str]:
    c = _MANAGER.get(name)
    if c is None:
        raise KeyError(f"lookup [{name}] not registered")
    return c.mapping
