"""Peon process entry point: `python -m druid_tpu.peon <task-spec.json>`.

Reference analog: CliPeon (services/src/main/java/org/apache/druid/cli/
CliPeon.java) — the forked child that runs exactly one task, doing its
lock/publish metadata actions against the overlord's action endpoint and
writing segment bytes straight to shared deep storage.
"""
import sys

from druid_tpu.indexing.forking import peon_main

if __name__ == "__main__":
    if len(sys.argv) != 2:
        print("usage: python -m druid_tpu.peon <task-spec.json>",
              file=sys.stderr)
        sys.exit(2)
    sys.exit(peon_main(sys.argv[1]))
